#!/usr/bin/env bash
# Launcher for the TPU-native rebuild's layers — the role of the
# reference's deploy/bin/oryx-run.sh:194-286 (spark-submit / YARN
# distributed shell), re-targeted at TPU-VM / container hosts: layers are
# plain processes (python -m oryx_tpu <layer>) and cluster placement is
# handled by the GKE manifests in deploy/gke/ or by running this script
# on each host.
#
#   oryx-run.sh command [--option value] ...
#     command: batch | speed | serving | bus-serve | bus-setup |
#              bus-tail | bus-input | all
#     --conf        Oryx config file (default: ./oryx.conf)
#     --app-dir     extra dir on sys.path for config-named app classes
#                   (the --app-jar analogue)
#     --set         KEY=VALUE config override; repeatable
#     --input-file  for bus-input
#     --bind        for bus-serve (default 0.0.0.0:6378)
#     --data-dir    for bus-serve (topic log directory on this host)
#     --foreground  run in the foreground (default: nohup to logs/)
#
# `all` stands up a single-host pipeline: bus-serve + batch + speed +
# serving, each as its own process with logs under ./logs/ — the
# quick-start topology for one TPU VM (docs/admin.md).

set -euo pipefail

COMMAND="${1:-}"
[ -n "${COMMAND}" ] || { grep '^#   ' "$0" | sed 's/^#   //'; exit 1; }
shift

CONF="oryx.conf"
FOREGROUND=0
PASS_ARGS=()
while (($#)); do
  case "$1" in
    --conf)       CONF="$2"; PASS_ARGS+=(--conf "$2"); shift 2 ;;
    --foreground) FOREGROUND=1; shift ;;
    --app-dir|--set|--input-file|--bind|--data-dir)
                  PASS_ARGS+=("$1" "$2"); shift 2 ;;
    *) echo "unknown option $1"; exit 1 ;;
  esac
done

PY="${ORYX_PYTHON:-python3}"
LOG_DIR="${ORYX_LOG_DIR:-logs}"
mkdir -p "${LOG_DIR}"

launch() {  # launch <name> <subcommand...>
  local name="$1"; shift
  if [ "${FOREGROUND}" = "1" ]; then
    exec "${PY}" -m oryx_tpu "$@"
  fi
  nohup "${PY}" -m oryx_tpu "$@" >"${LOG_DIR}/${name}.log" 2>&1 &
  echo $! > "${LOG_DIR}/${name}.pid"
  echo "${name}: pid $(cat "${LOG_DIR}/${name}.pid") log ${LOG_DIR}/${name}.log"
}

case "${COMMAND}" in
  batch|speed|serving|bus-serve)
    launch "${COMMAND}" "${COMMAND}" "${PASS_ARGS[@]}"
    ;;
  bus-setup|bus-tail|bus-input)
    exec "${PY}" -m oryx_tpu "${COMMAND}" "${PASS_ARGS[@]}"
    ;;
  all)
    # single-host pipeline; bus topics must exist before layers attach
    "${PY}" -m oryx_tpu bus-setup "${PASS_ARGS[@]}"
    launch serving serving "${PASS_ARGS[@]}"
    launch speed   speed   "${PASS_ARGS[@]}"
    launch batch   batch   "${PASS_ARGS[@]}"
    echo "pipeline up; stop with: kill \$(cat ${LOG_DIR}/*.pid)"
    ;;
  *)
    echo "unknown command ${COMMAND}"; exit 1 ;;
esac
