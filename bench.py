"""Headline benchmark: ALS /recommend throughput on TPU.

Reproduces the reference's LoadBenchmark shape (app/oryx-app-serving/src/
test/.../als/LoadBenchmark.java + LoadTestALSModelFactory.java:34-101):
a synthetic model of `items` x `features` with random unit-ish factors,
then timed top-10 recommend queries for random users. The reference's
best published number at 50 features x 1M items is 437 qps (LSH
sample-rate 0.3, 32-core Xeon; docs/performance.md:108-117) — that is
the vs_baseline denominator. Here each query is ONE batched matvec +
top_k on the TPU over the full item matrix (exact, not approximate LSH).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs (LoadTestALSModelFactory-style): ORYX_BENCH_ITEMS,
ORYX_BENCH_FEATURES, ORYX_BENCH_USERS, ORYX_BENCH_SECONDS,
ORYX_BENCH_BATCH (request batch size; 1 = reference-like serial requests).
"""

import json
import os
import time

import numpy as np


def main() -> None:
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_USERS", 1024))
    seconds = float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    batch = int(os.environ.get("ORYX_BENCH_BATCH", 16))
    how_many = 10
    baseline_qps = 437.0  # reference: LSH 0.3, 50 feat x 1M items

    from oryx_tpu.ops import topn as topn_ops

    gen = np.random.default_rng(1234)
    y = gen.standard_normal((items, features), dtype=np.float32)
    x = gen.standard_normal((users, features), dtype=np.float32)

    uploaded = topn_ops.upload(y)
    # warm up / compile
    topn_ops.top_k_scores_batch(uploaded, x[:batch], how_many)
    topn_ops.top_k_scores(uploaded, x[0], how_many)

    served = 0
    start = time.perf_counter()
    while time.perf_counter() - start < seconds:
        qi = (served // batch) % max(1, users // batch)
        queries = x[qi * batch : qi * batch + batch]
        if batch == 1:
            topn_ops.top_k_scores(uploaded, queries[0], how_many)
        else:
            topn_ops.top_k_scores_batch(uploaded, queries, how_many)
        served += batch
    elapsed = time.perf_counter() - start
    qps = served / elapsed

    print(
        json.dumps(
            {
                "metric": f"ALS recommend top-{how_many} qps ({features} feat x {items} items, batch {batch})",
                "value": round(qps, 1),
                "unit": "recs/sec",
                "vs_baseline": round(qps / baseline_qps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
