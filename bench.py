"""Headline benchmark: ALS /recommend throughput on TPU.

Reproduces the reference's LoadBenchmark shape (app/oryx-app-serving/src/
test/.../als/LoadBenchmark.java + LoadTestALSModelFactory.java:34-101):
a synthetic model of `items` x `features` with random factors, then timed
top-10 recommend queries for random users. The reference's best published
number at 50 features x 1M items is 437 qps (LSH sample-rate 0.3, 32-core
Xeon; docs/performance.md:108-117) — that is the vs_baseline denominator.

Each request batch is ONE fused Pallas scan + top_k on the TPU over the
full item matrix (exact scoring — no LSH approximation), with the item
matrix held in bfloat16 to halve HBM traffic. Requests are pipelined:
a window of batches stays in flight so device→host result transfers
overlap the next batches' compute, exactly how the serving layer's
request pipeline runs concurrent clients.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.

Resilience: the benchmark body runs in a child process. The parent
preflights backend initialization and retries on transient UNAVAILABLE
errors (TPU backend setup through the tunnel can fail or hang once) with
a fresh process each time — JAX caches a failed backend for the life of
the process, so in-process retry is useless. If the TPU never comes up
within the attempt budget the bench falls back to CPU so the round still
records a number, with the backend named in the metric string.

Env knobs (LoadTestALSModelFactory-style): ORYX_BENCH_ITEMS,
ORYX_BENCH_FEATURES, ORYX_BENCH_USERS, ORYX_BENCH_SECONDS,
ORYX_BENCH_BATCH (request batch size), ORYX_BENCH_DEPTH (in-flight
batches), ORYX_BENCH_DTYPE (bfloat16|float32), ORYX_BENCH_ATTEMPTS,
ORYX_BENCH_INIT_TIMEOUT (per-attempt backend init timeout, seconds).
"""

import json
import os
import subprocess
import sys
import time
from collections import deque


# --------------------------------------------------------------------------
# Child: the actual benchmark body. Assumes the backend is importable; any
# backend failure here is caught by the parent and retried.
# --------------------------------------------------------------------------


def run_bench() -> None:
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_USERS", 4096))
    seconds = float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    batch = int(os.environ.get("ORYX_BENCH_BATCH", 128))
    depth = int(os.environ.get("ORYX_BENCH_DEPTH", 48))
    dtype_name = os.environ.get("ORYX_BENCH_DTYPE", "bfloat16")
    how_many = 10
    baseline_qps = 437.0  # reference: LSH 0.3, 50 feat x 1M items

    import numpy as np
    import jax

    # A site-installed accelerator plugin may import jax at interpreter
    # startup and pin jax_platforms, silently overriding $JAX_PLATFORMS —
    # so a CPU-fallback child would still try (and hang on) the TPU
    # backend. Re-assert the env var on the live config.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp

    backend = jax.default_backend()
    ndev = len(jax.devices())
    print(f"bench: backend={backend} devices={ndev}", file=sys.stderr)

    if backend != "tpu":
        # CPU fallback: keep the model shape honest but shrink the timed
        # window so the run completes promptly.
        seconds = min(seconds, 5.0)
        depth = min(depth, 8)

    from oryx_tpu.ops import topn as topn_ops

    gen = np.random.default_rng(1234)
    y = gen.standard_normal((items, features), dtype=np.float32)
    x = gen.standard_normal((users, features), dtype=np.float32)

    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    uploaded = topn_ops.upload(y, dtype=dtype)
    # warm up / compile
    t0 = time.perf_counter()
    topn_ops.submit_top_k(uploaded, x[:batch], how_many).result()
    print(f"bench: warmup/compile {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    served = 0
    inflight: deque = deque()
    num_batches = max(1, users // batch)
    start = time.perf_counter()
    deadline = start + seconds
    i = 0
    while True:
        now = time.perf_counter()
        if now < deadline and len(inflight) < depth:
            qi = i % num_batches
            queries = x[qi * batch : qi * batch + batch]
            inflight.append((topn_ops.submit_top_k(uploaded, queries, how_many), len(queries)))
            i += 1
        elif inflight:
            handle, rows = inflight.popleft()
            handle.result()
            served += rows
        else:
            break
    elapsed = time.perf_counter() - start
    qps = served / elapsed

    # HBM-bandwidth utilization diagnostic (the scan is bandwidth-bound):
    # each submitted batch reads the full item matrix once; `i` counts
    # submitted (and by now drained) batches, partial or full.
    bytes_per_scan = items * features * (2 if dtype_name == "bfloat16" else 4)
    gbps = i * bytes_per_scan / elapsed / 1e9
    print(f"bench: achieved ~{gbps:.1f} GB/s effective item-matrix read bandwidth", file=sys.stderr)

    tag = "" if backend == "tpu" else f", {backend} FALLBACK"
    print(
        json.dumps(
            {
                "metric": (
                    f"ALS recommend top-{how_many} qps, exact scan "
                    f"({features} feat x {items} items, {dtype_name}, "
                    f"batch {batch} x depth {depth}{tag})"
                ),
                "value": round(qps, 1),
                "unit": "recs/sec",
                "vs_baseline": round(qps / baseline_qps, 2),
            }
        )
    )


# --------------------------------------------------------------------------
# Parent: preflight + retry harness.
# --------------------------------------------------------------------------


def _diagnose_stray_processes() -> None:
    """Best-effort: list other python processes that might hold the chip."""
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid,etime,command"], capture_output=True, text=True, timeout=10
        ).stdout
        me = os.getpid()
        for line in out.splitlines():
            if ("python" in line or "libtpu" in line) and str(me) not in line.split()[:1]:
                if any(k in line for k in ("jax", "tpu", "bench", "oryx")):
                    print(f"bench[diag]: possible chip holder: {line.strip()}", file=sys.stderr)
    except Exception as e:  # pragma: no cover - diagnostics only
        print(f"bench[diag]: ps failed: {e}", file=sys.stderr)


def _run_child(env: dict, timeout: float) -> tuple[int, str, str]:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        return proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries bytes even when run() was given text=True.
        def _text(v) -> str:
            if isinstance(v, bytes):
                return v.decode("utf-8", "replace")
            return v or ""

        return -9, _text(e.stdout), _text(e.stderr) + "\n[parent] child timed out"


def main() -> None:
    attempts = int(os.environ.get("ORYX_BENCH_ATTEMPTS", 4))
    init_timeout = float(os.environ.get("ORYX_BENCH_INIT_TIMEOUT", 150))
    bench_seconds = float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    # init_timeout bounds backend bring-up + compile; the child also needs
    # the timed window and data generation on top of that.
    child_timeout = init_timeout + bench_seconds + 120

    base_env = dict(os.environ)
    base_env["ORYX_BENCH_CHILD"] = "1"
    cpu_fallback = attempts > 1 or os.environ.get("JAX_PLATFORMS") == "cpu"

    backoffs = [15, 30, 60, 90]
    attempt = 0
    while attempt < attempts:
        last = attempt == attempts - 1
        env = dict(base_env)
        label = "tpu"
        if last and cpu_fallback:
            # Last resort: record a CPU number rather than nothing.
            env["JAX_PLATFORMS"] = "cpu"
            label = "cpu-fallback"
        print(f"bench[parent]: attempt {attempt + 1}/{attempts} ({label})", file=sys.stderr)
        rc, out, err = _run_child(env, timeout=child_timeout)
        sys.stderr.write(err[-4000:])
        json_line = None
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                json_line = line
        if rc == 0 and json_line:
            print(json_line)
            return
        transient = any(
            k in err or k in out
            for k in ("UNAVAILABLE", "Unable to initialize backend", "DEADLINE_EXCEEDED", "timed out")
        )
        print(
            f"bench[parent]: attempt {attempt + 1} failed rc={rc} "
            f"({'transient backend error' if transient else 'non-transient'})",
            file=sys.stderr,
        )
        _diagnose_stray_processes()
        if not transient and not last:
            # Deterministic failure: retrying the same thing is pointless —
            # jump straight to the final (cpu-fallback) attempt.
            print("bench[parent]: skipping to final attempt", file=sys.stderr)
            attempt = attempts - 1
            continue
        next_is_cpu = cpu_fallback and attempt + 1 == attempts - 1
        if not last and not next_is_cpu:
            # no point waiting for the TPU to recover when the next attempt
            # is the forced-CPU fallback
            wait = backoffs[min(attempt, len(backoffs) - 1)]
            print(f"bench[parent]: retrying in {wait}s", file=sys.stderr)
            time.sleep(wait)
        attempt += 1

    print("bench[parent]: all attempts failed — no benchmark number this round", file=sys.stderr)
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("ORYX_BENCH_CHILD"):
        run_bench()
    else:
        main()
