"""Driver benchmark: the full BASELINE metric set on TPU.

Emits ONE JSON line PER METRIC ({"metric","value","unit","vs_baseline"}),
fastest first, streamed as each completes:

1. serving  — ALS /recommend exact-scan throughput, queries/sec (top-10).
   vs_baseline: the reference's best published 437 qps (LSH 0.3, 50 feat
   x 1M items, 32-core Xeon; docs/performance.md:108-117). Ours is an
   exact scan, theirs sampled 30% of items.
2. kmeans   — train wall (200k x 20, k=10, 20 Lloyd iters).
3. als      — ML-100K-shape train wall + held-out RMSE, rank 25.
4. als-scale— implicit 2M-rating power-law train, ratings/s, rank 32.
5. speed    — sustained events/s through the REAL SpeedLayer over the
   file bus (tools/speed_layer_benchmark.py, prefilled backlog).
   vs_baseline: the BASELINE.json 100K events/s target.
6. rdf      — covtype-shape train wall (100k x 54, 20 trees depth 10).

The reference publishes no batch-training numbers ("just that of the
underlying MLlib implementations", performance.md:19-27), so training
metrics use this build's r02 CPU-container floors (docs/performance.md
"Recorded batch-training numbers") as vs_baseline denominators — the
ratio is TPU-vs-CPU-floor for the identical config and is labeled as
such in the metric string.

Resilience: the benchmark body runs in a child process; the parent
retries transient TPU-backend failures with a fresh process (JAX caches
a failed backend for the life of the process) and falls back to CPU on
the last attempt so the round still records numbers. Child stdout is
streamed line-by-line so metrics that already completed survive a
mid-run kill. Each metric is independently try/except'd.

Env knobs: ORYX_BENCH_ITEMS/FEATURES/USERS/SECONDS/BATCH/DEPTH/DTYPE
(serving); ORYX_BENCH_ONLY (comma list of metric names to run);
ORYX_BENCH_ATTEMPTS, ORYX_BENCH_INIT_TIMEOUT; ORYX_TB_* (training
shapes, see tools/train_benchmark.py).
"""

import json
import os
import subprocess
import sys
import time
from collections import deque

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

# Persistent XLA compilation cache (inherited by the child processes):
# retried attempts and repeat runs reload compiled programs from disk
# instead of re-paying tens of seconds of compiles per bucketed shape.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(_HERE, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# r02 CPU-container floors (docs/performance.md, identical configs)
CPU_FLOOR_ALS_WALL = 4.3
CPU_FLOOR_ALS_SCALE_RPS = 227_000.0
CPU_FLOOR_KMEANS_WALL = 0.6
CPU_FLOOR_RDF_WALL = 34.3
SERVING_BASELINE_QPS = 437.0
SPEED_TARGET_EPS = 100_000.0


def _emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(float(value), 2),
                "unit": unit,
                "vs_baseline": round(float(vs_baseline), 2),
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# Child: the benchmark bodies.
# --------------------------------------------------------------------------


def bench_serving(features_override: int | None = None, baseline_qps: float | None = None) -> None:
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = features_override or int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_USERS", 8192))
    seconds = float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    group = int(os.environ.get("ORYX_BENCH_GROUP", 2048))  # queries/dispatch
    # narrower scans for wide features keep the kernel inside scoped VMEM
    scan_batch = int(
        os.environ.get("ORYX_BENCH_SCAN_BATCH", 256 if features <= 64 else 128)
    )
    depth = int(os.environ.get("ORYX_BENCH_DEPTH", 12))  # dispatches in flight
    dtype_name = os.environ.get("ORYX_BENCH_DTYPE", "bfloat16")
    how_many = 10

    import numpy as np
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend != "tpu":
        seconds = min(seconds, 5.0)
        depth = min(depth, 4)
        group = min(group, 512)

    from oryx_tpu.ops import topn as topn_ops

    gen = np.random.default_rng(1234)
    y = gen.standard_normal((items, features), dtype=np.float32)
    x = gen.standard_normal((users, features), dtype=np.float32)

    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    uploaded = topn_ops.upload(y, dtype=dtype)
    scans_per_dispatch = (group + scan_batch - 1) // scan_batch
    # "index": user-factor matrix staged on device once, each dispatch
    # ships int32 row indices (4 B/query up) — the serving layout where X
    # lives next to Y. "vector": full query vectors up per dispatch.
    submit_mode = os.environ.get("ORYX_BENCH_SUBMIT", "index")
    x_dev = topn_ops.upload_queries(x) if submit_mode == "index" else None
    idx_all = np.arange(users, dtype=np.int32)

    def submit(lo: int, hi: int):
        if submit_mode == "index":
            return topn_ops.submit_top_k_multi_indexed(
                uploaded, x_dev, idx_all[lo:hi], how_many, scan_batch=scan_batch
            )
        return topn_ops.submit_top_k_multi(
            uploaded, x[lo:hi], how_many, scan_batch=scan_batch
        )

    t0 = time.perf_counter()
    try:
        submit(0, group).result()
    except Exception as e:  # noqa: BLE001
        if submit_mode != "index":
            raise
        # index submit is the default but must never cost the metric:
        # fall back to vector upload if the indexed program won't build
        print(f"bench[serving]: index submit failed ({e!r}); vector fallback", file=sys.stderr)
        submit_mode = "vector"
        x_dev = None
        submit(0, group).result()
    print(f"bench[serving]: warmup/compile {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    served = 0
    inflight: deque = deque()
    latencies: list[float] = []
    # real row spans: the last (or only) group may be short of `group`
    bounds = [
        (lo, min(lo + group, users)) for lo in range(0, max(users, 1), group)
    ]
    start = time.perf_counter()
    deadline = start + seconds
    i = 0
    while True:
        now = time.perf_counter()
        if now < deadline and len(inflight) < depth:
            lo, hi = bounds[i % len(bounds)]
            inflight.append((submit(lo, hi), hi - lo, time.perf_counter()))
            i += 1
        elif inflight:
            handle, rows, t_submit = inflight.popleft()
            handle.result()
            latencies.append(time.perf_counter() - t_submit)
            served += rows
        else:
            break
    elapsed = time.perf_counter() - start
    qps = served / elapsed
    lat = np.percentile(np.array(latencies) * 1000, [50, 99]) if latencies else [0, 0]
    print(
        f"bench[serving]: request latency p50 {lat[0]:.0f} ms / p99 {lat[1]:.0f} ms "
        f"(queued-behind-pipeline latency at depth {depth})",
        file=sys.stderr,
    )
    bytes_per_scan = items * features * (2 if dtype_name == "bfloat16" else 4)
    gbps = i * scans_per_dispatch * bytes_per_scan / elapsed / 1e9
    print(
        f"bench[serving]: ~{gbps:.1f} GB/s effective item-matrix read bandwidth "
        f"({i} dispatches x {scans_per_dispatch} fused scans)",
        file=sys.stderr,
    )
    tag = "" if backend == "tpu" else f", {backend} FALLBACK"
    base = baseline_qps or SERVING_BASELINE_QPS
    _emit(
        f"ALS recommend top-{how_many} exact scan ({features} feat x {items} "
        f"items, {dtype_name}, {scans_per_dispatch} fused scans x {scan_batch} "
        f"queries x depth {depth}, {submit_mode}-submit, ~{gbps:.0f} GB/s effective, "
        f"p50 {lat[0]:.0f}ms/p99 {lat[1]:.0f}ms{tag}) "
        f"vs published {base:.0f} qps (LSH 0.3, 32-core Xeon)",
        qps,
        "queries/sec",
        qps / base,
    )


def bench_serving_250() -> None:
    """The reference table's heavier shape: 250 feat x 1M items
    (151 qps published at LSH 0.3; performance.md:113)."""
    bench_serving(features_override=250, baseline_qps=151.0)


def bench_kmeans() -> None:
    from tools import train_benchmark as tb

    tb.bench_kmeans()  # compile pass — generations reuse compiled programs
    r = tb.bench_kmeans()
    _emit(
        f"k-means train wall, steady-state ({r['config']}, sse/pt "
        f"{r['sse_per_point']}, silhouette {r['silhouette_2k_sample']}, "
        f"{r['backend']}) vs this build's CPU floor {CPU_FLOOR_KMEANS_WALL}s",
        r["wall_sec"],
        "sec",
        CPU_FLOOR_KMEANS_WALL / max(r["wall_sec"], 1e-9),
    )


def bench_als() -> None:
    from tools import train_benchmark as tb

    tb.bench_als()  # compile pass
    r = tb.bench_als()
    _emit(
        f"ALS train wall, steady-state (ML-100K shape, {r['config']}, "
        f"held-out RMSE {r['held_out_rmse']}, {r['backend']}) "
        f"vs this build's CPU floor {CPU_FLOOR_ALS_WALL}s",
        r["wall_sec"],
        "sec",
        CPU_FLOOR_ALS_WALL / max(r["wall_sec"], 1e-9),
    )


def bench_als_scale() -> None:
    from tools import train_benchmark as tb

    # the baseline row must be f32 even if the experiment knob is exported
    prev = os.environ.pop("ORYX_TB_MATMUL_DTYPE", None)
    r = tb.bench_als_scale()
    _emit(
        f"ALS implicit training throughput ({r['config']}, {r['backend']}) "
        f"vs this build's CPU floor {CPU_FLOOR_ALS_SCALE_RPS / 1000:.0f}k ratings/s",
        r["ratings_per_sec"],
        "ratings/sec",
        r["ratings_per_sec"] / CPU_FLOOR_ALS_SCALE_RPS,
    )
    # the bf16-Gramian variant (oryx.batch.compute.matmul-dtype=bfloat16):
    # half the HBM traffic, full-rate MXU; same CPU-floor denominator
    os.environ["ORYX_TB_MATMUL_DTYPE"] = "bfloat16"
    try:
        rb = tb.bench_als_scale()
    finally:
        if prev is None:
            os.environ.pop("ORYX_TB_MATMUL_DTYPE", None)
        else:
            os.environ["ORYX_TB_MATMUL_DTYPE"] = prev
    _emit(
        f"ALS implicit training throughput, bf16 Gramians ({rb['config']}, "
        f"{rb['backend']}) vs this build's CPU floor "
        f"{CPU_FLOOR_ALS_SCALE_RPS / 1000:.0f}k ratings/s",
        rb["ratings_per_sec"],
        "ratings/sec",
        rb["ratings_per_sec"] / CPU_FLOOR_ALS_SCALE_RPS,
    )


def bench_rdf() -> None:
    from tools import train_benchmark as tb

    tb.bench_rdf()  # compile pass — generations reuse compiled programs
    r = tb.bench_rdf()
    _emit(
        f"RDF train wall, steady-state ({r['config']}, held-out accuracy "
        f"{r['held_out_accuracy']}, {r['backend']}) "
        f"vs this build's CPU floor {CPU_FLOOR_RDF_WALL}s",
        r["wall_sec"],
        "sec",
        CPU_FLOOR_RDF_WALL / max(r["wall_sec"], 1e-9),
    )


def bench_speed() -> None:
    """Run the real-SpeedLayer bench as a subprocess (own process: it
    spins threads and a file bus) and relay its metric."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_HERE, "tools", "speed_layer_benchmark.py"),
            "--seconds",
            "25",
            "--prefill",
            "800000",
        ],
        capture_output=True,
        text=True,
        timeout=400,
        env=dict(os.environ),
    )
    sys.stderr.write(proc.stderr[-1500:])
    line = None
    for ln in proc.stdout.splitlines():
        if ln.startswith("{") and '"metric"' in ln:
            line = ln
    if proc.returncode != 0 or line is None:
        raise RuntimeError(f"speed bench failed rc={proc.returncode}")
    d = json.loads(line)
    _emit(
        f"{d['metric']} (prefilled backlog, {os.cpu_count()}-core host) "
        f"vs BASELINE 100K events/s target",
        d["value"],
        "events/sec",
        d["value"] / SPEED_TARGET_EPS,
    )


BENCHES = [
    ("serving", bench_serving),
    ("serving-250", bench_serving_250),
    ("kmeans", bench_kmeans),
    ("als", bench_als),
    ("als-scale", bench_als_scale),
    ("speed", bench_speed),
    ("rdf", bench_rdf),
]


def run_bench() -> None:
    only = os.environ.get("ORYX_BENCH_ONLY")
    selected = {s.strip() for s in only.split(",")} if only else None

    import jax

    import oryx_tpu

    # a site plugin may have pinned jax_platforms at import; re-assert
    oryx_tpu.honor_platform_env()
    print(
        f"bench: backend={jax.default_backend()} devices={len(jax.devices())}",
        file=sys.stderr,
    )
    ok = 0
    for name, fn in BENCHES:
        if selected is not None and name not in selected:
            continue
        t0 = time.perf_counter()
        try:
            fn()
            ok += 1
        except Exception as e:  # noqa: BLE001 - each metric independent
            print(f"bench[{name}]: FAILED: {e!r}", file=sys.stderr)
        print(
            f"bench[{name}]: done in {time.perf_counter() - t0:.0f}s",
            file=sys.stderr,
        )
    if ok == 0:
        sys.exit(3)


# --------------------------------------------------------------------------
# Parent: preflight + retry harness (fresh process per attempt — JAX
# caches a failed backend for the life of the process).
# --------------------------------------------------------------------------


def _run_child(env: dict, timeout: float) -> tuple[int, list[str], str]:
    """Stream child stdout, forwarding metric JSON lines immediately so
    completed metrics survive a mid-run kill."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    json_lines: list[str] = []

    import threading

    # hard watchdog: a child hung in backend init prints nothing, so the
    # readline loop alone would block forever — kill unconditionally at
    # the deadline
    timed_out = threading.Event()

    def _watchdog() -> None:
        if proc.poll() is None:
            timed_out.set()
            proc.kill()

    killer = threading.Timer(timeout, _watchdog)
    killer.daemon = True
    killer.start()

    err_chunks: list[str] = []
    t = threading.Thread(
        target=lambda: err_chunks.append(proc.stderr.read()), daemon=True
    )
    t.start()
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                json_lines.append(line)
                print(line, flush=True)
        rc = proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        rc = -9
    finally:
        killer.cancel()
    t.join(timeout=5)
    err = err_chunks[0] if err_chunks else ""
    if timed_out.is_set():
        rc = -9
        err += "\n[parent] child timed out"
    return rc, json_lines, err


def _probe_backend(timeout: float) -> bool:
    """Quick subprocess probe: can the device backend actually run an op?
    A wedged tunnel makes jax HANG (not error) in init, so without this
    a dead TPU costs a full child-watchdog cycle per attempt before the
    CPU fallback ever runs."""
    code = "import jax, jax.numpy as jnp; jnp.ones(3).sum().block_until_ready(); print('PROBE-OK')"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=dict(os.environ),
        )
        return "PROBE-OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    attempts = int(os.environ.get("ORYX_BENCH_ATTEMPTS", 3))
    init_timeout = float(os.environ.get("ORYX_BENCH_INIT_TIMEOUT", 150))
    # generous: metrics stream as they complete, so a watchdog kill only
    # costs whatever is still running (RDF, the slowest, goes last)
    child_timeout = init_timeout + 1800

    # attempts=1 is the documented fail-fast-TPU contract: no probe-driven
    # CPU fallback there either
    if os.environ.get("JAX_PLATFORMS") != "cpu" and attempts > 1:
        for p in range(2):
            if _probe_backend(init_timeout):
                break
            print(
                f"bench[parent]: backend probe {p + 1}/2 failed (hung init?)",
                file=sys.stderr,
            )
            if p == 0:
                time.sleep(20)
        else:
            print(
                "bench[parent]: device backend unreachable — CPU fallback",
                file=sys.stderr,
            )
            os.environ["JAX_PLATFORMS"] = "cpu"

    base_env = dict(os.environ)
    base_env["ORYX_BENCH_CHILD"] = "1"
    # only fall back to CPU when there was at least one real TPU attempt
    # (ORYX_BENCH_ATTEMPTS=1 means "one fail-fast TPU try", not "CPU")
    cpu_fallback = attempts > 1 or os.environ.get("JAX_PLATFORMS") == "cpu"

    backoffs = [15, 30, 60]
    attempt = 0
    while attempt < attempts:
        last = attempt == attempts - 1
        env = dict(base_env)
        label = "cpu" if env.get("JAX_PLATFORMS") == "cpu" else "tpu"
        if last and cpu_fallback and env.get("JAX_PLATFORMS") != "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            label = "cpu-fallback"
        print(f"bench[parent]: attempt {attempt + 1}/{attempts} ({label})", file=sys.stderr)
        rc, json_lines, err = _run_child(env, timeout=child_timeout)
        sys.stderr.write(err[-5000:])
        if json_lines:
            # metrics were already streamed to stdout; done
            print(
                f"bench[parent]: {len(json_lines)} metric(s) recorded", file=sys.stderr
            )
            return
        transient = any(
            k in err
            for k in (
                "UNAVAILABLE",
                "Unable to initialize backend",
                "DEADLINE_EXCEEDED",
                "timed out",
            )
        )
        print(
            f"bench[parent]: attempt {attempt + 1} failed rc={rc} "
            f"({'transient backend error' if transient else 'non-transient'})",
            file=sys.stderr,
        )
        if not transient and not last:
            print("bench[parent]: skipping to final attempt", file=sys.stderr)
            attempt = attempts - 1
            continue
        if not last:
            wait = backoffs[min(attempt, len(backoffs) - 1)]
            print(f"bench[parent]: retrying in {wait}s", file=sys.stderr)
            time.sleep(wait)
        attempt += 1

    print("bench[parent]: all attempts failed — no benchmark number this round", file=sys.stderr)
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("ORYX_BENCH_CHILD"):
        run_bench()
    else:
        main()
