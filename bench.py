"""Driver benchmark: the full BASELINE metric set on TPU.

Emits ONE JSON line PER METRIC ({"metric","value","unit","vs_baseline",
"backend",...}) as each completes, then — tail-cap-proof — re-prints the
complete set as the LAST lines of output under a `=== BENCH SUMMARY ===`
header, ordered so the headline serving row is the very last line. Every
row carries `"backend"` ("tpu/TPU v5e" style); a CPU-fallback run
produces honestly-labeled `"backend":"cpu/..."` rows, never rows that
read as TPU. All rows (plus per-row detail) are appended to
`tools/bench_evidence.txt`.

Metrics (vs_baseline frames):
1. serving  — ALS /recommend exact-scan qps across the reference's
   published table shapes: 50/250 feat x 1M/5M/20M items
   (docs/performance.md:108-117 LSH-0.3 rows: 437/151/84/36/14/6 qps,
   32-core Xeon; ours is an exact scan, theirs sampled 30% of items).
   Rows carry `hbm_util` = effective item-matrix read bandwidth over the
   chip's peak HBM bandwidth (the scan is bandwidth-bound).
2. kmeans / als / rdf — train walls vs this build's r05 CPU-container
   floors (docs/performance.md); training rows carry `mfu` = analytic
   useful FLOPs / wall / chip peak bf16 FLOP/s.
3. als-scale — implicit power-law training ratings/s (f32 and bf16
   Gramians).
4. speed — sustained events/s through the REAL SpeedLayer over the shm
   bus vs the BASELINE.json 100K events/s target: a backlog row
   (pre-encoded ring drain, layer capacity) and a live row (producer
   processes racing the layer).
5. serving closed-loop — 1..3 concurrent SYNCHRONOUS clients through the
   real HTTP serving path (ServingLayer + endpoints + micro-batcher):
   true per-request p50/p99 next to the pipelined-throughput rows, the
   apples-to-apples view against the reference's 437 qps / 7 ms table.
6. tracing-overhead — speed backlog events/s and closed-loop serving qps
   with the distributed tracer on (default 1% sampling) vs off
   (ORYX_TRACING=0); vs_baseline = on/off median ratio, hard-fails when
   clearly below the 0.98 envelope (docs/observability.md).

Noise protocol: every metric is measured over >= 3 trials (cheap
trainers 5) after the discarded compile pass; rows record the MEDIAN as
`value` plus `trials` and `spread` ([min, max] in the row's own units).
A row whose median misses its floor while its best trial clears it is
flagged `noise-suspect` — the regression call would flip on re-run luck,
so treat it as noise until a clean round says otherwise.

Resilience: the benchmark body runs in a child process; the parent
retries transient TPU-backend failures with a fresh process (JAX caches
a failed backend for the life of the process) and falls back to CPU on
the last attempt so the round still records (CPU-labeled) numbers.
Child stdout streams line-by-line so completed metrics survive a
mid-run kill; the summary block is printed by the parent after all
stderr, so XLA warning spam can never wash metrics out of a bounded
stdout tail (the round-4 failure mode).

Env knobs: ORYX_BENCH_ITEMS/FEATURES/USERS/SECONDS/BATCH/DEPTH/DTYPE
(serving); ORYX_BENCH_SHAPES=headline|all (serving table coverage);
ORYX_BENCH_ONLY (comma list of metric names); ORYX_BENCH_ATTEMPTS,
ORYX_BENCH_INIT_TIMEOUT; ORYX_BENCH_TRIALS / ORYX_BENCH_TRIALS_CHEAP
(noise protocol, default 3/5); ORYX_BENCH_CL_USERS/CL_SECONDS
(closed-loop serving); ORYX_BENCH_TRACE_PREFILL/ITEMS/SECONDS/ENVELOPE
(tracing-overhead); ORYX_BENCH_MAINTAIN_ITEMS/FEATURES/SECONDS/INTERVAL/
FRESH_BUDGET (live-maintenance ANN rows); ORYX_BENCH_COLD_ITEMS/COLD_RAM_MB
(cold-tier store row, sized down to free disk); ORYX_TB_* (training
shapes, see tools/train_benchmark.py).
"""

import json
import os
import statistics
import subprocess
import sys
import time
from collections import deque

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

EVIDENCE_PATH = os.path.join(_HERE, "tools", "bench_evidence.txt")

# Persistent XLA compilation cache (inherited by the child processes):
# retried attempts and repeat runs reload compiled programs from disk
# instead of re-paying tens of seconds of compiles per bucketed shape.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(_HERE, ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
# XLA:CPU AOT cache entries compiled on another machine spam stderr with
# E-level "machine features" lines (and can SIGILL); silence native logs
# below FATAL — bench prints its own diagnostics.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# r06 CPU-container floors (docs/performance.md, identical configs,
# re-measured 2026-08-06 under the trials/median protocol: one discarded
# compile pass, then 5 trials (k-means, ALS) / 3 trials (RDF, ALS-scale),
# median recorded; spreads were within 5% of the median for every floor.
# Much tighter than the 2026-07-30 r05 constants because the trainers
# themselves got faster in between (single-dispatch RDF level histograms,
# ALS solve caching, mini-batch k-means) — against the old floors every
# row would have read as a spurious speedup.
CPU_FLOOR_ALS_WALL = 0.42
CPU_FLOOR_ALS_SCALE_RPS = 575_000.0
CPU_FLOOR_KMEANS_WALL = 0.39
CPU_FLOOR_RDF_WALL = 7.2
SPEED_TARGET_EPS = 100_000.0

# Published /recommend qps at LSH sample-rate 0.3 on a 32-core Xeon
# (reference docs/performance.md:108-117), keyed by (features, items).
SERVING_BASELINE_QPS = {
    (50, 1_000_000): 437.0,
    (250, 1_000_000): 151.0,
    (50, 5_000_000): 84.0,
    (250, 5_000_000): 36.0,
    (50, 20_000_000): 14.0,
    (250, 20_000_000): 6.0,
}

# Chip peaks (bf16 FLOP/s, HBM bytes/s) by device-kind substring.
_CHIP_PEAKS = [
    ("v5 lite", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6", 918e12, 1640e9),
    ("trillium", 918e12, 1640e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
]


def _device_info():
    """(backend, device_kind, (peak_flops, peak_bw) or None)."""
    import jax

    backend = jax.default_backend()
    kind = getattr(jax.devices()[0], "device_kind", backend)
    peaks = None
    if backend == "tpu":
        low = kind.lower()
        for sub, fl, bw in _CHIP_PEAKS:
            if sub in low:
                peaks = (fl, bw)
                break
        if peaks is None:
            peaks = (197e12, 819e9)  # assume v5e-class if unrecognized
    return backend, kind, peaks


# Noise protocol: trials per metric. The cheap trainers (k-means, ALS
# ML-100K) get 5, everything else 3; medians go in `value`.
_TRIALS = max(1, int(os.environ.get("ORYX_BENCH_TRIALS", 3)))
_TRIALS_CHEAP = max(1, int(os.environ.get("ORYX_BENCH_TRIALS_CHEAP", 5)))


def _trial_fields(vals, ratios) -> dict:
    """`trials`/`spread` extras (plus the `noise-suspect` flag) for a set
    of per-trial measurements: spread is [min, max] in the row's own
    units; the row is noise-suspect when the MEDIAN misses the floor but
    the best trial clears it — the regression call would flip on re-run
    luck."""
    extra = {
        "trials": len(vals),
        "spread": [round(float(min(vals)), 3), round(float(max(vals)), 3)],
    }
    if statistics.median(ratios) < 1.0 <= max(ratios):
        extra["noise_suspect"] = True
    return extra


def _wall_row(walls, floor) -> tuple[float, float, dict]:
    """(median, vs_baseline, extras) for lower-is-better wall rows."""
    med = statistics.median(walls)
    return med, floor / max(med, 1e-9), _trial_fields(
        walls, [floor / max(w, 1e-9) for w in walls]
    )


def _rate_row(rates, floor) -> tuple[float, float, dict]:
    """(median, vs_baseline, extras) for higher-is-better rate rows."""
    med = statistics.median(rates)
    return med, med / floor, _trial_fields(rates, [v / floor for v in rates])


def _median_run(runs: list, key: str) -> dict:
    """The run dict whose `key` is the median trial's — its config,
    quality, and phase fields then describe a trial that was actually
    recorded rather than a synthetic average."""
    return sorted(runs, key=lambda r: r[key])[len(runs) // 2]


def _emit(
    metric: str,
    value: float,
    unit: str,
    vs_baseline: float,
    order: int = 50,
    detail: str = "",
    **extra,
) -> None:
    row = {
        "metric": metric,
        "value": round(float(value), 2),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 2),
    }
    if extra.pop("noise_suspect", False):
        row["noise-suspect"] = True
    if "backend" in extra:
        row["backend"] = extra.pop("backend")
    else:
        backend, kind, _ = _device_info()
        row["backend"] = f"{backend}/{kind}"
    for k, v in extra.items():
        if v is not None:
            row[k] = round(float(v), 4) if isinstance(v, float) else v
    row["order"] = order
    print(json.dumps(row), flush=True)
    try:
        with open(EVIDENCE_PATH, "a", encoding="utf-8") as f:
            ts = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
            f.write(f"{ts} {json.dumps(row)}\n")
            if detail:
                f.write(f"    {detail}\n")
    except OSError:
        pass


# --------------------------------------------------------------------------
# Child: the benchmark bodies.
# --------------------------------------------------------------------------


def bench_serving_shape(
    items: int, features: int, order: int, seconds: float | None = None
) -> None:
    users = int(os.environ.get("ORYX_BENCH_USERS", 8192))
    seconds = seconds or float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    group = int(os.environ.get("ORYX_BENCH_GROUP", 2048))  # queries/dispatch
    # narrower scans for wide features keep the kernel inside scoped VMEM
    scan_batch = int(
        os.environ.get("ORYX_BENCH_SCAN_BATCH", 256 if features <= 64 else 128)
    )
    depth = int(os.environ.get("ORYX_BENCH_DEPTH", 12))  # dispatches in flight
    # int8 by default: the row-quantized primary plane halves the scanned
    # bytes vs bf16 and the residual-plane rescore holds top-10 recall at
    # >= 0.99 of float32 (emitted below as its own metric row)
    dtype_name = os.environ.get("ORYX_BENCH_DTYPE", "int8")
    how_many = 10

    import numpy as np
    import jax
    import jax.numpy as jnp

    backend, kind, peaks = _device_info()
    if backend != "tpu":
        seconds = min(seconds, 5.0)
        depth = min(depth, 4)
        group = min(group, 512)

    from oryx_tpu.ops import topn as topn_ops

    gen = np.random.default_rng(1234)
    x = gen.standard_normal((users, features), dtype=np.float32)

    dtype = {"bfloat16": jnp.bfloat16, "int8": jnp.int8}.get(dtype_name, jnp.float32)
    # item matrix generated ON DEVICE: at 20M x 250 the bf16 matrix is
    # 10 GB that must not cross the host<->device tunnel
    uploaded = topn_ops.upload_random(items, features, dtype=dtype, seed=97 + features)
    scans_per_dispatch = (group + scan_batch - 1) // scan_batch
    # "index": user-factor matrix staged on device once, each dispatch
    # ships int32 row indices (4 B/query up) — the serving layout where X
    # lives next to Y. "vector": full query vectors up per dispatch.
    submit_mode = os.environ.get("ORYX_BENCH_SUBMIT", "index")
    x_dev = topn_ops.upload_queries(x) if submit_mode == "index" else None
    idx_all = np.arange(users, dtype=np.int32)

    def submit(lo: int, hi: int):
        if submit_mode == "index":
            return topn_ops.submit_top_k_multi_indexed(
                uploaded, x_dev, idx_all[lo:hi], how_many, scan_batch=scan_batch
            )
        return topn_ops.submit_top_k_multi(
            uploaded, x[lo:hi], how_many, scan_batch=scan_batch
        )

    t0 = time.perf_counter()
    try:
        submit(0, group).result()
    except Exception as e:  # noqa: BLE001
        if submit_mode != "index":
            raise
        # index submit is the default but must never cost the metric:
        # fall back to vector upload if the indexed program won't build
        print(f"bench[serving]: index submit failed ({e!r}); vector fallback", file=sys.stderr)
        submit_mode = "vector"
        x_dev = None
        submit(0, group).result()
    print(
        f"bench[serving {features}f x {items} items]: warmup/compile "
        f"{time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )

    # real row spans: the last (or only) group may be short of `group`
    bounds = [
        (lo, min(lo + group, users)) for lo in range(0, max(users, 1), group)
    ]

    def run_trial() -> tuple[float, float, list[float]]:
        """(qps, dispatches_per_sec, per-dispatch latencies) for one
        `seconds`-long pipelined pass."""
        served = 0
        inflight: deque = deque()
        lats: list[float] = []
        start = time.perf_counter()
        deadline = start + seconds
        i = 0
        while True:
            now = time.perf_counter()
            if now < deadline and len(inflight) < depth:
                lo, hi = bounds[i % len(bounds)]
                inflight.append((submit(lo, hi), hi - lo, time.perf_counter()))
                i += 1
            elif inflight:
                handle, rows, t_submit = inflight.popleft()
                handle.result()
                lats.append(time.perf_counter() - t_submit)
                served += rows
            else:
                break
        elapsed = time.perf_counter() - start
        return served / elapsed, i / elapsed, lats

    qps_trials: list[float] = []
    dispatch_rates: list[float] = []
    latencies: list[float] = []
    for _ in range(_TRIALS):
        q, dr, lats = run_trial()
        qps_trials.append(q)
        dispatch_rates.append(dr)
        latencies.extend(lats)
    lat = np.percentile(np.array(latencies) * 1000, [50, 99]) if latencies else [0, 0]
    # scanned bytes per full-matrix pass: int8 streams the 1 B/feat
    # primary plane (the residual plane is only gathered for the few
    # hundred rescore candidates), bf16 2 B/feat, f32 4 B/feat
    bytes_per_scan = items * features * {"bfloat16": 2, "int8": 1}.get(dtype_name, 4)
    gbps = statistics.median(dispatch_rates) * scans_per_dispatch * bytes_per_scan / 1e9
    hbm_util = gbps * 1e9 / peaks[1] if peaks else None
    published = (features, items) in SERVING_BASELINE_QPS
    base = SERVING_BASELINE_QPS.get((features, items), 437.0)
    qps, vs, tf = _rate_row(qps_trials, base)
    detail = (
        f"p50 {lat[0]:.0f} ms / p99 {lat[1]:.0f} ms queued-behind-pipeline at "
        f"depth {depth}; {tf['trials']} x {seconds:.0f}s trials, "
        f"{scans_per_dispatch} fused scans x {scan_batch} queries per dispatch, "
        f"{submit_mode}-submit; ~{gbps:.1f} GB/s "
        f"effective item-matrix read bandwidth"
        + (f" = {100 * hbm_util:.0f}% of {kind} peak {peaks[1] / 1e9:.0f} GB/s" if peaks else "")
    )
    print(f"bench[serving {features}f x {items}]: {detail}", file=sys.stderr)
    frame = (
        f"vs {base:.0f} qps published (LSH 0.3, 32-core Xeon)"
        if published
        else f"vs {base:.0f} qps headline figure (no published number for this shape)"
    )
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"
    _emit(
        f"ALS /recommend top-{how_many} exact scan, {features}f x {label_m} items, "
        f"{dtype_name}, {frame}",
        qps,
        "queries/sec",
        vs,
        order=order,
        detail=detail,
        hbm_util=hbm_util,
        p50_ms=float(lat[0]),
        p99_ms=float(lat[1]),
        effective_gbps=float(gbps),
        dispatch_depth=depth,
        **tf,
    )
    if dtype_name == "int8":
        _bench_serving_recall(items, features, how_many, order)


def _bench_serving_recall(
    items: int, features: int, how_many: int, order: int
) -> None:
    """Quantized-recall companion row: top-``how_many`` overlap of the
    int8 two-plane scan against the exact float32 ranking on a
    host-generated matrix of the same shape (capped at 1M items — the
    probe needs the float32 truth in host RAM). Tie-tolerant: a returned
    item counts as a hit when its true score reaches the true k-th best
    minus 1e-5, so exact-tie reorderings don't read as recall loss."""
    import numpy as np
    import jax.numpy as jnp

    from oryx_tpu.ops import topn as topn_ops

    n = min(items, 1_000_000)
    probes = int(os.environ.get("ORYX_BENCH_RECALL_PROBES", 32))
    gen = np.random.default_rng(4321)
    mat = gen.standard_normal((n, features), dtype=np.float32)
    up8 = topn_ops.upload(mat, dtype=jnp.int8)
    recalls: list[float] = []
    for t in range(_TRIALS):
        # fresh probe set per trial: the spread measures probe-sampling
        # noise on the one quantized matrix actually served
        qgen = np.random.default_rng(9876 + t)
        queries = qgen.standard_normal((probes, features), dtype=np.float32)
        hits = 0
        for r in range(probes):
            idx, _vals = topn_ops.top_k_scores(up8, queries[r], how_many)
            truth = mat @ queries[r]
            kth = np.partition(truth, -how_many)[-how_many]
            hits += int(np.sum(truth[np.asarray(idx)] >= kth - 1e-5))
        recalls.append(hits / (probes * how_many))
    recall, vs, tf = _rate_row(recalls, 0.99)
    label_m = f"{n // 1_000_000}M" if n >= 1_000_000 else f"{n // 1000}K"
    _emit(
        f"ALS /recommend top-{how_many} int8 recall vs exact float32, "
        f"{features}f x {label_m} items, vs 0.99 floor",
        recall,
        "recall@10",
        vs,
        order=order + 1,
        detail=f"{probes} probe queries x {tf['trials']} probe sets, "
        "tie-tolerant at 1e-5",
        **tf,
    )


def _ann_mixture(n: int, features: int, cells: int, seed: int, batch: int):
    """Cell-matched mixture catalog + queries. IVF's recall-vs-probe
    curve requires cluster structure (ALS item factors have it; isotropic
    gaussian is the adversarial no-structure case where probing p% of
    cells finds ~p% of neighbors) — the rows say so in their detail."""
    import numpy as np

    gen = np.random.default_rng(seed)
    centers = gen.standard_normal((cells, features), dtype=np.float32)
    mat = centers[gen.integers(0, cells, n)] + 0.3 * gen.standard_normal(
        (n, features), dtype=np.float32
    )
    queries = centers[gen.integers(0, cells, batch)] + 0.3 * gen.standard_normal(
        (batch, features), dtype=np.float32
    )
    return mat, queries


def _ann_recall_vs_exact(mat, queries, exact_ids, ann_ids, k: int) -> float:
    """recall@k of the ANN result against the exact int8 scan's result on
    the same matrix, tie-tolerant on true f32 scores (an ANN item whose
    true score reaches the exact k-th's minus 1e-5 is a hit)."""
    import numpy as np

    hits = 0
    for r in range(len(queries)):
        q = queries[r]
        e = np.asarray(exact_ids[r][:k])
        a = np.asarray(ann_ids[r][:k])
        a = a[a >= 0]
        kth = float(np.min(mat[e] @ q))
        hits += int(np.sum(mat[a] @ q >= kth - 1e-5))
    return hits / (len(queries) * k)


def _ann_measure(fn, batch: int, dispatches: int):
    """(per-trial qps list, per-dispatch walls) after one warm dispatch."""
    fn()  # warm: trace/compile + route-table caches
    rates: list[float] = []
    walls: list[float] = []
    for _ in range(_TRIALS):
        t0 = time.perf_counter()
        for _ in range(dispatches):
            td = time.perf_counter()
            fn()
            walls.append(time.perf_counter() - td)
        rates.append(dispatches * batch / (time.perf_counter() - t0))
    return rates, walls


def _bench_ann_shape(
    items: int,
    features: int,
    nprobe: int,
    sweep: tuple,
    order: int,
    dispatches: int,
    emit_p99: bool = False,
) -> None:
    import numpy as np
    import jax.numpy as jnp

    from oryx_tpu.ops import ivf as ivf_ops
    from oryx_tpu.ops import topn as topn_ops

    how_many = 10
    batch = int(os.environ.get("ORYX_BENCH_ANN_BATCH", 256))
    cells = max(64, int(round(items**0.5 / 8)) * 8)
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"
    mat, queries = _ann_mixture(items, features, cells, 4242 + features, batch)

    # in-run exact int8 baseline on the SAME matrix: the ANN speedup
    # claim is only honest against the scan it displaces, measured under
    # the same noise
    up8 = topn_ops.upload(mat, dtype=jnp.int8)
    exact_ids_box: list = []

    def exact_call():
        ids, _vals = topn_ops.top_k_scores_batch(up8, queries, how_many)
        if not exact_ids_box:
            exact_ids_box.append(np.asarray(ids))

    exact_rates, _ = _ann_measure(exact_call, batch, max(1, dispatches // 2))
    exact_qps = statistics.median(exact_rates)
    exact_ids = exact_ids_box[0]
    del up8

    t0 = time.perf_counter()
    index = ivf_ops.build_ivf(mat, n_cells=cells, seed=7)
    build_sec = time.perf_counter() - t0
    print(
        f"bench[serving-ann {features}f x {label_m}]: build_ivf {build_sec:.0f}s "
        f"({index.n_cells} cells), exact int8 {exact_qps:.0f} qps",
        file=sys.stderr,
    )

    for np_ in sorted(set((nprobe,) + tuple(sweep))):
        ann_ids_box: list = []

        def ann_call():
            ids, _vals = ivf_ops.top_k(index, queries, how_many, nprobe=np_)
            if not ann_ids_box:
                ann_ids_box.append(np.asarray(ids))

        rates, walls = _ann_measure(ann_call, batch, dispatches)
        recall = _ann_recall_vs_exact(mat, queries, exact_ids, ann_ids_box[0], how_many)
        qps, vs, tf = _rate_row(rates, exact_qps)
        frac = 100.0 * np_ / index.n_cells
        headline = np_ == nprobe
        detail = (
            f"IVF {index.n_cells} cells, nprobe {np_} ({frac:.1f}% probed), "
            f"recall@10 {recall:.3f} vs exact int8 (tie-tolerant 1e-5), "
            f"{tf['trials']} x {dispatches} dispatches x {batch} queries, "
            f"cell-matched mixture catalog (see docs/serving-scan.md data-model "
            f"caveat), build {build_sec:.0f}s; vs_baseline = speedup over the "
            f"in-run exact int8 scan ({exact_qps:.0f} qps)"
        )
        print(f"bench[serving-ann {features}f x {label_m}]: {detail}", file=sys.stderr)
        extra = dict(
            recall_at_10=round(recall, 4),
            nprobe=np_,
            cells=index.n_cells,
            exact_qps=round(exact_qps, 1),
            build_sec=round(build_sec, 1),
        )
        if emit_p99:
            lat = np.percentile(np.array(walls) * 1000.0, [50, 99])
            extra.update(p50_ms=float(lat[0]), p99_ms=float(lat[1]))
        kind = "ANN scan" if headline else f"ANN probe sweep nprobe={np_}"
        _emit(
            f"ALS /recommend top-{how_many} {kind}, {features}f x {label_m} items, "
            f"int8 IVF, vs in-run exact int8 qps",
            qps,
            "queries/sec",
            vs,
            order=order if headline else order - 1,
            detail=detail,
            **extra,
            **tf,
        )
        if headline:
            # the acceptance floor rides its own row: recall@10 >= 0.95
            _emit(
                f"ALS /recommend top-{how_many} ANN recall vs exact int8, "
                f"{features}f x {label_m} items, vs 0.95 floor",
                recall,
                "recall@10",
                recall / 0.95,
                order=order,
                detail=f"nprobe {np_} of {index.n_cells} cells ({frac:.1f}%), "
                "tie-tolerant at 1e-5 on true f32 scores",
                nprobe=np_,
                cells=index.n_cells,
            )


def bench_serving_ann() -> None:
    """IVF ANN tier rows: qps + recall@10 against the exact int8 scan on
    the same matrix in the same run (both 1M shapes), a probe-fraction
    sweep at the wide shape, and a >=10M-item steady-state row with
    per-dispatch p50/p99."""
    from oryx_tpu.ops import ivf as ivf_ops

    items = int(os.environ.get("ORYX_BENCH_ANN_ITEMS", 1_000_000))
    old_qb = ivf_ops.QUERY_BLOCK
    # small query groups keep the probed-cell union near nprobe cells per
    # group — the measured host-path knee
    ivf_ops.configure_ann(query_block=4)
    try:
        _bench_ann_shape(items, 50, nprobe=7, sweep=(), order=86, dispatches=4)
        # 0.3% probed is the measured qps/recall knee at the wide shape on
        # clustered catalogs (recall@10 1.0, ~4-8x exact); 7 and 15 chart
        # the recall-insurance side of the curve
        _bench_ann_shape(items, 250, nprobe=3, sweep=(7, 15), order=87, dispatches=4)
        if os.environ.get("ORYX_BENCH_SHAPES", "all") == "all":
            large = int(os.environ.get("ORYX_BENCH_ANN_LARGE_ITEMS", 10_000_000))
            cells = max(64, int(round(large**0.5 / 8)) * 8)
            _bench_ann_shape(
                large,
                50,
                nprobe=max(8, int(round(0.0025 * cells))),
                sweep=(),
                order=88,
                dispatches=2,
                emit_p99=True,
            )
    finally:
        ivf_ops.configure_ann(query_block=old_qb)


def bench_serving() -> None:
    # headline shape last so its row is the last line of the summary
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    bench_serving_shape(items, features, order=100)


def bench_serving_250() -> None:
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    bench_serving_shape(items, 250, order=90)


def bench_serving_large() -> None:
    """The reference table's 5M/20M-item rows (performance.md:114-117).
    TPU-only: HBM-resident bf16; on CPU these would measure host DRAM."""
    backend, _, _ = _device_info()
    if backend != "tpu":
        print("bench[serving-large]: skipped (no TPU)", file=sys.stderr)
        return
    for items, features, order in (
        (5_000_000, 50, 80),
        (5_000_000, 250, 81),
        (20_000_000, 50, 82),
        (20_000_000, 250, 83),
    ):
        bench_serving_shape(items, features, order=order, seconds=6.0)


def _emit_phases(name: str, runs: list, order: int) -> None:
    """Per-phase wall row next to a trainer's headline: value = iterate
    (the sweep itself) from the median-iterate trial, vs_baseline =
    iterate's share of that trial's phased wall; pack/init/eval ride
    along as extra fields. Makes host packing and dispatch overhead vs
    real iteration visible without a profiler."""
    phs = [r.get("phase_sec") or {} for r in runs]
    phs = [p for p in phs if p]
    if not phs:
        return
    phs.sort(key=lambda p: p.get("iterate", 0.0))
    ph = phs[len(phs) // 2]
    total = sum(ph.values())
    iters = [p.get("iterate", 0.0) for p in phs]
    _emit(
        f"{name} per-phase wall, iterate sec (share of pack+init+iterate+eval)",
        ph.get("iterate", 0.0),
        "sec",
        ph.get("iterate", 0.0) / total if total > 0 else 0.0,
        order=order,
        detail=json.dumps(ph),
        trials=len(phs),
        spread=[round(min(iters), 3), round(max(iters), 3)],
        pack_sec=ph.get("pack"),
        init_sec=ph.get("init"),
        iterate_sec=ph.get("iterate"),
        eval_sec=ph.get("eval"),
    )


def bench_kmeans() -> None:
    from tools import train_benchmark as tb

    tb.bench_kmeans()  # compile pass — generations reuse compiled programs
    runs = [tb.bench_kmeans() for _ in range(_TRIALS_CHEAP)]
    r = _median_run(runs, "wall_sec")
    wall, vs, tf = _wall_row([t["wall_sec"] for t in runs], CPU_FLOOR_KMEANS_WALL)
    _, _, peaks = _device_info()
    n, d, k, iters = int(os.environ.get("ORYX_TB_KMEANS_N", 200_000)), 20, 10, 20
    flops = 3.0 * n * d * k * iters  # dist matmul 2ndk + argmin/update ~ndk
    mfu = flops / max(wall, 1e-9) / peaks[0] if peaks else None
    _emit(
        f"k-means train wall, median of {tf['trials']} steady-state trials, "
        f"{r['config']}, vs {CPU_FLOOR_KMEANS_WALL}s CPU floor",
        wall,
        "sec",
        vs,
        order=10,
        detail=f"sse/pt {r['sse_per_point']}, silhouette {r['silhouette_2k_sample']}",
        mfu=mfu,
        **tf,
    )
    _emit_phases("k-means", runs, order=30)


def bench_als() -> None:
    from tools import train_benchmark as tb

    tb.bench_als()  # compile pass
    runs = [tb.bench_als() for _ in range(_TRIALS_CHEAP)]
    r = _median_run(runs, "wall_sec")
    wall, vs, tf = _wall_row([t["wall_sec"] for t in runs], CPU_FLOOR_ALS_WALL)
    _emit(
        f"ALS train wall, median of {tf['trials']} steady-state trials, "
        f"ML-100K shape rank 25, vs {CPU_FLOOR_ALS_WALL}s CPU floor",
        wall,
        "sec",
        vs,
        order=12,
        detail=f"{r['config']}; held-out RMSE {r['held_out_rmse']}",
        **tf,
    )
    _emit_phases("ALS", runs, order=32)


def _als_scale_mfu(r: dict) -> float | None:
    """Analytic useful FLOPs for the sweep: each rating contributes a
    rank^2 outer product to its row's Gramian on both sides (4*nnz*r^2
    FLOPs/sweep); rank^3 solves are lower-order at these shapes."""
    _, _, peaks = _device_info()
    if not peaks:
        return None
    nnz = int(float(os.environ.get("ORYX_TB_SCALE_NNZ", 2e6)))
    rank = int(os.environ.get("ORYX_TB_SCALE_RANK", 32))
    flops_per_sweep = 4.0 * nnz * rank * rank
    return flops_per_sweep * 3 / max(r["wall_sec"], 1e-9) / peaks[0]


def bench_als_scale() -> None:
    from tools import train_benchmark as tb

    # the baseline row must be f32 even if the experiment knob is exported
    prev = os.environ.pop("ORYX_TB_MATMUL_DTYPE", None)
    runs = [tb.bench_als_scale() for _ in range(_TRIALS)]
    r = _median_run(runs, "ratings_per_sec")
    rate, vs, tf = _rate_row(
        [t["ratings_per_sec"] for t in runs], CPU_FLOOR_ALS_SCALE_RPS
    )
    _emit(
        f"ALS implicit training throughput, f32 Gramians, median of "
        f"{tf['trials']} trials, "
        f"vs {CPU_FLOOR_ALS_SCALE_RPS / 1000:.0f}k ratings/s CPU floor",
        rate,
        "ratings/sec",
        vs,
        order=20,
        detail=r["config"],
        mfu=_als_scale_mfu(r),
        **tf,
    )
    # the pack phase dominates host-side cost at this shape — surface it
    _emit_phases("ALS implicit scale f32", runs, order=33)
    # the bf16-Gramian variant (oryx.batch.compute.matmul-dtype=bfloat16):
    # half the HBM traffic, full-rate MXU; same CPU-floor denominator
    os.environ["ORYX_TB_MATMUL_DTYPE"] = "bfloat16"
    try:
        runs_b = [tb.bench_als_scale() for _ in range(_TRIALS)]
    finally:
        if prev is None:
            os.environ.pop("ORYX_TB_MATMUL_DTYPE", None)
        else:
            os.environ["ORYX_TB_MATMUL_DTYPE"] = prev
    rb = _median_run(runs_b, "ratings_per_sec")
    rate_b, vs_b, tf_b = _rate_row(
        [t["ratings_per_sec"] for t in runs_b], CPU_FLOOR_ALS_SCALE_RPS
    )
    _emit(
        f"ALS implicit training throughput, bf16 Gramians, median of "
        f"{tf_b['trials']} trials, "
        f"vs {CPU_FLOOR_ALS_SCALE_RPS / 1000:.0f}k ratings/s CPU floor",
        rate_b,
        "ratings/sec",
        vs_b,
        order=21,
        detail=rb["config"],
        mfu=_als_scale_mfu(rb),
        **tf_b,
    )
    backend, _, peaks = _device_info()
    if backend == "tpu":
        # a TPU-scale row: 2M x rank-32 can't fill the MXU; 20M x rank-64
        # is the shape docs/performance.md's sharded-CPU run recorded at
        # 106k ratings/s (the closest this build has to a CPU floor there)
        saved = {
            k: os.environ.get(k)
            for k in ("ORYX_TB_SCALE_NNZ", "ORYX_TB_SCALE_RANK", "ORYX_TB_MATMUL_DTYPE")
        }
        os.environ.update(
            ORYX_TB_SCALE_NNZ="20000000",
            ORYX_TB_SCALE_RANK="64",
            ORYX_TB_MATMUL_DTYPE="bfloat16",
        )
        try:
            runs_t = [tb.bench_als_scale() for _ in range(_TRIALS)]
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        rt = _median_run(runs_t, "ratings_per_sec")
        rate_t, vs_t, tf_t = _rate_row(
            [t["ratings_per_sec"] for t in runs_t], 106_000.0
        )
        flops = 4.0 * 20e6 * 64 * 64 * 3
        _emit(
            "ALS implicit training throughput, 20M ratings rank 64 bf16, "
            f"median of {tf_t['trials']} trials, vs 106k ratings/s (this "
            "build's 8-virtual-CPU sharded run of the same shape)",
            rate_t,
            "ratings/sec",
            vs_t,
            order=22,
            detail=rt["config"],
            mfu=flops / max(rt["wall_sec"], 1e-9) / peaks[0] if peaks else None,
            **tf_t,
        )


def bench_rdf() -> None:
    from tools import train_benchmark as tb

    tb.bench_rdf()  # compile pass — generations reuse compiled programs
    runs = [tb.bench_rdf() for _ in range(_TRIALS)]
    r = _median_run(runs, "wall_sec")
    wall, vs, tf = _wall_row([t["wall_sec"] for t in runs], CPU_FLOOR_RDF_WALL)
    _emit(
        f"RDF train wall, median of {tf['trials']} steady-state trials, "
        f"covtype shape 20 trees depth 10, vs {CPU_FLOOR_RDF_WALL}s CPU floor",
        wall,
        "sec",
        vs,
        order=11,
        detail=f"{r['config']}; held-out accuracy {r['held_out_accuracy']}",
        **tf,
    )
    _emit_phases("RDF", runs, order=31)


def bench_speed() -> None:
    """Run the real-SpeedLayer bench as a subprocess (own process: it
    spins threads, producer processes, and an shm bus). Two rows:
    backlog mode (pre-encoded events drained from the ring — the
    layer-capacity measure) and live mode (producer processes racing the
    layer — the end-to-end measure). The trial protocol runs INSIDE the
    subprocess (--trials): model seeding is paid once per mode instead
    of once per trial, and the per-trial rates come back in the JSON."""

    def run_mode(label: str, extra: list) -> dict:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_HERE, "tools", "speed_layer_benchmark.py"),
                "--trials",
                str(_TRIALS),
                *extra,
            ],
            capture_output=True,
            text=True,
            timeout=600,
            env=dict(os.environ),
        )
        sys.stderr.write(proc.stderr[-1500:])
        line = None
        for ln in proc.stdout.splitlines():
            if ln.startswith("{") and '"metric"' in ln:
                line = ln
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"speed bench ({label}) failed rc={proc.returncode}"
            )
        return json.loads(line)

    # sharded row at N_cores shards (floor 2 so the multi-chain path is
    # exercised even on single-core CI hosts)
    n_shards = max(2, os.cpu_count() or 1)
    modes = [
        ("backlog", ["--prefill", "500000"]),
        (
            f"backlog {n_shards}-shard",
            ["--prefill", "500000", "--shards", str(n_shards)],
        ),
        ("live", ["--seconds", "12", "--producers", "2"]),
    ]
    for idx, (label, extra) in enumerate(modes):
        d = run_mode(label, extra)
        rates = d.get("rates") or [d["value"]]
        rate, vs, tf = _rate_row(rates, SPEED_TARGET_EPS)
        _emit(
            f"speed layer sustained fold-in over shm bus, {label} mode, "
            f"median of {tf['trials']} trials, vs 100K events/s BASELINE "
            f"target ({os.cpu_count()}-core host)",
            rate,
            "events/sec",
            vs,
            order=30 + idx,
            detail=d["metric"],
            # the speed layer is a host pipeline (bus I/O + parse +
            # fold-in); label it as such rather than stamping this
            # process's jax backend
            backend=f"host/{os.cpu_count()}-core",
            **tf,
        )


def bench_tracing_overhead() -> None:
    """Tracing-cost acceptance rows: the distributed tracer at its
    default 1% sample rate must cost <= 2% on both hot paths. Two
    comparisons, each >= 3-trial medians with tracing ON vs OFF:

    - speed layer backlog events/s — subprocess runs of the real
      SpeedLayer bench toggled via ORYX_TRACING (the layer process reads
      the env at import, exactly how an operator would disable tracing);
    - closed-loop serving qps through the real HTTP path (in-process
      `tracing.configure` toggle around the same layer + model).

    vs_baseline = on/off median ratio. A row whose median AND best trial
    both land below the 0.98 envelope hard-fails the bench; median-only
    misses are flagged `noise-suspect` per the repo's noise protocol."""
    import threading
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.common import tracing
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_TRACE_ENVELOPE", 0.98))
    failures: list[str] = []

    def ratio_row(
        kind: str, unit: str, on_rates: list, off_rates: list, order: int
    ) -> None:
        med_on = statistics.median(on_rates)
        med_off = max(statistics.median(off_rates), 1e-9)
        ratio = med_on / med_off
        best = max(on_rates) / med_off
        detail = (
            f"tracing on {med_on:.0f} vs off {med_off:.0f} {unit} "
            f"(medians of {len(on_rates)}/{len(off_rates)} trials), "
            f"overhead {100 * (1 - ratio):.2f}%, envelope <= "
            f"{100 * (1 - envelope):.0f}%"
        )
        print(f"bench[tracing-overhead {kind}]: {detail}", file=sys.stderr)
        _emit(
            f"tracing overhead, {kind}, default 1% sampling on vs off "
            f"(vs_baseline = on/off ratio, floor {envelope})",
            med_on,
            unit,
            ratio,
            order=order,
            detail=detail,
            off_value=round(med_off, 2),
            overhead_pct=round(100 * (1 - ratio), 3),
            noise_suspect=ratio < envelope <= best,
            spread=[round(float(min(on_rates)), 2), round(float(max(on_rates)), 2)],
            trials=len(on_rates),
        )
        if ratio < envelope and best < envelope:
            failures.append(f"{kind}: on/off {ratio:.4f} < {envelope}")

    # --- speed backlog: subprocess per mode, env toggle ---------------------
    prefill = int(os.environ.get("ORYX_BENCH_TRACE_PREFILL", 300_000))

    def speed_rates(tracing_on: bool) -> list:
        env = dict(os.environ)
        env["ORYX_TRACING"] = "1" if tracing_on else "0"
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_HERE, "tools", "speed_layer_benchmark.py"),
                "--trials",
                str(_TRIALS),
                "--prefill",
                str(prefill),
            ],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
        )
        sys.stderr.write(proc.stderr[-800:])
        line = None
        for ln in proc.stdout.splitlines():
            if ln.startswith("{") and '"metric"' in ln:
                line = ln
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"tracing-overhead speed run (on={tracing_on}) failed "
                f"rc={proc.returncode}"
            )
        d = json.loads(line)
        return d.get("rates") or [d["value"]]

    ratio_row(
        "speed backlog fold-in", "events/sec",
        speed_rates(True), speed_rates(False), order=40,
    )

    # --- serving closed-loop: in-process toggle around one warm layer ------
    items = int(os.environ.get("ORYX_BENCH_TRACE_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_TRACE_SECONDS", 4.0))
    cfg = C.get_default().with_overlay(
        """
        oryx {
          id = "BenchTracingOverhead"
          input-topic.broker = "inproc://benchtrc"
          update-topic.broker = "inproc://benchtrc"
          serving {
            api.port = 0
            api.read-only = true
            model-manager-class = "tools.load_benchmark:LoadTestModelManager"
            application-resources = "oryx_tpu.app.als.endpoints"
          }
        }
        """
    )
    layer = ServingLayer(cfg)
    layer.start()
    layer.model_manager.model = build_model(users, items, 50)
    base = f"http://127.0.0.1:{layer.port}"
    try:
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()

        def serving_qps(tracing_on: bool) -> list:
            tracing.configure(enabled=tracing_on)
            rates: list = []
            for _ in range(_TRIALS):
                lats: list = []
                stop = threading.Event()
                deadline = time.perf_counter() + seconds
                t1 = time.perf_counter()
                worker(base, "/recommend/u%d", users, deadline, lats, [], stop)
                if not lats:
                    raise RuntimeError("tracing-overhead serving: no requests")
                rates.append(len(lats) / (time.perf_counter() - t1))
            return rates

        on = serving_qps(True)
        off = serving_qps(False)
    finally:
        tracing.configure(enabled=True)
        layer.close()
    ratio_row("serving closed-loop", "queries/sec", on, off, order=41)

    if failures:
        raise RuntimeError("tracing overhead above envelope: " + "; ".join(failures))


def bench_lock_watchdog_overhead() -> None:
    """OrderedLock watchdog cost acceptance rows (docs/static-analysis.md):
    the runtime lock-order/timeout instrumentation the chaos, fleet and
    pipeline suites run under must cost <= 2% on both hot paths. Two
    comparisons, each >= 3-trial medians instrumented vs plain locks:

    - speed layer backlog events/s — subprocess runs of the real
      SpeedLayer bench toggled via ORYX_LOCK_WATCHDOG (patched before
      the broker/layer allocate their locks, like the test fixture);
    - closed-loop serving qps through the real HTTP path, one layer
      built under instrument() vs one built with raw locks.

    Trials are INTERLEAVED on/off in alternating order (on-off,
    off-on, ...): the instrumented hot paths take O(10) lock acquires
    per drain, so any minutes-apart block comparison measures host
    drift, not the watchdog — pairing adjacent trials cancels it.

    vs_baseline = instrumented/plain median ratio. A row whose median
    AND best trial both land below the 0.98 envelope hard-fails; a
    median-only miss is flagged `noise-suspect`. Strict mode stays on,
    so an observed lock-order cycle under load also fails the bench."""
    import threading
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.common import locks
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_LOCK_ENVELOPE", 0.98))
    failures: list[str] = []

    def ratio_row(
        kind: str, unit: str, on_rates: list, off_rates: list, order: int
    ) -> None:
        med_on = statistics.median(on_rates)
        med_off = max(statistics.median(off_rates), 1e-9)
        ratio = med_on / med_off
        best = max(on_rates) / med_off
        detail = (
            f"watchdog on {med_on:.0f} vs plain {med_off:.0f} {unit} "
            f"(medians of {len(on_rates)}/{len(off_rates)} trials), "
            f"overhead {100 * (1 - ratio):.2f}%, envelope <= "
            f"{100 * (1 - envelope):.0f}%"
        )
        print(f"bench[lock-watchdog {kind}]: {detail}", file=sys.stderr)
        _emit(
            f"OrderedLock watchdog overhead, {kind}, instrumented vs plain "
            f"locks (vs_baseline = on/off ratio, floor {envelope})",
            med_on,
            unit,
            ratio,
            order=order,
            detail=detail,
            off_value=round(med_off, 2),
            overhead_pct=round(100 * (1 - ratio), 3),
            noise_suspect=ratio < envelope <= best,
            spread=[round(float(min(on_rates)), 2), round(float(max(on_rates)), 2)],
            trials=len(on_rates),
        )
        if ratio < envelope and best < envelope:
            failures.append(f"{kind}: on/off {ratio:.4f} < {envelope}")

    # --- speed backlog: one single-trial subprocess per mode, interleaved ---
    prefill = int(os.environ.get("ORYX_BENCH_LOCK_PREFILL", 300_000))

    def speed_rate(watchdog_on: bool) -> float:
        env = dict(os.environ)
        env["ORYX_LOCK_WATCHDOG"] = "1" if watchdog_on else "0"
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(_HERE, "tools", "speed_layer_benchmark.py"),
                "--trials",
                "1",
                "--prefill",
                str(prefill),
            ],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
        )
        sys.stderr.write(proc.stderr[-800:])
        line = None
        for ln in proc.stdout.splitlines():
            if ln.startswith("{") and '"metric"' in ln:
                line = ln
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"lock-watchdog speed run (on={watchdog_on}) failed "
                f"rc={proc.returncode}"
            )
        return float(json.loads(line)["value"])

    speed_on: list = []
    speed_off: list = []
    for pair in range(_TRIALS):
        for mode_on in (True, False) if pair % 2 == 0 else (False, True):
            (speed_on if mode_on else speed_off).append(speed_rate(mode_on))
    ratio_row("speed backlog fold-in", "events/sec", speed_on, speed_off, order=42)

    # --- serving closed-loop: two live layers (one per lock flavor), --------
    # --- trials interleaved between them ------------------------------------
    items = int(os.environ.get("ORYX_BENCH_LOCK_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_LOCK_SECONDS", 4.0))
    cfg = C.get_default().with_overlay(
        """
        oryx {
          id = "BenchLockWatchdog"
          input-topic.broker = "inproc://benchlock"
          update-topic.broker = "inproc://benchlock"
          serving {
            api.port = 0
            api.read-only = true
            model-manager-class = "tools.load_benchmark:LoadTestModelManager"
            application-resources = "oryx_tpu.app.als.endpoints"
          }
        }
        """
    )

    def make_layer() -> tuple:
        layer = ServingLayer(cfg)
        layer.start()
        layer.model_manager.model = build_model(users, items, 50)
        base = f"http://127.0.0.1:{layer.port}"
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()
        return layer, base

    def serving_trial(base: str) -> float:
        lats: list = []
        stop = threading.Event()
        deadline = time.perf_counter() + seconds
        t1 = time.perf_counter()
        worker(base, "/recommend/u%d", users, deadline, lats, [], stop)
        if not lats:
            raise RuntimeError("lock-watchdog serving: no requests")
        return len(lats) / (time.perf_counter() - t1)

    plain_layer, plain_base = make_layer()
    try:
        locks.instrument(strict=True)
        try:
            # built under instrument(): every lock this layer (and its
            # batcher/server/model) constructs is a tracked OrderedLock
            inst_layer, inst_base = make_layer()
            try:
                srv_on: list = []
                srv_off: list = []
                for pair in range(_TRIALS):
                    for mode_on in (True, False) if pair % 2 == 0 else (False, True):
                        r = serving_trial(inst_base if mode_on else plain_base)
                        (srv_on if mode_on else srv_off).append(r)
                if locks.violations():
                    raise RuntimeError(
                        f"lock watchdog violations under load: {locks.violations()}"
                    )
            finally:
                inst_layer.close()
        finally:
            locks.deinstrument()
            locks.reset()
    finally:
        plain_layer.close()
    ratio_row("serving closed-loop", "queries/sec", srv_on, srv_off, order=43)

    if failures:
        raise RuntimeError("lock watchdog overhead above envelope: " + "; ".join(failures))


def bench_experiment_overhead() -> None:
    """Online-experiment cost acceptance rows (docs/experiments.md): the
    champion/challenger A/B machinery — sticky arm routing, the
    per-request observe hook, per-arm instance metrics, and the attached
    evaluator consumer thread — must cost <= 2% on the serving hot path
    when an experiment is ACTIVE. Same protocol as the lock-watchdog
    rows: two live layers in one process (one with a 10% challenger
    split and the evaluator attached, one with experiments bypassed
    entirely), >= 3 closed-loop trials per arm INTERLEAVED in
    alternating order so host drift cancels pairwise.

    vs_baseline = attached/bypassed median qps ratio; a row whose median
    AND best trial both land below the 0.98 envelope hard-fails, a
    median-only miss is flagged `noise-suspect`. A second row pins the
    realized challenger share against the configured 10% split — if
    routing were silently inactive the overhead row would measure
    nothing, so a share outside [0.05, 0.20] hard-fails too."""
    import shutil
    import tempfile
    import threading
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_EXPERIMENT_ENVELOPE", 0.98))
    failures: list[str] = []

    items = int(os.environ.get("ORYX_BENCH_EXPERIMENT_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_EXPERIMENT_SECONDS", 4.0))
    model_dir = tempfile.mkdtemp(prefix="oryx-bench-exp-")

    def overlay(ab_fraction: float, with_registry: bool) -> object:
        registry = (
            f'batch.storage.model-dir = "{model_dir}"' if with_registry else ""
        )
        return C.get_default().with_overlay(
            f"""
            oryx {{
              id = "BenchExperimentOverhead"
              input-topic.broker = "inproc://benchexp"
              update-topic.broker = "inproc://benchexp"
              {registry}
              serving {{
                api.port = 0
                api.read-only = true
                model-manager-class = "tools.load_benchmark:LoadTestModelManager"
                application-resources = "oryx_tpu.app.als.endpoints"
                ab.fraction = {ab_fraction}
              }}
            }}
            """
        )

    def make_layer(cfg) -> tuple:
        layer = ServingLayer(cfg)
        layer.start()
        layer.model_manager.model = build_model(users, items, 50)
        base = f"http://127.0.0.1:{layer.port}"
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()
        return layer, base

    def serving_trial(base: str) -> float:
        lats: list = []
        stop = threading.Event()
        deadline = time.perf_counter() + seconds
        t1 = time.perf_counter()
        worker(base, "/recommend/u%d", users, deadline, lats, [], stop)
        if not lats:
            raise RuntimeError("experiment-overhead serving: no requests")
        return len(lats) / (time.perf_counter() - t1)

    off_layer, off_base = make_layer(overlay(0.0, with_registry=False))
    try:
        on_layer, on_base = make_layer(overlay(0.10, with_registry=True))
        try:
            # make the experiment genuinely ACTIVE: champion pointer set,
            # a challenger generation live in the tracker, so every
            # request pays arm assignment + observe + per-arm metrics
            # (the load-test manager serves both arms identically)
            on_layer.registry_store.set_champion("1970010100000000")
            on_layer.generation_tracker._set_live("1970010100000000")
            on_layer.generation_tracker._set_challenger("1970010100000001")
            if on_layer.experiments is None or not on_layer.experiments.active:
                raise RuntimeError(
                    "experiment-overhead: experiments failed to activate"
                )
            srv_on: list = []
            srv_off: list = []
            for pair in range(_TRIALS):
                for mode_on in (True, False) if pair % 2 == 0 else (False, True):
                    r = serving_trial(on_base if mode_on else off_base)
                    (srv_on if mode_on else srv_off).append(r)
            with urllib.request.urlopen(f"{on_base}/experiments", timeout=30) as resp:
                report = json.loads(resp.read())
        finally:
            on_layer.close()
    finally:
        off_layer.close()
        shutil.rmtree(model_dir, ignore_errors=True)

    med_on = statistics.median(srv_on)
    med_off = max(statistics.median(srv_off), 1e-9)
    ratio = med_on / med_off
    best = max(srv_on) / med_off
    detail = (
        f"experiment active {med_on:.0f} vs bypassed {med_off:.0f} "
        f"queries/sec (medians of {len(srv_on)}/{len(srv_off)} trials), "
        f"overhead {100 * (1 - ratio):.2f}%, envelope <= "
        f"{100 * (1 - envelope):.0f}%"
    )
    print(f"bench[experiment-overhead serving]: {detail}", file=sys.stderr)
    _emit(
        "online experiment overhead, serving closed-loop, 10% challenger "
        f"split + evaluator attached vs bypassed (vs_baseline = on/off "
        f"ratio, floor {envelope})",
        med_on,
        "queries/sec",
        ratio,
        order=46,
        detail=detail,
        off_value=round(med_off, 2),
        overhead_pct=round(100 * (1 - ratio), 3),
        noise_suspect=ratio < envelope <= best,
        spread=[round(float(min(srv_on)), 2), round(float(max(srv_on)), 2)],
        trials=len(srv_on),
    )
    if ratio < envelope and best < envelope:
        failures.append(f"serving closed-loop: on/off {ratio:.4f} < {envelope}")

    arms = (report.get("report") or {}).get("arms") or {}
    champ_serves = int((arms.get("champion") or {}).get("serves") or 0)
    chal_serves = int((arms.get("challenger") or {}).get("serves") or 0)
    total = champ_serves + chal_serves
    share = chal_serves / total if total else 0.0
    detail = (
        f"challenger served {chal_serves}/{total} assigned requests "
        f"(share {share:.4f}) under ab.fraction = 0.10; sticky blake2b "
        f"bucketing over {users} uniform users"
    )
    print(f"bench[experiment-overhead split]: {detail}", file=sys.stderr)
    _emit(
        "online experiment realized challenger share, 10% configured split "
        "(vs_baseline = share/0.10)",
        round(share, 4),
        "fraction",
        round(share / 0.10, 4),
        order=47,
        detail=detail,
        trials=total,
    )
    if total == 0 or not 0.05 <= share <= 0.20:
        failures.append(
            f"challenger share {share:.4f} outside [0.05, 0.20] "
            f"({chal_serves}/{total} serves) — routing not active?"
        )

    if failures:
        raise RuntimeError(
            "experiment overhead above envelope: " + "; ".join(failures)
        )


def bench_ledger_overhead() -> None:
    """Resource-ledger cost acceptance rows (docs/static-analysis.md):
    the weakref live-resource accounting every layer registers into must
    cost <= 2% on the same two hot paths the lock-watchdog rows guard.
    Registration happens per acquisition (layer/consumer/session
    construction), never per event or per request, so the expected
    overhead is indistinguishable from noise — these rows pin that down.

    Both halves pair the arms INSIDE one process — the ledger's cost is
    so small that any protocol comparing separate processes (or separate
    layers) measures placement/drift artifacts instead; median AND best
    must both miss the envelope before a row hard-fails.

    - speed layer backlog events/s: ONE subprocess run of the real
      SpeedLayer bench with --toggle-env ORYX_RESOURCE_LEDGER flipping
      the ledger between drain trials (``enabled()`` re-reads the env
      per call), so on/off trials share JIT warm-up and host state;
    - closed-loop serving qps under a 2 Hz /metrics scraper: ONE live
      layer (its resources registered at construction), with the env
      toggle flipping the ledger's only steady-state work — the gauge
      refresh that probes every weakref on each scrape. A same-layer
      A/B sidesteps the two-layers-in-one-process placement bias that
      dwarfs the real cost (the /recommend path itself never touches
      the ledger).
    """
    import threading
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_LEDGER_ENVELOPE", 0.98))
    failures: list[str] = []

    def ratio_row(
        kind: str, unit: str, on_rates: list, off_rates: list, order: int
    ) -> None:
        med_on = statistics.median(on_rates)
        med_off = max(statistics.median(off_rates), 1e-9)
        ratio = med_on / med_off
        best = max(on_rates) / med_off
        detail = (
            f"ledger on {med_on:.0f} vs off {med_off:.0f} {unit} "
            f"(medians of {len(on_rates)}/{len(off_rates)} trials), "
            f"overhead {100 * (1 - ratio):.2f}%, envelope <= "
            f"{100 * (1 - envelope):.0f}%"
        )
        print(f"bench[resource-ledger {kind}]: {detail}", file=sys.stderr)
        _emit(
            f"resource ledger overhead, {kind}, registered vs disabled "
            f"(vs_baseline = on/off ratio, floor {envelope})",
            med_on,
            unit,
            ratio,
            order=order,
            detail=detail,
            off_value=round(med_off, 2),
            overhead_pct=round(100 * (1 - ratio), 3),
            noise_suspect=ratio < envelope <= best,
            spread=[round(float(min(on_rates)), 2), round(float(max(on_rates)), 2)],
            trials=len(on_rates),
        )
        if ratio < envelope and best < envelope:
            failures.append(f"{kind}: on/off {ratio:.4f} < {envelope}")

    # --- speed backlog: ONE subprocess, env flipped per drain trial ---------
    # (--toggle-env pairs the arms inside one process; separate on/off
    # subprocesses on this 1-core host measure minutes-apart machine
    # drift — a control run with the ledger off in BOTH arms showed
    # 3-11% phantom "overhead" under that protocol)
    prefill = int(os.environ.get("ORYX_BENCH_LEDGER_PREFILL", 300_000))
    # round up to a multiple of 4: the tool's ABBA toggle order is only
    # first-order balanced against host drift at 4k trials (drain trials
    # cost ~1.5s each, so the extra arms are nearly free)
    speed_trials = ((max(8, 2 * _TRIALS) + 3) // 4) * 4

    env = dict(os.environ)
    env["ORYX_RESOURCE_LEDGER"] = "1"  # construction registers under "on"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_HERE, "tools", "speed_layer_benchmark.py"),
            "--trials",
            str(speed_trials),
            "--prefill",
            str(prefill),
            "--toggle-env",
            "ORYX_RESOURCE_LEDGER",
        ],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
    )
    sys.stderr.write(proc.stderr[-800:])
    line = None
    for ln in proc.stdout.splitlines():
        if ln.startswith("{") and '"metric"' in ln:
            line = ln
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"resource-ledger speed run failed rc={proc.returncode}"
        )
    toggle = json.loads(line)["toggle"]
    ratio_row(
        "speed backlog fold-in", "events/sec",
        [float(r) for r in toggle["on"]],
        [float(r) for r in toggle["off"]],
        order=44,
    )

    # --- serving closed-loop: ONE live layer, env toggle flips the ----------
    # --- /metrics-scrape refresh work, trials interleaved -------------------
    items = int(os.environ.get("ORYX_BENCH_LEDGER_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_LEDGER_SECONDS", 4.0))
    cfg = C.get_default().with_overlay(
        """
        oryx {
          id = "BenchResourceLedger"
          input-topic.broker = "inproc://benchledger"
          update-topic.broker = "inproc://benchledger"
          serving {
            api.port = 0
            api.read-only = true
            model-manager-class = "tools.load_benchmark:LoadTestModelManager"
            application-resources = "oryx_tpu.app.als.endpoints"
          }
        }
        """
    )
    layer = ServingLayer(cfg)  # built with the ledger at its default (on)
    try:
        layer.start()
        layer.model_manager.model = build_model(users, items, 50)
        base = f"http://127.0.0.1:{layer.port}"
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()

        def serving_trial(ledger_on: bool) -> float:
            prev = os.environ.get("ORYX_RESOURCE_LEDGER")
            os.environ["ORYX_RESOURCE_LEDGER"] = "1" if ledger_on else "0"
            stop = threading.Event()

            def scrape():  # 2 Hz operator scrape: where refresh() runs
                while not stop.is_set():
                    try:
                        urllib.request.urlopen(f"{base}/metrics", timeout=10).read()
                    except OSError:
                        pass
                    stop.wait(0.5)

            scraper = threading.Thread(target=scrape, daemon=True)
            scraper.start()
            try:
                lats: list = []
                deadline = time.perf_counter() + seconds
                t1 = time.perf_counter()
                worker(base, "/recommend/u%d", users, deadline, lats, [], stop)
                if not lats:
                    raise RuntimeError("resource-ledger serving: no requests")
                return len(lats) / (time.perf_counter() - t1)
            finally:
                stop.set()
                scraper.join(timeout=10)
                if prev is None:
                    os.environ.pop("ORYX_RESOURCE_LEDGER", None)
                else:
                    os.environ["ORYX_RESOURCE_LEDGER"] = prev

        srv_on: list = []
        srv_off: list = []
        # an EVEN pair count keeps the alternating (on,off)/(off,on)
        # order positionally balanced against host drift
        for pair in range(((max(4, _TRIALS) + 1) // 2) * 2):
            for mode_on in (True, False) if pair % 2 == 0 else (False, True):
                (srv_on if mode_on else srv_off).append(serving_trial(mode_on))
    finally:
        layer.close()
    ratio_row("serving closed-loop", "queries/sec", srv_on, srv_off, order=45)

    if failures:
        raise RuntimeError(
            "resource ledger overhead above envelope: " + "; ".join(failures)
        )


def bench_serving_closed_loop() -> None:
    """Closed-loop /recommend latency through the REAL serving stack:
    ServingLayer HTTP server + ALS endpoints + request micro-batcher +
    device scan, driven by 1..3 SYNCHRONOUS clients (each waits for its
    response before sending the next request). Unlike the pipelined rows
    above — which measure device throughput with a deep submit queue —
    these are true per-request p50/p99 latencies, the number a single
    caller experiences, directly comparable to the reference's published
    437 qps / ~7 ms table (LSH 0.3, 32-core Xeon). Since ISSUE 18 the
    driver reuses persistent keep-alive connections (tools/traffic.py
    worker -> loadgen KeepAliveClient), so these rows re-measure the
    437-qps reference under the same protocol the native-front rows use:
    latency is the server's, not TCP setup's."""
    import threading
    import urllib.request

    import numpy as np

    from oryx_tpu.common import config as C
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_CL_USERS", 10_000))
    seconds = float(os.environ.get("ORYX_BENCH_CL_SECONDS", 6.0))
    backend, _, _ = _device_info()
    if backend != "tpu":
        # each request exact-scans the whole item matrix; on a CPU
        # container keep the model small enough that a trial finishes
        items = min(items, int(os.environ.get("ORYX_BENCH_CL_CPU_ITEMS", 200_000)))
        seconds = min(seconds, 4.0)

    cfg = C.get_default().with_overlay(
        """
        oryx {
          id = "BenchClosedLoop"
          input-topic.broker = "inproc://benchcl"
          update-topic.broker = "inproc://benchcl"
          serving {
            api.port = 0
            api.read-only = true
            model-manager-class = "tools.load_benchmark:LoadTestModelManager"
            application-resources = "oryx_tpu.app.als.endpoints"
          }
        }
        """
    )
    t0 = time.perf_counter()
    model = build_model(users, items, features)
    layer = ServingLayer(cfg)
    layer.start()
    layer.model_manager.model = model
    base = f"http://127.0.0.1:{layer.port}"
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"
    try:
        # warm request uploads Y to device and compiles the scan kernel
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()
        print(
            f"bench[serving-closed]: model+layer+warm in "
            f"{time.perf_counter() - t0:.1f}s ({users}u x {items}i x {features}f)",
            file=sys.stderr,
        )
        for clients, order in ((1, 94), (3, 95)):
            qps_trials: list[float] = []
            lats: list[float] = []
            errors: list[float] = []
            for _ in range(_TRIALS):
                trial_lats: list[float] = []
                stop = threading.Event()
                deadline = time.perf_counter() + seconds
                threads = [
                    threading.Thread(
                        target=worker,
                        args=(base, "/recommend/u%d", users, deadline,
                              trial_lats, errors, stop),
                        daemon=True,
                    )
                    for _ in range(clients)
                ]
                t1 = time.perf_counter()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                elapsed = time.perf_counter() - t1
                qps_trials.append(len(trial_lats) / max(elapsed, 1e-9))
                lats.extend(trial_lats)
            if not lats:
                raise RuntimeError(
                    f"closed-loop serving: no successful requests "
                    f"({len(errors)} errors)"
                )
            p50, p99 = np.percentile(np.array(lats) * 1000, [50, 99])
            qps, vs, tf = _rate_row(qps_trials, 437.0)
            detail = (
                f"true per-request HTTP latency: p50 {p50:.1f} ms / "
                f"p99 {p99:.1f} ms over {len(lats)} requests "
                f"({len(errors)} errors), {tf['trials']} x {seconds:.0f}s "
                f"trials; reference table: 437 qps / ~7 ms at LSH 0.3"
            )
            print(
                f"bench[serving-closed {clients} client(s)]: {detail}",
                file=sys.stderr,
            )
            _emit(
                f"ALS /recommend closed-loop, {clients} sync client(s), "
                f"{features}f x {label_m} items, vs 437 qps / 7 ms p50 "
                f"published (LSH 0.3, 32-core Xeon)",
                qps,
                "queries/sec",
                vs,
                order=order,
                detail=detail,
                p50_ms=float(p50),
                p99_ms=float(p99),
                clients=clients,
                **tf,
            )
    finally:
        layer.close()


def bench_native_front() -> None:
    """Native C++ HTTP front vs the Python front: the serving-latency
    identity rows (ISSUE 18). Two identically configured ServingLayers —
    one with ``oryx.serving.native.enabled = true``, one forced to the
    Python ``http.server`` front — share one prebuilt ALS model, and
    1/2/3 SYNCHRONOUS keep-alive clients drive each arm closed-loop with
    no pipeline co-tenancy, so p50/p99 are true per-request latencies of
    the data plane alone. Arms alternate order every trial (>= 3 trials,
    median/spread/NOISY protocol) so drift hits both equally.

    Two kinds of rows. The FORWARDED rows (orders 91-93) are the latency
    identity: /recommend full-quality requests travel the same Python
    dispatch on both arms (the native front forwards them as RBLK
    frames), so their ratio is ~1.0 by construction and the row proves
    the native plumbing adds nothing. The PAIRED-RATIO row (order 89)
    carries the acceptance floor — native/Python qps >= 1.5x — and is
    measured on the stale answer-cache rung (admission pinned at stage
    STALE over a primed cache): the same /recommend 200s, but answered
    entirely in C++ on one arm and through the Python ladder + cache on
    the other. That is the rung the native data plane exists for.
    Skips cleanly (no rows) when the toolchain is absent — the fallback
    environments serve through the Python front and the plain
    serving-closed rows already cover them."""
    import threading

    import numpy as np

    from oryx_tpu import native as native_mod
    from oryx_tpu.common import config as C
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    lib = native_mod.get_library()
    if lib is None or not hasattr(lib, "hf_create"):
        print("bench[serving-native]: skipped (native toolchain unavailable)",
              file=sys.stderr)
        return

    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_CL_USERS", 10_000))
    seconds = float(os.environ.get("ORYX_BENCH_CL_SECONDS", 6.0))
    backend, _, _ = _device_info()
    if backend != "tpu":
        items = min(items, int(os.environ.get("ORYX_BENCH_CL_CPU_ITEMS", 200_000)))
        seconds = min(seconds, 4.0)

    def make_layer(arm: str, enabled: str) -> ServingLayer:
        cfg = C.get_default().with_overlay(
            f"""
            oryx {{
              id = "BenchNativeFront"
              input-topic.broker = "inproc://benchnf-{arm}"
              update-topic.broker = "inproc://benchnf-{arm}"
              serving {{
                api.port = 0
                api.read-only = true
                model-manager-class = "tools.load_benchmark:LoadTestModelManager"
                application-resources = "oryx_tpu.app.als.endpoints"
                native.enabled = "{enabled}"
              }}
            }}
            """
        )
        return ServingLayer(cfg)

    t0 = time.perf_counter()
    model = build_model(users, items, features)
    arms = {"native": make_layer("native", "true"),
            "python": make_layer("python", "false")}
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"
    try:
        for name, layer in arms.items():
            layer.start()
            layer.model_manager.model = model
        if arms["native"]._native_front is None:
            print("bench[serving-native]: skipped (native front declined)",
                  file=sys.stderr)
            return
        from oryx_tpu.loadgen.engine import KeepAliveClient

        warm = KeepAliveClient(timeout_s=300)
        for layer in arms.values():
            status, _, _, _ = warm.request(
                f"http://127.0.0.1:{layer.port}/recommend/u0")
            assert status == 200, status
        warm.close()
        print(
            f"bench[serving-native]: model+2 layers+warm in "
            f"{time.perf_counter() - t0:.1f}s ({users}u x {items}i x "
            f"{features}f), arms: native :{arms['native'].port} / "
            f"python :{arms['python'].port}",
            file=sys.stderr,
        )

        def one_trial(layer, clients: int, n_users: int = users) -> tuple[float, list]:
            base = f"http://127.0.0.1:{layer.port}"
            lats: list = []
            errs: list = []
            stop = threading.Event()
            deadline = time.perf_counter() + seconds
            threads = [
                threading.Thread(
                    target=worker,
                    args=(base, "/recommend/u%d", n_users, deadline, lats,
                          errs, stop),
                    daemon=True,
                )
                for _ in range(clients)
            ]
            t1 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            elapsed = time.perf_counter() - t1
            if errs:
                raise RuntimeError(
                    f"serving-native trial errors ({clients} clients): "
                    f"{errs[:5]}"
                )
            return len(lats) / max(elapsed, 1e-9), lats

        floor = 1.5
        for clients, order in ((1, 91), (2, 92), (3, 93)):
            qps: dict = {"native": [], "python": []}
            lats: dict = {"native": [], "python": []}
            for trial in range(_TRIALS):
                # alternate which arm runs first so thermal / scheduler
                # drift lands on both arms equally
                order_names = (
                    ("native", "python") if trial % 2 == 0
                    else ("python", "native")
                )
                for name in order_names:
                    rate, trial_lats = one_trial(arms[name], clients)
                    qps[name].append(rate)
                    lats[name].extend(trial_lats)
            med_py = max(statistics.median(qps["python"]), 1e-9)
            ratios = [r / med_py for r in qps["native"]]
            p50n, p99n = np.percentile(np.array(lats["native"]) * 1000, [50, 99])
            p50p, p99p = np.percentile(np.array(lats["python"]) * 1000, [50, 99])
            value, vs, tf = _rate_row(qps["native"], 437.0)
            ratio = statistics.median(ratios)
            detail = (
                f"paired closed-loop arms, {clients} sync keep-alive "
                f"client(s): native {value:.0f} qps p50 {p50n:.1f} / "
                f"p99 {p99n:.1f} ms vs python {med_py:.0f} qps p50 "
                f"{p50p:.1f} / p99 {p99p:.1f} ms ({tf['trials']} x "
                f"{seconds:.0f}s trials per arm, interleaved); "
                f"native/python {ratio:.2f}x; reference 437 qps / ~7 ms"
            )
            print(f"bench[serving-native {clients} client(s)]: {detail}",
                  file=sys.stderr)
            _emit(
                f"native-front closed-loop, {clients} sync client(s), "
                f"{features}f x {label_m} items, vs 437 qps published",
                value,
                "queries/sec",
                vs,
                order=order,
                detail=detail,
                p50_ms=float(p50n),
                p99_ms=float(p99n),
                python_qps=round(med_py, 2),
                python_p50_ms=float(p50p),
                python_p99_ms=float(p99p),
                front_ratio=round(ratio, 3),
                clients=clients,
                **tf,
            )
        # --- the acceptance row: stale answer-cache rung, paired arms -------
        # Pin admission at STAGE_STALE over a primed cache so every
        # /recommend is a champion-gated cache hit: C++ template on the
        # native arm, Python ladder + AnswerCache on the other. Same 200
        # bytes (byte-parity suite), very different data planes.
        hot_users = 64
        prime = KeepAliveClient(timeout_s=300)
        for layer in arms.values():
            layer.health.live_generation = "bench-gen"
            adm = layer.admission
            # freeze the ladder: evaluate() keeps returning the pinned stage
            adm.evaluate = (lambda a: (lambda *x, **k: a._stage))(adm)
            for u in range(hot_users):
                status, _, _, _ = prime.request(
                    f"http://127.0.0.1:{layer.port}/recommend/u{u}")
                assert status == 200, status
        prime.close()
        for layer in arms.values():
            layer.admission._stage = 2  # STAGE_STALE
        arms["native"]._native_front.push_control()  # mirror cache + stage

        clients = 3
        qps = {"native": [], "python": []}
        lats = {"native": [], "python": []}
        for trial in range(_TRIALS):
            order_names = (
                ("native", "python") if trial % 2 == 0
                else ("python", "native")
            )
            for name in order_names:
                rate, trial_lats = one_trial(arms[name], clients,
                                             n_users=hot_users)
                qps[name].append(rate)
                lats[name].extend(trial_lats)
        med_py = max(statistics.median(qps["python"]), 1e-9)
        ratios = [r / med_py for r in qps["native"]]
        ratio_med = statistics.median(ratios)
        p50n, p99n = np.percentile(np.array(lats["native"]) * 1000, [50, 99])
        p50p, p99p = np.percentile(np.array(lats["python"]) * 1000, [50, 99])
        tf = _trial_fields(ratios, [r / floor for r in ratios])
        detail = (
            f"stale answer-cache rung (admission pinned at stage stale, "
            f"{hot_users} hot keys primed), {clients} sync keep-alive "
            f"clients: native {statistics.median(qps['native']):.0f} qps "
            f"p50 {p50n:.2f} / p99 {p99n:.2f} ms vs python {med_py:.0f} "
            f"qps p50 {p50p:.2f} / p99 {p99p:.2f} ms; ratio {ratio_med:.2f}x "
            f"(floor {floor}x; per-trial {[round(r, 2) for r in ratios]})"
        )
        print(f"bench[serving-native ratio]: {detail}", file=sys.stderr)
        _emit(
            "native-front vs python-front paired qps, stale-rung "
            f"/recommend, 3 clients (vs_baseline = ratio/{floor} floor)",
            ratio_med,
            "x python-front qps",
            ratio_med / floor,
            order=89,
            detail=detail,
            native_qps=round(statistics.median(qps["native"]), 2),
            python_qps=round(med_py, 2),
            p50_ms=float(p50n),
            p99_ms=float(p99n),
            python_p50_ms=float(p50p),
            python_p99_ms=float(p99p),
            **tf,
        )
    finally:
        for layer in arms.values():
            layer.close()


def bench_serving_open_loop() -> None:
    """OPEN-loop serving rows: arrivals fire on their own Poisson clock
    regardless of outstanding responses, so offered vs achieved rate and
    queue-inclusive p99 are measured the way production traffic would
    experience them (closed-loop rows above can never show queueing —
    the generator slows down with the server). Three rows: steady state
    at 1 and 3 replicas, then the rotation row — a scripted generation
    publish + chaos window + rollback mid-run at a held offered rate,
    with the failed-request count in the row (0 = zero-downtime held)."""
    import tempfile

    from oryx_tpu.loadgen import OpenLoopEngine, PoissonProcess, PowerLawUsers
    from tools.fleet import FleetHarness, default_scenario, run_scenario

    rate = float(os.environ.get("ORYX_BENCH_OL_RATE", 150.0))
    seconds = float(os.environ.get("ORYX_BENCH_OL_SECONDS", 6.0))
    n_users = int(os.environ.get("ORYX_BENCH_OL_USERS", 2_000_000))

    for replicas, order in ((1, 96), (3, 97)):
        with tempfile.TemporaryDirectory() as tmp:
            with FleetHarness(replicas, tmp, bus_name=f"benchol{replicas}") as fleet:
                first = fleet.publish(metric=0.90)
                if not fleet.wait_converged(first, timeout=30.0):
                    raise RuntimeError("open-loop bench: fleet never converged")
                engine = OpenLoopEngine(fleet.targets, template="/probe/recommend/u%d")
                result = engine.run(
                    PoissonProcess(rate=rate, seed=7),
                    PowerLawUsers(n_users, exponent=1.1, hot_count=16,
                                  hot_weight=0.2, seed=7),
                    seconds,
                )
        s = result.summary()
        detail = (
            f"open-loop Poisson {s['offered_rate']:.0f} rps offered over "
            f"{seconds:.0f}s, {replicas} replica(s): achieved "
            f"{s['achieved_rate']:.0f} rps, p50 {s['p50_ms']:.1f} ms / "
            f"queue-inclusive p99 {s['p99_ms']:.1f} ms (service p99 "
            f"{s['service_p99_ms']:.1f} ms), {s['failed']} failed, "
            f"{s['queued_arrivals']} queued arrivals"
        )
        print(f"bench[serving-open {replicas}r]: {detail}", file=sys.stderr)
        _emit(
            f"open-loop serving, {replicas} replica(s), Poisson "
            f"{rate:.0f} rps offered, power-law users (achieved rate; "
            f"vs_baseline = achieved/offered, 1.0 = kept up)",
            s["achieved_rate"],
            "requests/sec",
            s["achieved_rate"] / max(s["offered_rate"], 1e-9),
            order=order,
            detail=detail,
            p50_ms=s["p50_ms"],
            p99_ms=s["p99_ms"],
            service_p99_ms=s["service_p99_ms"],
            offered_rate=s["offered_rate"],
            failed=s["failed"],
            queued_arrivals=s["queued_arrivals"],
            replicas=replicas,
        )

    # rotation under load: publish + chaos + rollback mid-run, 3 replicas
    with tempfile.TemporaryDirectory() as tmp:
        with FleetHarness(3, tmp, bus_name="bencholrot") as fleet:
            first = fleet.publish(metric=0.90)
            if not fleet.wait_converged(first, timeout=30.0):
                raise RuntimeError("open-loop bench: fleet never converged")
            scenario = default_scenario(rate=rate, seconds=max(seconds, 8.0))
            result, verdict, _runner = run_scenario(fleet, scenario)
            converged = fleet.wait_converged(fleet.generations[-1], timeout=15.0)
    s = result.summary()
    detail = (
        f"generation rotation under load (publish + chaos window + "
        f"rollback mid-run, 3 replicas, {s['offered_rate']:.0f} rps "
        f"offered): achieved {s['achieved_rate']:.0f} rps, p99 "
        f"{s['p99_ms']:.1f} ms, {s['failed']} failed request(s), SLO "
        f"{'PASS' if verdict.passed else 'FAIL ' + '; '.join(verdict.violations)}, "
        f"fleet {'re-converged' if converged else 'DID NOT re-converge'}"
    )
    print(f"bench[serving-open rotation]: {detail}", file=sys.stderr)
    _emit(
        "open-loop rotation-under-load, 3 replicas: publish + chaos + "
        "rollback mid-run at held offered rate (achieved rate; "
        "vs_baseline = achieved/offered with zero failures required)",
        s["achieved_rate"],
        "requests/sec",
        (s["achieved_rate"] / max(s["offered_rate"], 1e-9))
        if s["failed"] == 0 and verdict.passed
        else 0.0,
        order=98,
        detail=detail,
        p99_ms=s["p99_ms"],
        offered_rate=s["offered_rate"],
        failed=s["failed"],
        slo_passed=verdict.passed,
        converged=converged,
        replicas=3,
    )


def bench_overload() -> None:
    """Overload-control acceptance rows (docs/overload.md). Two halves:

    - idle admission overhead: closed-loop serving qps through one warm
      layer with the admission controller wired vs bypassed — the
      per-request decide() cost at calm pressure must stay <= 2%
      (median AND best below the 0.98 envelope hard-fails; median-only
      misses are flagged `noise-suspect` per the repo's noise protocol);
    - 10x Poisson spike over a 3-replica fleet with 60 ms scripted probe
      work (saturation is then a function of offered rate alone —
      Little's law — deterministic on a single-core host): offered vs
      answered rate, queue-inclusive p99, per-stage shed fractions, zero
      failed requests and zero 5xx required, plus the seconds until every
      replica answers at full quality again after the spike ends."""
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.loadgen import OpenLoopEngine, PoissonProcess, PowerLawUsers
    from oryx_tpu.serving.layer import ServingLayer
    from oryx_tpu.serving.overload import SHED_HEADER
    from tools.fleet import FleetHarness
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_OVERLOAD_ENVELOPE", 0.98))
    failures: list[str] = []

    # --- idle overhead: admission wired vs bypassed, one warm layer -------
    items = int(os.environ.get("ORYX_BENCH_OVERLOAD_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_OVERLOAD_SECONDS", 4.0))
    cfg = C.get_default().with_overlay(
        """
        oryx {
          id = "BenchOverload"
          input-topic.broker = "inproc://benchovl"
          update-topic.broker = "inproc://benchovl"
          serving {
            api.port = 0
            api.read-only = true
            model-manager-class = "tools.load_benchmark:LoadTestModelManager"
            application-resources = "oryx_tpu.app.als.endpoints"
          }
        }
        """
    )
    layer = ServingLayer(cfg)
    layer.start()
    layer.model_manager.model = build_model(users, items, 50)
    base = f"http://127.0.0.1:{layer.port}"
    admission = layer.admission
    if admission is None:
        raise RuntimeError("bench overload: admission controller not enabled")
    try:
        urllib.request.urlopen(f"{base}/recommend/u0", timeout=300).read()

        def one_trial(wired: bool) -> float:
            # _admit_and_route reads layer.admission per request, so this
            # is the exact operator toggle (oryx.serving.overload.enabled)
            layer.admission = admission if wired else None
            lats: list = []
            stop = threading.Event()
            deadline = time.perf_counter() + seconds
            t1 = time.perf_counter()
            worker(base, "/recommend/u%d", users, deadline, lats, [], stop)
            if not lats:
                raise RuntimeError("bench overload: no requests completed")
            return len(lats) / (time.perf_counter() - t1)

        # interleave wired/bypassed pairs, alternating order, so the slow
        # single-core throughput drift over a long run cancels instead of
        # landing entirely on one arm
        on: list = []
        off: list = []
        for i in range(_TRIALS):
            if i % 2 == 0:
                on.append(one_trial(True))
                off.append(one_trial(False))
            else:
                off.append(one_trial(False))
                on.append(one_trial(True))
    finally:
        layer.admission = admission
        layer.close()

    med_on = statistics.median(on)
    med_off = max(statistics.median(off), 1e-9)
    ratio = med_on / med_off
    best = max(on) / med_off
    detail = (
        f"admission wired {med_on:.0f} vs bypassed {med_off:.0f} queries/sec "
        f"(medians of {len(on)}/{len(off)} trials), overhead "
        f"{100 * (1 - ratio):.2f}%, envelope <= {100 * (1 - envelope):.0f}%"
    )
    print(f"bench[overload idle]: {detail}", file=sys.stderr)
    _emit(
        "overload admission idle overhead, closed-loop serving, controller "
        f"wired vs bypassed (vs_baseline = wired/bypassed ratio, floor "
        f"{envelope})",
        med_on,
        "queries/sec",
        ratio,
        order=43,
        detail=detail,
        off_value=round(med_off, 2),
        overhead_pct=round(100 * (1 - ratio), 3),
        noise_suspect=ratio < envelope <= best,
        spread=[round(float(min(on)), 2), round(float(max(on)), 2)],
        trials=len(on),
    )
    if ratio < envelope and best < envelope:
        failures.append(f"idle overhead: wired/bypassed {ratio:.4f} < {envelope}")

    # --- 10x spike over 3 replicas, scripted 60 ms probe work -------------
    base_rate = float(os.environ.get("ORYX_BENCH_OVERLOAD_BASE_RATE", 25.0))
    spike_rate = 10.0 * base_rate
    recovery_cap_s = 20.0
    recovery_budget_s = 10.0
    # same tuning as test_spike_absorbed_by_staged_shedding_zero_5xx: the
    # tightened ladder knobs let the controller walk rungs within the
    # few-second phases of one trial
    overlay = """
        oryx {
          serving.overload {
            inflight-target = 4
            hold-s = 0.2
            control-interval-ms = 25
            alpha = 0.5
          }
          test.probe-work-ms = 60
        }
        """

    def fivexx_total(fleet) -> float:
        total = 0.0
        for replica in fleet.replicas:
            snap = replica.instance_metrics.snapshot()
            entry = snap.get("serving.responses.5xx") or {}
            total += float(entry.get("value") or 0.0)
        return total

    trials: list[dict] = []
    for t in range(_TRIALS):
        with tempfile.TemporaryDirectory() as tmp:
            with FleetHarness(
                3, tmp, bus_name=f"benchovl{t}", overlay=overlay
            ) as fleet:
                gen = fleet.publish(metric=0.90)
                if not fleet.wait_converged(gen, timeout=30.0):
                    raise RuntimeError("bench overload: fleet never converged")

                def run_phase(rate, secs, seed):
                    engine = OpenLoopEngine(
                        fleet.targets,
                        template="/probe/recommend/u%d",
                        readiness_poll_s=0.1,
                    )
                    return engine.run(
                        PoissonProcess(rate=rate, seed=seed),
                        PowerLawUsers(100_000, seed=seed),
                        secs,
                    )

                baseline = run_phase(base_rate, 2.0, seed=31 + t)
                spike = run_phase(spike_rate, 2.5, seed=47 + t)

                # recovery: seconds from spike end until every replica
                # answers 3 straight probes at full quality (no shed
                # header, no 429) — the probes themselves drive the
                # controllers' release evaluations
                t0 = time.perf_counter()
                recovery_s = recovery_cap_s
                streak = 0
                while time.perf_counter() - t0 < recovery_cap_s:
                    full = True
                    for target in fleet.targets:
                        try:
                            with urllib.request.urlopen(
                                target.base_url + "/probe/recommend/u1",
                                timeout=10,
                            ) as resp:
                                resp.read()
                                if resp.headers.get(SHED_HEADER):
                                    full = False
                        except urllib.error.HTTPError:
                            full = False
                    streak = streak + 1 if full else 0
                    if streak >= 3:
                        recovery_s = time.perf_counter() - t0
                        break
                    time.sleep(0.05)

                q = spike.quality()
                trials.append(
                    {
                        "answered_qps": (spike.ok + spike.shed)
                        / max(spike.duration_s, 1e-9),
                        "offered_qps": spike.offered_rate,
                        "p99_ms": spike.latency_quantile(0.99) * 1000.0,
                        "failed": baseline.failed + spike.failed,
                        "fivexx": fivexx_total(fleet),
                        "q_full": q["full"],
                        "q_reduced": q["reduced-probe"],
                        "q_stale": q["stale"],
                        "q_shed": q["shed"],
                        "recovery_s": recovery_s,
                    }
                )

    med = _median_run(trials, "answered_qps")
    answered = [r["answered_qps"] for r in trials]
    clean = med["failed"] == 0 and med["fivexx"] == 0
    detail = (
        f"10x Poisson spike over 3 replicas ({med['offered_qps']:.0f} rps "
        f"offered, 60 ms scripted probe work): answered "
        f"{med['answered_qps']:.0f} rps (ok + deliberate 429 sheds), "
        f"queue-inclusive p99 {med['p99_ms']:.0f} ms, quality "
        f"full/reduced/stale/shed = {med['q_full']:.2f}/{med['q_reduced']:.2f}"
        f"/{med['q_stale']:.2f}/{med['q_shed']:.2f}, "
        f"{int(med['failed'])} failed, {int(med['fivexx'])} 5xx"
    )
    print(f"bench[overload spike]: {detail}", file=sys.stderr)
    _emit(
        "overload 10x spike, 3 replicas: answered rate under staged "
        "shedding (vs_baseline = answered/offered with zero failures and "
        "zero 5xx required)",
        med["answered_qps"],
        "responses/sec",
        (med["answered_qps"] / max(med["offered_qps"], 1e-9)) if clean else 0.0,
        order=44,
        detail=detail,
        offered_rate=med["offered_qps"],
        p99_ms=med["p99_ms"],
        quality_full=med["q_full"],
        quality_reduced_probe=med["q_reduced"],
        quality_stale=med["q_stale"],
        quality_shed=med["q_shed"],
        failed=int(med["failed"]),
        responses_5xx=int(med["fivexx"]),
        replicas=3,
        spread=[round(min(answered), 2), round(max(answered), 2)],
        trials=len(trials),
    )
    for r in trials:
        if r["failed"] or r["fivexx"]:
            failures.append(
                f"spike trial: {int(r['failed'])} failed, "
                f"{int(r['fivexx'])} 5xx (both must be 0)"
            )
    if med["q_full"] >= 1.0:
        failures.append("spike: shed ladder never engaged (quality full = 1.0)")

    recs = [r["recovery_s"] for r in trials]
    med_rec = statistics.median(recs)
    detail = (
        f"seconds from spike end until all 3 replicas answer 3 straight "
        f"probes at full quality: median {med_rec:.2f}s over {len(recs)} "
        f"trials (budget {recovery_budget_s:.0f}s, poll cap {recovery_cap_s:.0f}s)"
    )
    print(f"bench[overload recovery]: {detail}", file=sys.stderr)
    _emit(
        "overload recovery after 10x spike: seconds until every replica "
        f"answers at full quality again (vs_baseline = {recovery_budget_s:.0f}s "
        "budget / measured, >= 1.0 = inside budget)",
        med_rec,
        "seconds",
        recovery_budget_s / max(med_rec, 1e-9),
        order=45,
        detail=detail,
        spread=[round(min(recs), 2), round(max(recs), 2)],
        trials=len(recs),
    )
    if med_rec > recovery_budget_s:
        failures.append(f"recovery {med_rec:.2f}s > {recovery_budget_s:.0f}s budget")

    if failures:
        raise RuntimeError("overload bench failed: " + "; ".join(failures))


def bench_crash_recovery() -> None:
    """Crash-recovery row: 3 subprocess replicas under open-loop load, one
    SIGKILLed mid-run (no drain). Value = SIGKILL->/readyz recovery time
    of the killed slot (respawn + restage-cache repair + update-topic
    replay); vs_baseline = budget/recovery (>1.0 = inside budget), gated
    to 0.0 unless the surviving fleet held the SLO with zero failed
    requests — the zero-downtime claim is part of the metric."""
    import tempfile

    from tools.fleet import run_crash_campaign

    rate = float(os.environ.get("ORYX_BENCH_CRASH_RATE", 150.0))
    seconds = float(os.environ.get("ORYX_BENCH_CRASH_SECONDS", 8.0))
    budget_s = float(os.environ.get("ORYX_BENCH_CRASH_BUDGET_S", 30.0))

    with tempfile.TemporaryDirectory() as tmp:
        report = run_crash_campaign(
            3, rate, seconds, tmp, recovery_budget_s=budget_s
        )
    recovery_s = max(report["recovery_seconds"], default=float("nan"))
    clean = report["failed"] == 0 and report["slo"]["passed"]
    detail = (
        f"one SIGKILL at 35% of a {seconds:.0f}s open-loop run, "
        f"{report['offered_rate']:.0f} rps offered over 3 replicas: "
        f"recovery {recovery_s:.2f}s (budget {budget_s:.0f}s), "
        f"{report['failed']} failed request(s), {report['retried']} "
        f"failed over to survivors, p99 {report['p99_ms']:.1f} ms, SLO "
        f"{'PASS' if report['slo']['passed'] else 'FAIL ' + '; '.join(report['slo']['violations'])}"
    )
    print(f"bench[crash-recovery]: {detail}", file=sys.stderr)
    _emit(
        "crash-recovery, 3 replicas open-loop, one SIGKILL mid-run: "
        "killed-slot SIGKILL->/readyz seconds, vs 30s budget "
        "(vs_baseline = budget/recovery, 0.0 unless zero failed + SLO held)",
        recovery_s,
        "sec",
        (budget_s / recovery_s) if clean and recovery_s > 0 else 0.0,
        order=99,
        detail=detail,
        p99_ms=report["p99_ms"],
        offered_rate=report["offered_rate"],
        failed=report["failed"],
        retried=report["retried"],
        slo_passed=report["slo"]["passed"],
        recovery_budget_s=budget_s,
        replicas=3,
    )


def bench_tenancy_overhead() -> None:
    """Multi-tenancy cost acceptance rows (docs/multi-tenancy.md).

    Row 1 — single-tenant overhead: the tenancy plumbing (tenant
    resolution from the /t/ prefix, the request-scoped ContextVar, the
    TenantServingMux attribute forwarding, per-tenant metric twins) must
    cost <= 2% on the serving hot path when only ONE tenant exists —
    the price of *being able* to multi-tenant, paid by deployments that
    don't. Protocol: two live layers in one process (one with a
    single-tenant `oryx.tenancy` block, one with tenancy absent),
    >= 3 closed-loop trial PAIRS in alternating order; the statistic is
    the median of per-pair on/off ratios — host drift on this class of
    machine is +-10% between trials but near-zero within an adjacent
    pair, so pairing cancels it (server-side handler timing puts the
    true plumbing cost at ~8us on a ~2ms request). A median-AND-best
    pair-ratio miss below 0.98 hard-fails, a median-only miss flags
    `noise-suspect`.

    Row 2 — noisy-neighbour fairness: deterministic arrivals through the
    batcher's DRR queue. An attacker tenant parks a deep backlog, a
    victim tenant's entries arrive steadily, one consumer drains at a
    fixed per-entry service time. With DRR on (tenanted entries, equal
    weights) the victim's queue-wait p99 is bounded by one quantum
    rotation; with DRR off (untenanted entries, FIFO-equivalent path
    through the SAME queue class) every victim entry waits behind the
    whole backlog. vs_baseline = fifo_p99/drr_p99 (improvement factor);
    < 5x hard-fails — the fairness mechanism, not the scheduler, must
    be doing the work."""
    import shutil
    import tempfile
    import threading
    import urllib.request

    from oryx_tpu.common import config as C
    from oryx_tpu.serving.layer import ServingLayer
    from tools.load_benchmark import build_model
    from tools.traffic import worker

    envelope = float(os.environ.get("ORYX_BENCH_TENANCY_ENVELOPE", 0.98))
    failures: list[str] = []

    items = int(os.environ.get("ORYX_BENCH_TENANCY_ITEMS", 200_000))
    users = 10_000
    seconds = float(os.environ.get("ORYX_BENCH_TENANCY_SECONDS", 4.0))
    model_dir = tempfile.mkdtemp(prefix="oryx-bench-tenancy-")

    def overlay(tenanted: bool) -> object:
        tenancy = (
            """
              tenancy {
                enabled = true
                default-tenant = t0
                tenants.t0 = {
                  app = als
                  serving-manager = "tools.load_benchmark:LoadTestModelManager"
                }
              }
            """
            if tenanted
            else """
              serving.model-manager-class = "tools.load_benchmark:LoadTestModelManager"
              serving.application-resources = "oryx_tpu.app.als.endpoints"
            """
        )
        return C.get_default().with_overlay(
            f"""
            oryx {{
              id = "BenchTenancyOverhead"
              update-topic.broker = "inproc://benchtenancy"
              batch.storage.model-dir = "{model_dir}"
              serving {{
                api.port = 0
                api.read-only = true
              }}
              {tenancy}
            }}
            """
        )

    def make_layer(tenanted: bool) -> tuple:
        layer = ServingLayer(overlay(tenanted))
        layer.start()
        if tenanted:
            manager = layer.tenant_mux.runtime("t0").manager
        else:
            manager = layer.model_manager
        manager.model = build_model(users, items, 50)
        base = f"http://127.0.0.1:{layer.port}"
        template = "/t/t0/recommend/u%d" if tenanted else "/recommend/u%d"
        urllib.request.urlopen(base + template % 0, timeout=300).read()
        return layer, base, template

    def serving_trial(base: str, template: str) -> float:
        lats: list = []
        stop = threading.Event()
        deadline = time.perf_counter() + seconds
        t1 = time.perf_counter()
        worker(base, template, users, deadline, lats, [], stop)
        if not lats:
            raise RuntimeError("tenancy-overhead serving: no requests")
        return len(lats) / (time.perf_counter() - t1)

    off_layer, off_base, off_tmpl = make_layer(tenanted=False)
    try:
        on_layer, on_base, on_tmpl = make_layer(tenanted=True)
        try:
            if on_layer.tenant_mux is None or on_layer.tenant_mux.ids() != ["t0"]:
                raise RuntimeError("tenancy-overhead: tenancy failed to activate")
            srv_on: list = []
            srv_off: list = []
            pair_ratios: list = []
            for pair in range(_TRIALS):
                rates = {}
                for mode_on in (True, False) if pair % 2 == 0 else (False, True):
                    rates[mode_on] = serving_trial(
                        on_base if mode_on else off_base,
                        on_tmpl if mode_on else off_tmpl,
                    )
                srv_on.append(rates[True])
                srv_off.append(rates[False])
                pair_ratios.append(rates[True] / max(rates[False], 1e-9))
        finally:
            on_layer.close()
    finally:
        off_layer.close()
        shutil.rmtree(model_dir, ignore_errors=True)

    med_on = statistics.median(srv_on)
    med_off = max(statistics.median(srv_off), 1e-9)
    ratio = statistics.median(pair_ratios)
    best = max(pair_ratios)
    detail = (
        f"single tenant wired {med_on:.0f} vs tenancy absent {med_off:.0f} "
        f"queries/sec, per-pair on/off ratios "
        f"{[round(r, 4) for r in pair_ratios]} (median {ratio:.4f}), "
        f"overhead {100 * (1 - ratio):.2f}%, envelope <= "
        f"{100 * (1 - envelope):.0f}%"
    )
    print(f"bench[tenancy-overhead serving]: {detail}", file=sys.stderr)
    _emit(
        "multi-tenancy overhead, serving closed-loop, single tenant wired "
        f"(/t/ prefix + mux + per-tenant metrics) vs tenancy absent "
        f"(vs_baseline = median per-pair on/off ratio, floor {envelope})",
        med_on,
        "queries/sec",
        ratio,
        order=48,
        detail=detail,
        off_value=round(med_off, 2),
        overhead_pct=round(100 * (1 - ratio), 3),
        noise_suspect=ratio < envelope <= best,
        spread=[round(float(min(srv_on)), 2), round(float(max(srv_on)), 2)],
        trials=len(srv_on),
    )
    if ratio < envelope and best < envelope:
        failures.append(f"serving closed-loop: on/off {ratio:.4f} < {envelope}")

    # -- row 2: noisy-neighbour victim queue-wait p99, DRR on vs off ------
    from oryx_tpu.serving.batcher import _Entry, _FairQueue

    backlog = int(os.environ.get("ORYX_BENCH_TENANCY_BACKLOG", 2000))
    victims = 200
    service_s = 50e-6  # fixed per-entry service time (busy-wait, not sleep)
    arrival_s = 0.002  # one victim entry every 2 ms

    def victim_wait_p99(drr: bool) -> float:
        q = _FairQueue({"attacker": 1.0, "victim": 1.0} if drr else None)
        waits: dict[str, list[float]] = {"attacker": [], "victim": []}
        drained = threading.Event()

        def enq(tenant: str) -> None:
            e = _Entry(None, None, 1, False)
            e.tenant = tenant if drr else None
            e.t_q = time.perf_counter()
            # label rides the entry even when untenanted so the drain
            # loop attributes the wait to the right victim/attacker list
            e.trace_ctx = tenant
            q.put(e)

        def drain() -> None:
            served = 0
            while served < backlog + victims:
                e = q.get()
                waits[e.trace_ctx].append(time.perf_counter() - e.t_q)
                served += 1
                t_end = time.perf_counter() + service_s
                while time.perf_counter() < t_end:
                    pass
            drained.set()

        for _ in range(backlog):
            enq("attacker")
        consumer = threading.Thread(target=drain, daemon=True)
        consumer.start()
        for i in range(victims):
            enq("victim")
            time.sleep(arrival_s)
        if not drained.wait(timeout=60.0):
            raise RuntimeError("tenancy-overhead DRR drain did not finish")
        consumer.join()
        v = sorted(waits["victim"])
        return v[min(len(v) - 1, int(0.99 * len(v)))] * 1000.0

    drr_p99_ms = victim_wait_p99(drr=True)
    fifo_p99_ms = victim_wait_p99(drr=False)
    improvement = fifo_p99_ms / max(drr_p99_ms, 1e-9)
    detail = (
        f"victim queue-wait p99 {drr_p99_ms:.2f} ms with DRR vs "
        f"{fifo_p99_ms:.2f} ms FIFO ({backlog}-entry attacker backlog, "
        f"{victims} victim arrivals @ {1 / arrival_s:.0f}/s, "
        f"{service_s * 1e6:.0f}us service): {improvement:.0f}x better"
    )
    print(f"bench[tenancy-overhead drr]: {detail}", file=sys.stderr)
    _emit(
        "noisy-neighbour victim queue-wait p99, DRR fair queue vs FIFO "
        f"under a {backlog}-entry attacker backlog "
        "(vs_baseline = fifo_p99/drr_p99 improvement, floor 5x)",
        drr_p99_ms,
        "ms",
        improvement,
        order=49,
        detail=detail,
        fifo_p99_ms=round(fifo_p99_ms, 2),
        attacker_backlog=backlog,
        victim_arrivals=victims,
    )
    if improvement < 5.0:
        failures.append(
            f"DRR victim p99 {drr_p99_ms:.2f} ms only {improvement:.1f}x "
            f"better than FIFO {fifo_p99_ms:.2f} ms"
        )

    if failures:
        raise RuntimeError(
            "tenancy acceptance failed: " + "; ".join(failures)
        )


def bench_serving_maintain() -> None:
    """Always-fresh ANN maintenance acceptance rows at the >=10M-item
    shape: steady-state qps + per-dispatch p99 of the probed IVF scan
    while a continuous fold-in stream AND the background IndexMaintainer
    (snapshot -> compact_ivf -> install) run against the same index,
    next to a no-maintenance baseline measured first on the same
    catalog. Acceptance: p99 under maintenance within 1.5x the baseline
    p99 (median AND best of >= 3 trials must miss before the row
    hard-fails; a median-only miss is `noise-suspect` per the repo's
    noise protocol), ZERO full re-clusters on any path (build_ivf is
    wrapped and counted for the whole measured window), plus a
    freshness-seconds row (fold-in -> clustered-visibility lag the
    maintainer observed) and a recall@10 row against the exact f32
    ranking over the union catalog after the final drain."""
    import threading

    import numpy as np

    from oryx_tpu.common import metrics
    from oryx_tpu.ops import ivf as ivf_ops
    from oryx_tpu.serving import maintain as maintain_mod

    items = int(os.environ.get("ORYX_BENCH_MAINTAIN_ITEMS", 10_000_000))
    features = int(os.environ.get("ORYX_BENCH_MAINTAIN_FEATURES", 50))
    batch = int(os.environ.get("ORYX_BENCH_ANN_BATCH", 256))
    seconds = float(os.environ.get("ORYX_BENCH_MAINTAIN_SECONDS", 6.0))
    interval = float(os.environ.get("ORYX_BENCH_MAINTAIN_INTERVAL", 1.0))
    fold_rate = float(os.environ.get("ORYX_BENCH_MAINTAIN_RATE", 1000.0))
    fresh_budget = float(os.environ.get("ORYX_BENCH_MAINTAIN_FRESH_BUDGET", 10.0))
    how_many = 10
    cells = max(64, int(round(items**0.5 / 8)) * 8)
    nprobe = max(8, int(round(0.0025 * cells)))
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"

    mat, queries = _ann_mixture(items, features, cells, 7117, batch)
    old_qb = ivf_ops.QUERY_BLOCK
    ivf_ops.configure_ann(query_block=4)
    t0 = time.perf_counter()
    index = ivf_ops.build_ivf(mat, n_cells=cells, seed=7, overlay_capacity=2048)
    build_sec = time.perf_counter() - t0
    print(
        f"bench[serving-maintain {features}f x {label_m}]: build_ivf "
        f"{build_sec:.0f}s ({index.n_cells} cells, nprobe {nprobe})",
        file=sys.stderr,
    )

    lock = threading.Lock()
    holder = {"index": index}

    class _OpsModel:
        """ops-level maintenance protocol (the serving-model half of
        serving/maintain.py's contract) over a plain index holder."""

        def set_index_pressure_callback(self, cb):
            self._cb = cb

        def maintenance_snapshot(self, watermark, force=False):
            with lock:
                idx = holder["index"]
                if not force and not ivf_ops.needs_maintenance(idx, watermark=watermark):
                    return None
                return idx, ivf_ops.snapshot_pending(idx)

        def install_compacted(self, new_index, stats):
            with lock:
                cur = holder["index"]
                snap_born = stats.get("born") or {}
                feat = new_index.features
                rids, raws = [], []
                for item, slot in (cur.ov_map or {}).items():
                    b = (cur.ov_born or {}).get(item, 0.0)
                    if item not in snap_born or b > snap_born[item]:
                        rids.append(item)
                        raws.append(np.asarray(cur.ov_raw_host[slot][:feat], np.float32))
                for item, (raw, b) in (cur.pending_spill or {}).items():
                    if item not in snap_born or b > snap_born[item]:
                        rids.append(item)
                        raws.append(np.asarray(raw[:feat], np.float32))
                if rids:
                    new_index = ivf_ops.update_rows(
                        new_index, np.asarray(rids, np.int64), np.stack(raws)
                    )
                    stats["replayed"] = len(rids)
                holder["index"] = new_index
                return True

    def run_trials(tag: str) -> tuple[list, list, list]:
        """(per-trial qps, per-trial p99 ms, all walls) over _TRIALS
        `seconds`-long passes of batch dispatches on the live index."""
        qps_t, p99_t, walls_all = [], [], []
        ivf_ops.top_k(holder["index"], queries, how_many, nprobe=nprobe)  # warm
        for _ in range(_TRIALS):
            walls = []
            start = time.perf_counter()
            deadline = start + seconds
            served = 0
            while time.perf_counter() < deadline:
                td = time.perf_counter()
                ivf_ops.top_k(holder["index"], queries, how_many, nprobe=nprobe)
                walls.append(time.perf_counter() - td)
                served += batch
            qps_t.append(served / (time.perf_counter() - start))
            p99_t.append(float(np.percentile(np.array(walls) * 1000.0, 99)))
            walls_all.extend(walls)
        print(
            f"bench[serving-maintain]: {tag} qps {statistics.median(qps_t):.0f}, "
            f"p99 {statistics.median(p99_t):.1f} ms",
            file=sys.stderr,
        )
        return qps_t, p99_t, walls_all

    # phase A: no fold-ins, no maintainer — the baseline the 1.5x bound frames
    base_qps_t, base_p99_t, _ = run_trials("baseline")
    base_qps = statistics.median(base_qps_t)
    base_p99 = statistics.median(base_p99_t)

    # full-re-cluster tripwire: the request path and the maintenance loop
    # must never call build_ivf during the measured window
    real_build = ivf_ops.build_ivf
    recluster = [0]

    def counting_build(*a, **k):
        recluster[0] += 1
        return real_build(*a, **k)

    ivf_ops.build_ivf = counting_build
    folded_log: dict[int, np.ndarray] = {}
    fresh_samples: list[float] = []
    stop = threading.Event()
    model = _OpsModel()
    maint = maintain_mod.IndexMaintainer(
        lambda: model, interval_sec=interval, watermark=0.5, seed=11
    )

    def fold_loop():
        gen = np.random.default_rng(99)
        next_id = len(mat)
        seen = maint.compactions
        while not stop.is_set():
            vals = (
                mat[gen.integers(0, len(mat), 64)]
                + 0.1 * gen.standard_normal((64, features)).astype(np.float32)
            ).astype(np.float32)
            ids = np.arange(next_id, next_id + 64, dtype=np.int64)
            next_id += 64
            with lock:
                holder["index"] = ivf_ops.update_rows(holder["index"], ids, vals)
            for i, v in zip(ids.tolist(), vals):
                folded_log[i] = v
            if maint.compactions != seen:
                seen = maint.compactions
                fresh_samples.append(
                    metrics.registry.gauge(maintain_mod.FRESHNESS_GAUGE).value
                )
            stop.wait(64.0 / fold_rate)

    folder = threading.Thread(target=fold_loop, daemon=True)
    maint.start()
    folder.start()
    try:
        m_qps_t, m_p99_t, _ = run_trials("under maintenance")
    finally:
        stop.set()
        folder.join(timeout=10)
        maint.close()
        ivf_ops.build_ivf = real_build
    # final forced drain so the recall row sees every fold-in clustered
    maint.run_once(force=True)
    if maint.last_stats and maint.last_stats.get("born"):
        fresh_samples.append(metrics.registry.gauge(maintain_mod.FRESHNESS_GAUGE).value)
    ivf_ops.configure_ann(query_block=old_qb)

    m_p99 = statistics.median(m_p99_t)
    ratio = m_p99 / max(base_p99, 1e-9)
    best_ratio = min(m_p99_t) / max(base_p99, 1e-9)
    # the 1.5x bound presumes a spare core for the background compaction
    # (the design's deployment shape); on a single-core host the OS
    # time-slices compaction against the scan, so the row records the
    # honest ratio but only multi-core hosts hard-fail on it
    cores = os.cpu_count() or 1
    detail = (
        f"p99 {m_p99:.1f} ms under maintenance vs {base_p99:.1f} ms baseline "
        f"({ratio:.2f}x, bound 1.5x"
        f"{' — advisory: single-core host' if cores < 2 else ''}), "
        f"{maint.compactions} compactions, ~{fold_rate:.0f} items/s folded "
        f"({len(folded_log)} total), {recluster[0]} full re-clusters "
        f"(must be 0), {_TRIALS} x {seconds:.0f}s trials"
    )
    print(f"bench[serving-maintain]: {detail}", file=sys.stderr)
    _emit(
        f"ALS /recommend ANN p99 under live maintenance, {features}f x "
        f"{label_m} items, vs 1.5x no-maintenance p99",
        m_p99,
        "ms",
        1.5 * base_p99 / max(m_p99, 1e-9),
        order=84,
        detail=detail,
        base_p99_ms=round(base_p99, 2),
        compactions=maint.compactions,
        folded=len(folded_log),
        recluster_calls=recluster[0],
        noise_suspect=ratio > 1.5 >= best_ratio,
        trials=_TRIALS,
        spread=[round(min(m_p99_t), 2), round(max(m_p99_t), 2)],
    )
    qps, vs, tf = _rate_row(m_qps_t, base_qps)
    _emit(
        f"ALS /recommend ANN steady-state qps under live maintenance, "
        f"{features}f x {label_m} items, vs no-maintenance qps",
        qps,
        "queries/sec",
        vs,
        order=85,
        detail=f"baseline {base_qps:.0f} qps on the same catalog",
        base_qps=round(base_qps, 1),
        **tf,
    )
    if fresh_samples:
        fr = statistics.median(fresh_samples)
        _emit(
            f"ANN freshness under continuous fold-ins, {features}f x {label_m} "
            f"items, vs {fresh_budget:.0f}s budget",
            fr,
            "seconds",
            fresh_budget / max(fr, 1e-9),
            order=85,
            detail=f"fold-in -> clustered-visibility lag at each of "
            f"{len(fresh_samples)} compactions, maintain interval {interval}s",
            trials=len(fresh_samples),
            spread=[round(min(fresh_samples), 3), round(max(fresh_samples), 3)],
        )
    # recall@10 vs the exact f32 ranking over the union catalog (truth
    # computed per-probe: base-matrix scores + folded-row scores merged)
    probes = min(16, batch)
    fids = np.asarray(sorted(folded_log), np.int64)
    fvals = np.stack([folded_log[i] for i in fids.tolist()]) if len(fids) else None
    final = holder["index"]
    aidx, _ = ivf_ops.top_k(final, queries[:probes], how_many, nprobe=nprobe)
    hits = 0
    for r in range(probes):
        q = queries[r]
        t_base = mat @ q
        scores = np.concatenate([t_base, fvals @ q]) if fvals is not None else t_base
        ids_all = (
            np.concatenate([np.arange(len(mat), dtype=np.int64), fids])
            if fvals is not None
            else np.arange(len(mat), dtype=np.int64)
        )
        kth = np.partition(scores, -how_many)[-how_many]
        truth = dict(zip(ids_all.tolist(), scores.tolist()))
        got = [int(i) for i in np.asarray(aidx[r]) if int(i) >= 0]
        hits += sum(1 for i in got if truth.get(i, -np.inf) >= kth - 1e-4)
    recall = hits / (probes * how_many)
    _emit(
        f"ALS /recommend ANN recall after maintenance drain, {features}f x "
        f"{label_m} items, vs 0.95 floor",
        recall,
        "recall@10",
        recall / 0.95,
        order=85,
        detail=f"{probes} probes, nprobe {nprobe} of {final.n_cells} cells, "
        f"union catalog = {len(mat)} built + {len(folded_log)} folded live, "
        "tie-tolerant at 1e-4",
        folded=len(folded_log),
    )
    if ratio > 1.5 and best_ratio > 1.5 and cores >= 2:
        raise RuntimeError(
            f"maintenance p99 {m_p99:.1f} ms breaches 1.5x baseline "
            f"{base_p99:.1f} ms in every trial"
        )
    if recluster[0]:
        raise RuntimeError(
            f"{recluster[0]} full re-cluster(s) during the maintenance window"
        )


def bench_store_tier_cold() -> None:
    """The 100M-item cold-tier capacity row, sized to free disk: the
    tiered cell store holds a catalog far past host RAM as mmap'd disk
    cells (int8-plane bytes per item), and the row measures sequential
    cold-scan bandwidth through `read_cell` — disk -> pinned-RAM
    promotion under a RAM budget that forces continuous LRU eviction, so
    every pass stays cold like a worst-case probe storm."""
    import shutil as _sh

    import numpy as np

    from oryx_tpu.native.store import make_tier_store

    features = int(os.environ.get("ORYX_BENCH_MAINTAIN_FEATURES", 50))
    target = int(os.environ.get("ORYX_BENCH_COLD_ITEMS", 100_000_000))
    ram_budget = int(os.environ.get("ORYX_BENCH_COLD_RAM_MB", 256)) << 20
    items_per_cell = 65_536
    import tempfile

    spill = tempfile.mkdtemp(prefix="oryx-bench-cold-")
    free = _sh.disk_usage(spill).free
    items = min(target, int(free * 0.4 / features))
    n_cells = max(1, (items + items_per_cell - 1) // items_per_cell)
    items = n_cells * items_per_cell
    label_m = f"{items // 1_000_000}M" if items >= 1_000_000 else f"{items // 1000}K"
    sized_down = items < target

    st = make_tier_store(n_cells, ram_budget, spill)
    try:
        gen = np.random.default_rng(31)
        # one random payload reused per cell: content is irrelevant to the
        # mmap/LRU data path and generating the full catalog would bench
        # the RNG, not the store
        block = gen.integers(-127, 128, (items_per_cell, features)).astype(np.int8)
        t0 = time.perf_counter()
        for c in range(n_cells):
            st.put_cell(c, block)
        write_sec = time.perf_counter() - t0
        total_bytes = n_cells * block.nbytes
        print(
            f"bench[store-tier]: {label_m} items / {n_cells} cells / "
            f"{total_bytes / 1e9:.1f} GB written in {write_sec:.0f}s "
            f"({'sized to disk' if sized_down else 'full target'})",
            file=sys.stderr,
        )
        rates = []
        for _ in range(_TRIALS):
            t1 = time.perf_counter()
            for c in range(n_cells):
                buf = st.read_cell(c)
                assert buf is not None
            rates.append(total_bytes / (time.perf_counter() - t1) / 1e9)
        gbps, vs, tf = _rate_row(rates, 0.5)
        s = st.stats()
        detail = (
            f"{n_cells} cells x {items_per_cell} items x {features} B "
            f"(int8 plane), RAM budget {ram_budget >> 20} MB "
            f"({s['ram_cells']} cells resident), {s['demotions']} LRU "
            f"demotions, {tf['trials']} sequential cold passes; "
            f"{items / max(statistics.median(rates), 1e-9) / 1e9 * features:.1f}s "
            "per full-catalog pass"
        )
        print(f"bench[store-tier]: {detail}", file=sys.stderr)
        _emit(
            f"tiered item store cold-tier scan, {label_m} items mmap'd on "
            f"disk{' (sized to free disk)' if sized_down else ''}, "
            "vs 0.5 GB/s floor",
            gbps,
            "GB/s",
            vs,
            order=83,
            detail=detail,
            items=items,
            cells=n_cells,
            disk_gb=round(total_bytes / 1e9, 2),
            ram_cells=s["ram_cells"],
            backend=f"host/{os.cpu_count()}-core",
            **tf,
        )
    finally:
        st.close()
        _sh.rmtree(spill, ignore_errors=True)


BENCHES = [
    ("kmeans", bench_kmeans),
    ("als", bench_als),
    ("als-scale", bench_als_scale),
    ("speed", bench_speed),
    ("tracing-overhead", bench_tracing_overhead),
    ("lock-watchdog", bench_lock_watchdog_overhead),
    ("experiment-overhead", bench_experiment_overhead),
    ("resource-ledger", bench_ledger_overhead),
    ("overload", bench_overload),
    ("tenancy", bench_tenancy_overhead),
    ("rdf", bench_rdf),
    ("serving-large", bench_serving_large),
    ("serving-ann", bench_serving_ann),
    ("serving-maintain", bench_serving_maintain),
    ("store-tier", bench_store_tier_cold),
    ("serving-closed", bench_serving_closed_loop),
    ("serving-native", bench_native_front),
    ("serving-open", bench_serving_open_loop),
    ("crash-recovery", bench_crash_recovery),
    ("serving-250", bench_serving_250),
    ("serving", bench_serving),
]


def run_bench() -> None:
    only = os.environ.get("ORYX_BENCH_ONLY")
    selected = {s.strip() for s in only.split(",")} if only else None
    shapes = os.environ.get("ORYX_BENCH_SHAPES", "all")

    import logging

    logging.getLogger("jax._src.xla_bridge").setLevel(logging.ERROR)

    import jax

    import oryx_tpu

    # a site plugin may have pinned jax_platforms at import; re-assert
    oryx_tpu.honor_platform_env()
    backend, kind, _ = _device_info()
    if backend != "tpu":
        # cross-machine XLA:CPU AOT cache loads can SIGILL; compile fresh
        jax.config.update("jax_compilation_cache_dir", None)
    print(
        f"bench: backend={backend} device={kind} n={len(jax.devices())}",
        file=sys.stderr,
    )
    try:
        with open(EVIDENCE_PATH, "a", encoding="utf-8") as f:
            ts = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
            f.write(f"=== bench run @ {ts} backend={backend} device={kind} ===\n")
    except OSError:
        pass
    ok = 0
    for name, fn in BENCHES:
        if selected is not None and name not in selected:
            continue
        if name == "serving-large" and shapes != "all":
            continue
        t0 = time.perf_counter()
        try:
            fn()
            ok += 1
        except Exception as e:  # noqa: BLE001 - each metric independent
            print(f"bench[{name}]: FAILED: {e!r}", file=sys.stderr)
        print(
            f"bench[{name}]: done in {time.perf_counter() - t0:.0f}s",
            file=sys.stderr,
        )
    if ok == 0:
        sys.exit(3)


# --------------------------------------------------------------------------
# Parent: preflight + retry harness (fresh process per attempt — JAX
# caches a failed backend for the life of the process).
# --------------------------------------------------------------------------

# Only strip lines positively identified as known spam sources — a real
# crash report (which may mention SIGILL or external/xla paths) must
# survive into the operator-visible excerpt.
_NOISE_MARKERS = (
    "cpu_aot_loader",
    "Platform 'axon' is experimental",
    "TfrtCpuClient created",
    "absl::InitializeLog",
)


def _filter_stderr(err: str) -> str:
    kept = [
        ln
        for ln in err.splitlines()
        if ln.strip() and not any(m in ln for m in _NOISE_MARKERS)
    ]
    return "\n".join(kept)[-3000:]


def _print_summary(json_lines: list[str]) -> None:
    """The LAST thing this process writes: every metric row, compact,
    sorted so the headline serving row is the final line. The driver
    records a bounded tail of merged output and parses the last JSON
    line, so nothing may print after this."""
    rows = []
    for ln in json_lines:
        try:
            rows.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    # de-dup by metric (later wins), stable order field
    by_metric = {}
    for r in rows:
        by_metric[r["metric"]] = r
    final = sorted(by_metric.values(), key=lambda r: r.get("order", 50))
    sys.stderr.flush()
    print("=== BENCH SUMMARY ===", flush=True)
    for r in final:
        # keep summary rows compact — the driver records a bounded tail;
        # the full rows (latencies, detail) live in tools/bench_evidence.txt.
        # Closed-loop rows keep p50/p99: true latency is their whole point.
        drop = (
            ("order",)
            if "closed-loop" in r.get("metric", "")
            else ("order", "p50_ms", "p99_ms")
        )
        for k in drop:
            r.pop(k, None)
        print(json.dumps(r), flush=True)
    sys.stdout.flush()


def _run_child(env: dict, timeout: float) -> tuple[int, list[str], str]:
    """Stream child stdout, forwarding metric JSON lines immediately so
    completed metrics survive a mid-run kill."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    json_lines: list[str] = []

    import threading

    # hard watchdog: a child hung in backend init prints nothing, so the
    # readline loop alone would block forever — kill unconditionally at
    # the deadline
    timed_out = threading.Event()

    def _watchdog() -> None:
        if proc.poll() is None:
            timed_out.set()
            proc.kill()

    killer = threading.Timer(timeout, _watchdog)
    killer.daemon = True
    killer.start()

    err_chunks: list[str] = []
    t = threading.Thread(
        target=lambda: err_chunks.append(proc.stderr.read()), daemon=True
    )
    t.start()
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                json_lines.append(line)
                print(line, flush=True)
        rc = proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        rc = -9
    finally:
        killer.cancel()
    t.join(timeout=5)
    err = err_chunks[0] if err_chunks else ""
    if timed_out.is_set():
        rc = -9
        err += "\n[parent] child timed out"
    return rc, json_lines, err


def _probe_backend(timeout: float) -> bool:
    """Quick subprocess probe: can the device backend actually run an op?
    A wedged tunnel makes jax HANG (not error) in init, so without this
    a dead TPU costs a full child-watchdog cycle per attempt before the
    CPU fallback ever runs."""
    code = "import jax, jax.numpy as jnp; jnp.ones(3).sum().block_until_ready(); print('PROBE-OK')"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=dict(os.environ),
        )
        return "PROBE-OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    attempts = int(os.environ.get("ORYX_BENCH_ATTEMPTS", 3))
    init_timeout = float(os.environ.get("ORYX_BENCH_INIT_TIMEOUT", 150))
    # generous: metrics stream as they complete, so a watchdog kill only
    # costs whatever is still running (r5 added the 5M/20M serving shapes
    # and the 20M-rating scale row — first-compile-heavy on a cold cache;
    # r6's >=3-trials-per-metric noise protocol multiplies steady-state
    # measurement time, though compiles still happen once)
    child_timeout = init_timeout + 4500

    # attempts=1 is the documented fail-fast-TPU contract: no probe-driven
    # CPU fallback there either
    if os.environ.get("JAX_PLATFORMS") != "cpu" and attempts > 1:
        for p in range(2):
            if _probe_backend(init_timeout):
                break
            print(
                f"bench[parent]: backend probe {p + 1}/2 failed (hung init?)",
                file=sys.stderr,
            )
            if p == 0:
                time.sleep(20)
        else:
            print(
                "bench[parent]: device backend unreachable — CPU fallback "
                "(rows will be labeled backend=cpu)",
                file=sys.stderr,
            )
            os.environ["JAX_PLATFORMS"] = "cpu"

    base_env = dict(os.environ)
    base_env["ORYX_BENCH_CHILD"] = "1"
    # only fall back to CPU when there was at least one real TPU attempt
    # (ORYX_BENCH_ATTEMPTS=1 means "one fail-fast TPU try", not "CPU")
    cpu_fallback = attempts > 1 or os.environ.get("JAX_PLATFORMS") == "cpu"

    backoffs = [15, 30, 60]
    attempt = 0
    while attempt < attempts:
        last = attempt == attempts - 1
        env = dict(base_env)
        label = "cpu" if env.get("JAX_PLATFORMS") == "cpu" else "tpu"
        if last and cpu_fallback and env.get("JAX_PLATFORMS") != "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            label = "cpu-fallback"
        print(f"bench[parent]: attempt {attempt + 1}/{attempts} ({label})", file=sys.stderr)
        rc, json_lines, err = _run_child(env, timeout=child_timeout)
        sys.stderr.write(_filter_stderr(err) + "\n")
        if json_lines:
            print(
                f"bench[parent]: {len(json_lines)} metric(s) recorded (rc={rc})",
                file=sys.stderr,
            )
            _print_summary(json_lines)
            return
        transient = any(
            k in err
            for k in (
                "UNAVAILABLE",
                "Unable to initialize backend",
                "DEADLINE_EXCEEDED",
                "timed out",
            )
        )
        print(
            f"bench[parent]: attempt {attempt + 1} failed rc={rc} "
            f"({'transient backend error' if transient else 'non-transient'})",
            file=sys.stderr,
        )
        if not transient and not last:
            print("bench[parent]: skipping to final attempt", file=sys.stderr)
            attempt = attempts - 1
            continue
        if not last:
            wait = backoffs[min(attempt, len(backoffs) - 1)]
            print(f"bench[parent]: retrying in {wait}s", file=sys.stderr)
            time.sleep(wait)
        attempt += 1

    print("bench[parent]: all attempts failed — no benchmark number this round", file=sys.stderr)
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("ORYX_BENCH_CHILD"):
        run_bench()
    else:
        main()
