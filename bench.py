"""Headline benchmark: ALS /recommend throughput on TPU.

Reproduces the reference's LoadBenchmark shape (app/oryx-app-serving/src/
test/.../als/LoadBenchmark.java + LoadTestALSModelFactory.java:34-101):
a synthetic model of `items` x `features` with random factors, then timed
top-10 recommend queries for random users. The reference's best published
number at 50 features x 1M items is 437 qps (LSH sample-rate 0.3, 32-core
Xeon; docs/performance.md:108-117) — that is the vs_baseline denominator.

Each request batch is ONE fused Pallas scan + top_k on the TPU over the
full item matrix (exact scoring — no LSH approximation), with the item
matrix held in bfloat16 to halve HBM traffic. Requests are pipelined:
a window of batches stays in flight so device→host result transfers
overlap the next batches' compute, exactly how the serving layer's
request pipeline runs concurrent clients.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs (LoadTestALSModelFactory-style): ORYX_BENCH_ITEMS,
ORYX_BENCH_FEATURES, ORYX_BENCH_USERS, ORYX_BENCH_SECONDS,
ORYX_BENCH_BATCH (request batch size), ORYX_BENCH_DEPTH (in-flight
batches), ORYX_BENCH_DTYPE (bfloat16|float32).
"""

import json
import os
import time
from collections import deque

import numpy as np


def main() -> None:
    items = int(os.environ.get("ORYX_BENCH_ITEMS", 1_000_000))
    features = int(os.environ.get("ORYX_BENCH_FEATURES", 50))
    users = int(os.environ.get("ORYX_BENCH_USERS", 4096))
    seconds = float(os.environ.get("ORYX_BENCH_SECONDS", 10.0))
    batch = int(os.environ.get("ORYX_BENCH_BATCH", 128))
    depth = int(os.environ.get("ORYX_BENCH_DEPTH", 48))
    dtype_name = os.environ.get("ORYX_BENCH_DTYPE", "bfloat16")
    how_many = 10
    baseline_qps = 437.0  # reference: LSH 0.3, 50 feat x 1M items

    import jax.numpy as jnp

    from oryx_tpu.ops import topn as topn_ops

    gen = np.random.default_rng(1234)
    y = gen.standard_normal((items, features), dtype=np.float32)
    x = gen.standard_normal((users, features), dtype=np.float32)

    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    uploaded = topn_ops.upload(y, dtype=dtype)
    # warm up / compile
    topn_ops.submit_top_k(uploaded, x[:batch], how_many).result()

    served = 0
    inflight: deque = deque()
    num_batches = max(1, users // batch)
    start = time.perf_counter()
    deadline = start + seconds
    i = 0
    while True:
        now = time.perf_counter()
        if now < deadline and len(inflight) < depth:
            qi = i % num_batches
            queries = x[qi * batch : qi * batch + batch]
            inflight.append((topn_ops.submit_top_k(uploaded, queries, how_many), len(queries)))
            i += 1
        elif inflight:
            handle, rows = inflight.popleft()
            handle.result()
            served += rows
        else:
            break
    elapsed = time.perf_counter() - start
    qps = served / elapsed

    print(
        json.dumps(
            {
                "metric": (
                    f"ALS recommend top-{how_many} qps, exact scan "
                    f"({features} feat x {items} items, {dtype_name}, "
                    f"batch {batch} x depth {depth})"
                ),
                "value": round(qps, 1),
                "unit": "recs/sec",
                "vs_baseline": round(qps / baseline_qps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
