"""App-tier PMML glue.

Rebuild of AppPMMLUtils (app/oryx-app-common/.../pmml/AppPMMLUtils.java:
59-285): PMML Extension get/set (the ALS model's pointers live in
extensions), DataDictionary/MiningSchema construction from an InputSchema,
and resolution of update-topic model messages — "MODEL" carries inline
PMML, "MODEL-REF" carries a path to read it from
(readPMMLFromUpdateKeyMessage, AppPMMLUtils.java:256-285).
"""

from __future__ import annotations

import logging
from pathlib import Path
from xml.etree.ElementTree import Element

from oryx_tpu.common import pmml as pmml_io, storage
from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema

log = logging.getLogger(__name__)


# -- extensions -------------------------------------------------------------


def add_extension(root: Element, name: str, value) -> None:
    pmml_io.sub(root, "Extension", {"name": name, "value": str(value)})


def add_extension_content(root: Element, name: str, content: list) -> None:
    """Extension whose content is a space-joined token list
    (AppPMMLUtils.addExtensionContent). Tokens with spaces are quoted."""
    if not content:
        return
    from oryx_tpu.common.text import join_delimited

    e = pmml_io.sub(root, "Extension", {"name": name})
    e.text = join_delimited([str(x) for x in content], " ")


def get_extension_value(root: Element, name: str) -> str | None:
    for ext in pmml_io.findall(root, "Extension"):
        if ext.get("name") == name:
            return ext.get("value")
    return None


def get_extension_content(root: Element, name: str) -> list[str] | None:
    from oryx_tpu.common.text import parse_delimited

    for ext in pmml_io.findall(root, "Extension"):
        if ext.get("name") == name and ext.get("value") is None:
            return parse_delimited(ext.text or "", " ")
    return None


def get_required_extension_value(root: Element, name: str) -> str:
    v = get_extension_value(root, name)
    if v is None:
        raise ValueError(f"missing PMML extension {name}")
    return v


# -- schema -> PMML ---------------------------------------------------------


def build_data_dictionary(
    root: Element, schema: InputSchema, encodings: CategoricalValueEncodings | None = None
) -> Element:
    """DataDictionary from schema (AppPMMLUtils.buildDataDictionary:195-227).

    Mirrors the reference's field set exactly: EVERY feature gets a
    DataField — id/ignored features as bare fields with no optype or
    dataType — and numberOfFields counts them all, so a document written
    here is column-for-column what the reference's JAXB writer emits."""
    dd = pmml_io.sub(root, "DataDictionary")
    for i, name in enumerate(schema.feature_names):
        if schema.is_numeric(i):
            pmml_io.sub(dd, "DataField", {"name": name, "optype": "continuous", "dataType": "double"})
        elif schema.is_categorical(i):
            df = pmml_io.sub(dd, "DataField", {"name": name, "optype": "categorical", "dataType": "string"})
            if encodings is not None:
                for v, _ in sorted(
                    encodings.value_to_index_map(i).items(), key=lambda kv: kv[1]
                ):
                    pmml_io.sub(df, "Value", {"value": v})
        else:
            pmml_io.sub(dd, "DataField", {"name": name})
    dd.set("numberOfFields", str(len(schema.feature_names)))
    return dd


def build_mining_schema(
    parent: Element, schema: InputSchema, importances: list[float] | None = None
) -> Element:
    """MiningSchema mirroring AppPMMLUtils.buildMiningSchema:140-171:
    every feature appears; numeric/categorical actives carry optype +
    usageType=active, id/ignored features usageType=supplementary (no
    optype), the target's usageType is overridden to predicted, and
    importances land on active predictor fields."""
    ms = pmml_io.sub(parent, "MiningSchema")
    for i, name in enumerate(schema.feature_names):
        attrs = {"name": name}
        if schema.is_numeric(i):
            attrs["optype"] = "continuous"
            attrs["usageType"] = "active"
        elif schema.is_categorical(i):
            attrs["optype"] = "categorical"
            attrs["usageType"] = "active"
        else:
            attrs["usageType"] = "supplementary"
        if schema.is_target(i):
            attrs["usageType"] = "predicted"
        if attrs["usageType"] == "active" and importances is not None:
            p = schema.feature_to_predictor_index(i)
            attrs["importance"] = repr(float(importances[p]))
        pmml_io.sub(ms, "MiningField", attrs)
    return ms


def build_categorical_encodings(pmml_root: Element, schema: InputSchema) -> CategoricalValueEncodings:
    """Recover encodings from DataDictionary Values
    (AppPMMLUtils.buildCategoricalValueEncodings:208-229)."""
    distinct: dict[int, list[str]] = {}
    dd = pmml_io.find(pmml_root, "DataDictionary")
    if dd is not None:
        for df in pmml_io.findall(dd, "DataField"):
            values = [v.get("value") for v in pmml_io.findall(df, "Value")]
            if values:
                feat = schema.feature_names.index(df.get("name"))
                distinct[feat] = values
    return CategoricalValueEncodings(distinct)


# -- update-topic model resolution ------------------------------------------


def read_pmml_from_update_message(key: str, message: str) -> Element | None:
    """Resolve a MODEL / MODEL-REF update message to a PMML tree, or None
    for other keys (AppPMMLUtils.readPMMLFromUpdateKeyMessage:256-285).
    A MODEL-REF whose path has vanished returns None (logged by caller)."""
    if key == "MODEL":
        return pmml_io.from_string(message)
    if key == "MODEL-REF":
        # the path may be local or an object-store URI (gs://...) — the
        # reference reads referenced models from HDFS the same way. The
        # registry publishes refs as *generation dirs* (resolvable to
        # manifest + artifacts, not just the document), so try
        # <ref>/model.pmml first; a plain file path (legacy producers)
        # still resolves. A poison reference (unknown scheme, missing
        # driver, vanished path) must never kill a consumer loop:
        # resolve to None.
        try:
            from oryx_tpu.registry.store import MODEL_FILE_NAME

            ref = message
            stager = _active_stager()
            if stager is not None:
                staged = stager.stage(ref)
                if staged is not None:
                    ref = str(staged)
            in_dir = storage.join(ref, MODEL_FILE_NAME)
            if storage.exists(in_dir):
                return pmml_io.from_string(storage.read_text(in_dir))
            if not storage.exists(ref):
                return None
            return pmml_io.from_string(storage.read_text(ref))
        except Exception:
            log.warning("unresolvable MODEL-REF %r", message, exc_info=True)
            return None
    return None


def _active_stager():
    """The serving layer's restage cache, when one is registered
    (oryx.serving.restage-dir). Lazy import: app must not pull the
    serving package in at module load."""
    try:
        from oryx_tpu.serving import restage

        return restage.active()
    except Exception:  # pragma: no cover - serving package unavailable
        return None
