"""Rescorer SPI: user-pluggable filtering/boosting of recommendations.

Rebuild of app/oryx-app-api .../als/{Rescorer,RescorerProvider,
AbstractRescorerProvider,MultiRescorer,MultiRescorerProvider}.java:
providers are named in config (oryx.als.rescorer-provider-class) and
asked per-request for a Rescorer given the request's rescorerParams.
"""

from __future__ import annotations

import abc
import math
from typing import Sequence


class Rescorer(abc.ABC):
    @abc.abstractmethod
    def rescore(self, id_: str, original_score: float) -> float:
        """New score; NaN removes the candidate (Rescorer.java)."""

    def is_filtered(self, id_: str) -> bool:
        return False


class RescorerProvider(abc.ABC):
    """Per-endpoint rescorer factories (RescorerProvider.java); any may
    return None meaning 'no rescoring here'."""

    def get_recommend_rescorer(self, user_ids: Sequence[str], args: Sequence[str]) -> Rescorer | None:
        return None

    def get_recommend_to_anonymous_rescorer(self, item_ids: Sequence[str], args: Sequence[str]) -> Rescorer | None:
        return None

    def get_most_popular_items_rescorer(self, args: Sequence[str]) -> Rescorer | None:
        return None

    def get_most_active_users_rescorer(self, args: Sequence[str]) -> Rescorer | None:
        return None


class MultiRescorer(Rescorer):
    """AND-combination of several rescorers (MultiRescorer.java)."""

    def __init__(self, rescorers: Sequence[Rescorer]) -> None:
        self.rescorers = list(rescorers)

    def rescore(self, id_: str, original_score: float) -> float:
        score = original_score
        for r in self.rescorers:
            score = r.rescore(id_, score)
            if math.isnan(score):
                return score
        return score

    def is_filtered(self, id_: str) -> bool:
        return any(r.is_filtered(id_) for r in self.rescorers)


def _combine(rescorers: list[Rescorer]) -> Rescorer | None:
    rescorers = [r for r in rescorers if r is not None]
    if not rescorers:
        return None
    if len(rescorers) == 1:
        return rescorers[0]
    return MultiRescorer(rescorers)


class MultiRescorerProvider(RescorerProvider):
    """Chains several providers (MultiRescorerProvider.java)."""

    def __init__(self, providers: Sequence[RescorerProvider]) -> None:
        self.providers = list(providers)

    def get_recommend_rescorer(self, user_ids, args):
        return _combine([p.get_recommend_rescorer(user_ids, args) for p in self.providers])

    def get_recommend_to_anonymous_rescorer(self, item_ids, args):
        return _combine(
            [p.get_recommend_to_anonymous_rescorer(item_ids, args) for p in self.providers]
        )

    def get_most_popular_items_rescorer(self, args):
        return _combine([p.get_most_popular_items_rescorer(args) for p in self.providers])

    def get_most_active_users_rescorer(self, args):
        return _combine([p.get_most_active_users_rescorer(args) for p in self.providers])
