"""Shared incremental-ALS state and math.

Rebuild of app/oryx-app-common .../als/FeatureVectors.java:36-161 (a
concurrent id -> float32-vector store with recent-ID tracking and
rotation reconciliation) and ALSUtils.java:24-108 (the fold-in update:
how a user vector changes in response to one new interaction, used on the
speed- and serving-layer hot paths).

IDs are strings end to end. (The reference hashes string IDs to int32
because Spark MLlib requires int IDs, ALSUpdate.java:305-326; the JAX
trainer indexes rows directly so no lossy hash is needed.)
"""

from __future__ import annotations

import math
from typing import Callable

import numpy as np

from oryx_tpu.common.lang import ReadWriteLock
from oryx_tpu.common.vectormath import Solver


class FeatureVectors:
    """Concurrent ID -> float32 vector store (FeatureVectors.java)."""

    def __init__(self) -> None:
        self._lock = ReadWriteLock()
        self._vectors: dict[str, np.ndarray] = {}
        self._recent_ids: set[str] = set()

    def size(self) -> int:
        with self._lock.read():
            return len(self._vectors)

    def get_vector(self, id_: str) -> np.ndarray | None:
        with self._lock.read():
            return self._vectors.get(id_)

    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            self._vectors[id_] = vector
            self._recent_ids.add(id_)

    def set_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        """Insert/update many vectors under one write lock."""
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock.write():
            for id_, vec in zip(ids, vectors):
                # copy: a row view would pin the whole batch matrix alive
                # for as long as any single id keeps its vector
                self._vectors[id_] = np.array(vec)
            self._recent_ids.update(ids)

    def get_batch(
        self, ids: list[str], dim: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectors for many ids: ([n, dim] float32 with zero rows for
        misses, [n] bool valid). Interface parity with the native store.
        ``dim`` keeps the matrix shape well-formed when the store is empty
        (e.g. right after a rotation removed every vector)."""
        n = len(ids)
        with self._lock.read():
            for v in self._vectors.values():
                dim = len(v)
                break
            dim = dim or 0
            mat = np.zeros((n, dim), dtype=np.float32)
            valid = np.zeros(n, dtype=bool)
            for j, id_ in enumerate(ids):
                v = self._vectors.get(id_)
                if v is not None:
                    mat[j], valid[j] = v, True
        return mat, valid

    def remove_vector(self, id_: str) -> None:
        with self._lock.write():
            self._vectors.pop(id_, None)
            self._recent_ids.discard(id_)

    def add_all_ids_to(self, out: set[str]) -> None:
        with self._lock.read():
            out.update(self._vectors.keys())

    def add_all_recent_to(self, out: set[str]) -> None:
        with self._lock.read():
            out.update(self._recent_ids)

    def retain_recent_and_ids(self, new_model_ids: set[str]) -> None:
        """On model rotation keep only ids in the new model OR written
        since the last rotation, then reset recency
        (FeatureVectors.retainRecentAndIDs:131-136 — this is what makes
        'recent writes survive model swap' true)."""
        with self._lock.write():
            keep = self._recent_ids | new_model_ids
            for id_ in [i for i in self._vectors if i not in keep]:
                del self._vectors[id_]
            self._recent_ids.clear()

    def items(self) -> list[tuple[str, np.ndarray]]:
        with self._lock.read():
            return list(self._vectors.items())

    def ids(self) -> list[str]:
        with self._lock.read():
            return list(self._vectors.keys())

    def for_each(self, fn: Callable[[str, np.ndarray], None]) -> None:
        for id_, v in self.items():
            fn(id_, v)

    def get_vtv(self) -> np.ndarray | None:
        """V^T V over all vectors (FeatureVectors.getVTV:150-154)."""
        with self._lock.read():
            if not self._vectors:
                return None
            m = np.stack(list(self._vectors.values())).astype(np.float64)
        return m.T @ m

    def to_matrix(self) -> tuple[list[str], np.ndarray]:
        """Packed (ids, [n, k] float32 matrix) snapshot, for device upload."""
        with self._lock.read():
            if not self._vectors:
                return [], np.zeros((0, 0), dtype=np.float32)
            ids = list(self._vectors.keys())
            mat = np.stack([self._vectors[i] for i in ids])
        return ids, mat


# -- fold-in math (ALSUtils) -------------------------------------------------


def compute_target_qui(implicit: bool, value: float, current_value: float) -> float:
    """Target estimated interaction strength after a new interaction of
    the given value, or NaN for "no change" (ALSUtils.computeTargetQui:
    37-59). Implicit targets move part of the way from the current
    estimate toward 1 (positive value) or 0 (negative), proportionally to
    the interaction strength; explicit targets are the value itself."""
    if not implicit:
        return value
    if value > 0.0 and current_value < 1.0:
        diff = 1.0 - max(0.0, current_value)
        return current_value + (value / (1.0 + value)) * diff
    if value < 0.0 and current_value > 0.0:
        diff = -min(1.0, current_value)
        return current_value + (value / (value - 1.0)) * diff
    return math.nan


def compute_updated_xu(
    solver: Solver,
    value: float,
    xu: np.ndarray | None,
    yi: np.ndarray | None,
    implicit: bool,
) -> np.ndarray | None:
    """New user vector after one (user, item, value) interaction, or None
    when no update applies (ALSUtils.computeUpdatedXu:74-106). Also used
    with roles swapped to update item vectors. Solves
    dXu = (YtY)^-1 (dQui * Yi) and adds it to Xu."""
    if yi is None:
        return None
    yi = np.asarray(yi, dtype=np.float32)
    qui = 0.0 if xu is None else float(np.dot(np.asarray(xu, dtype=np.float64), yi))
    # 0.5 reflects a "don't know" prior for a brand-new user
    target_qui = compute_target_qui(implicit, value, 0.5 if xu is None else qui)
    if math.isnan(target_qui):
        return None
    d_qui = target_qui - qui
    d_xu = solver.solve_f_to_f(d_qui * yi)
    if xu is None:
        return d_xu
    return np.asarray(xu, dtype=np.float32) + d_xu


# ---------------------------------------------------------------------------
# Columnar UP-message consumption (shared by the speed and serving managers)
# ---------------------------------------------------------------------------


def consume_blocks_columnar(block_iterator, model_ready, apply_up_batch, consume):
    """Columnar consume loop: contiguous runs of "UP" records hand off to
    ``apply_up_batch`` as raw byte lines; everything else — MODEL/
    MODEL-REF, blocks with no key column, records before a model exists —
    falls back to the per-record ``consume`` in order."""
    from oryx_tpu.bus.core import KeyMessage

    for block in block_iterator:
        if not model_ready() or block.keys is None:
            consume(block.iter_key_messages())
            continue
        keys = block.keys.tolist()
        msgs = block.messages.tolist()
        n = len(msgs)
        i = 0
        while i < n:
            if keys[i] == b"UP":
                j = i
                while j < n and keys[j] == b"UP":
                    j += 1
                apply_up_batch(msgs[i:j])
                i = j
            else:
                consume(iter([KeyMessage(
                    keys[i].decode("utf-8", "replace"),
                    msgs[i].decode("utf-8", "replace"),
                )]))
                i += 1


def apply_up_lines(
    lines: list,
    k: int,
    set_x: Callable,
    set_y: Callable,
    slow_consume: Callable,
    on_known: Callable | None = None,
    strict_tail: bool = False,
) -> int:
    """Batched fast path for a run of raw "UP" byte lines.

    Groups ``["X","id",[floats]...`` / ``["Y",...`` lines, parses every
    float component in one native pass (numpy twin as backstop), and
    applies each group via one batched setter call. Records the fast
    parser can't take — escaped ids, malformed lines, (with
    ``strict_tail``) unrecognized trailing elements — are handed to
    ``slow_consume`` ONE AT A TIME, and pending groups flush first: a
    later fast update for the same id must not be overwritten by
    replaying this older record after it.

    ``on_known(pairs)`` receives the X-side (id, known-ids-list) pairs of
    each flushed group when given; it implies strict tail validation for
    X records (the known list is part of the wire contract there).
    Returns rows applied via the fast path (slow-path records are the
    caller's consume's to count)."""
    from oryx_tpu.bus.core import KeyMessage
    from oryx_tpu.native.store import parse_float_csv

    parse_known = on_known is not None
    strict = strict_tail or parse_known

    def fresh():
        return {
            b'["X","': ([], [], [], [], set_x),
            b'["Y","': ([], [], [], [], set_y),
        }

    groups = fresh()
    applied = 0

    def flush() -> None:
        nonlocal groups, applied
        for which, (ids, vecs, origs, knowns, setter) in groups.items():
            if not ids:
                continue
            payload = b",".join(vecs)
            flat = parse_float_csv(payload, len(ids) * k)  # native strtof
            if flat is None:  # library absent / mismatch: numpy twin
                parts = payload.split(b",")
                if len(parts) == len(ids) * k:
                    try:
                        flat = np.array(parts, dtype="S").astype(np.float32)
                    except ValueError:
                        flat = None
            if flat is None:
                # oddball numerics: whole group per-record, in order
                for ln in origs:
                    slow_consume(KeyMessage("UP", ln.decode("utf-8", "replace")))
                continue
            setter(ids, flat.reshape(len(ids), k))
            applied += len(ids)
            if which == b'["X","' and parse_known:
                on_known([(u, kn) for u, kn in zip(ids, knowns) if kn])
        groups = fresh()

    for ln in lines:
        slow = False
        group = groups.get(ln[:6])
        known: list[str] | None = None
        at = end = -1
        # escaped ids defeat the byte-slicing parse. With a strict tail the
        # known list is parsed too, so a backslash ANYWHERE disqualifies;
        # otherwise the tail is ignored and only the id region matters
        # (known ids with JSON escapes must not collapse the fast path).
        if group is None or (strict and b"\\" in ln):
            slow = True
        else:
            at = ln.find(b'",[', 6)
            end = ln.find(b"]", at + 3) if at != -1 else -1
            if at == -1 or end == -1 or b"\\" in ln[:at]:
                slow = True
            elif strict:
                tail = ln[end + 1 :]
                if tail != b"]":
                    # optional known-ids list: ,["i1","i2"]] (X only)
                    if not (tail.startswith(b',[') and tail.endswith(b"]]")):
                        slow = True
                    else:
                        inner = tail[2:-2]
                        if inner == b"":
                            known = []
                        elif inner.startswith(b'"') and inner.endswith(b'"'):
                            known = [
                                s.decode("utf-8", "replace")
                                for s in inner[1:-1].split(b'","')
                            ]
                        else:
                            slow = True
        if slow:
            flush()
            slow_consume(KeyMessage("UP", ln.decode("utf-8", "replace")))
            continue
        group[0].append(ln[6:at].decode("utf-8", "replace"))
        group[1].append(ln[at + 3 : end])
        group[2].append(ln)
        group[3].append(known)
    flush()
    return applied
