"""Shared incremental-ALS state and math.

Rebuild of app/oryx-app-common .../als/FeatureVectors.java:36-161 (a
concurrent id -> float32-vector store with recent-ID tracking and
rotation reconciliation) and ALSUtils.java:24-108 (the fold-in update:
how a user vector changes in response to one new interaction, used on the
speed- and serving-layer hot paths).

IDs are strings end to end. (The reference hashes string IDs to int32
because Spark MLlib requires int IDs, ALSUpdate.java:305-326; the JAX
trainer indexes rows directly so no lossy hash is needed.)
"""

from __future__ import annotations

import math
from typing import Callable

import numpy as np

from oryx_tpu.common.lang import ReadWriteLock
from oryx_tpu.common.vectormath import Solver


class FeatureVectors:
    """Concurrent ID -> float32 vector store (FeatureVectors.java)."""

    def __init__(self) -> None:
        self._lock = ReadWriteLock()
        self._vectors: dict[str, np.ndarray] = {}
        self._recent_ids: set[str] = set()

    def size(self) -> int:
        with self._lock.read():
            return len(self._vectors)

    def get_vector(self, id_: str) -> np.ndarray | None:
        with self._lock.read():
            return self._vectors.get(id_)

    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            self._vectors[id_] = vector
            self._recent_ids.add(id_)

    def set_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        """Insert/update many vectors under one write lock."""
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock.write():
            for id_, vec in zip(ids, vectors):
                # copy: a row view would pin the whole batch matrix alive
                # for as long as any single id keeps its vector
                self._vectors[id_] = np.array(vec)
            self._recent_ids.update(ids)

    def get_batch(
        self, ids: list[str], dim: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectors for many ids: ([n, dim] float32 with zero rows for
        misses, [n] bool valid). Interface parity with the native store.
        ``dim`` keeps the matrix shape well-formed when the store is empty
        (e.g. right after a rotation removed every vector)."""
        n = len(ids)
        with self._lock.read():
            for v in self._vectors.values():
                dim = len(v)
                break
            dim = dim or 0
            mat = np.zeros((n, dim), dtype=np.float32)
            valid = np.zeros(n, dtype=bool)
            for j, id_ in enumerate(ids):
                v = self._vectors.get(id_)
                if v is not None:
                    mat[j], valid[j] = v, True
        return mat, valid

    def remove_vector(self, id_: str) -> None:
        with self._lock.write():
            self._vectors.pop(id_, None)
            self._recent_ids.discard(id_)

    def add_all_ids_to(self, out: set[str]) -> None:
        with self._lock.read():
            out.update(self._vectors.keys())

    def add_all_recent_to(self, out: set[str]) -> None:
        with self._lock.read():
            out.update(self._recent_ids)

    def retain_recent_and_ids(self, new_model_ids: set[str]) -> None:
        """On model rotation keep only ids in the new model OR written
        since the last rotation, then reset recency
        (FeatureVectors.retainRecentAndIDs:131-136 — this is what makes
        'recent writes survive model swap' true)."""
        with self._lock.write():
            keep = self._recent_ids | new_model_ids
            for id_ in [i for i in self._vectors if i not in keep]:
                del self._vectors[id_]
            self._recent_ids.clear()

    def items(self) -> list[tuple[str, np.ndarray]]:
        with self._lock.read():
            return list(self._vectors.items())

    def ids(self) -> list[str]:
        with self._lock.read():
            return list(self._vectors.keys())

    def for_each(self, fn: Callable[[str, np.ndarray], None]) -> None:
        for id_, v in self.items():
            fn(id_, v)

    def get_vtv(self) -> np.ndarray | None:
        """V^T V over all vectors (FeatureVectors.getVTV:150-154)."""
        with self._lock.read():
            if not self._vectors:
                return None
            m = np.stack(list(self._vectors.values())).astype(np.float64)
        return m.T @ m

    def to_matrix(self) -> tuple[list[str], np.ndarray]:
        """Packed (ids, [n, k] float32 matrix) snapshot, for device upload."""
        with self._lock.read():
            if not self._vectors:
                return [], np.zeros((0, 0), dtype=np.float32)
            ids = list(self._vectors.keys())
            mat = np.stack([self._vectors[i] for i in ids])
        return ids, mat


# -- fold-in math (ALSUtils) -------------------------------------------------


def compute_target_qui(implicit: bool, value: float, current_value: float) -> float:
    """Target estimated interaction strength after a new interaction of
    the given value, or NaN for "no change" (ALSUtils.computeTargetQui:
    37-59). Implicit targets move part of the way from the current
    estimate toward 1 (positive value) or 0 (negative), proportionally to
    the interaction strength; explicit targets are the value itself."""
    if not implicit:
        return value
    if value > 0.0 and current_value < 1.0:
        diff = 1.0 - max(0.0, current_value)
        return current_value + (value / (1.0 + value)) * diff
    if value < 0.0 and current_value > 0.0:
        diff = -min(1.0, current_value)
        return current_value + (value / (value - 1.0)) * diff
    return math.nan


def compute_updated_xu(
    solver: Solver,
    value: float,
    xu: np.ndarray | None,
    yi: np.ndarray | None,
    implicit: bool,
) -> np.ndarray | None:
    """New user vector after one (user, item, value) interaction, or None
    when no update applies (ALSUtils.computeUpdatedXu:74-106). Also used
    with roles swapped to update item vectors. Solves
    dXu = (YtY)^-1 (dQui * Yi) and adds it to Xu."""
    if yi is None:
        return None
    yi = np.asarray(yi, dtype=np.float32)
    qui = 0.0 if xu is None else float(np.dot(np.asarray(xu, dtype=np.float64), yi))
    # 0.5 reflects a "don't know" prior for a brand-new user
    target_qui = compute_target_qui(implicit, value, 0.5 if xu is None else qui)
    if math.isnan(target_qui):
        return None
    d_qui = target_qui - qui
    d_xu = solver.solve_f_to_f(d_qui * yi)
    if xu is None:
        return d_xu
    return np.asarray(xu, dtype=np.float32) + d_xu
