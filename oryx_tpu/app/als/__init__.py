"""ALS collaborative-filtering application: batch trainer, speed-layer
fold-in, serving model + REST endpoints (reference app components in
SURVEY.md §2.7-2.10 under als/).
"""
