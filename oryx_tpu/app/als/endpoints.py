"""ALS REST endpoints.

Rebuild of the ~20 JAX-RS resources under app/oryx-app-serving/src/main/
java/com/cloudera/oryx/app/serving/als/ (SURVEY.md §2.10 endpoint table).
Path/query parameter conventions follow the reference: howMany/offset
paging, considerKnownItems, rescorerParams, multi-segment ID lists, and
"item=value" pairs for anonymous endpoints
(e.g. RecommendToAnonymous.java:59, EstimateForAnonymous.java:47-87).
Responses are (id, value) records rendered as JSON objects or text/csv.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from oryx_tpu.app.als.common import compute_updated_xu
from oryx_tpu.app.serving_common import (
    check_not_read_only,
    get_ready_model,
    read_ingest_lines,
    send_input,
)
from oryx_tpu.common.text import join_csv
from oryx_tpu.common.vectormath import cosine_similarity
from oryx_tpu.serving.web import (
    OryxServingException,
    Request,
    Response,
    ServingContext,
    resource,
)


@dataclass
class IDValue:
    """id/value response record (serving/IDValue.java)."""

    id: str
    value: float

    def to_json(self):
        return {"id": self.id, "value": self.value}

    def to_csv(self) -> str:
        return join_csv([self.id, self.value])


@dataclass
class IDCount:
    id: str
    count: int

    def to_json(self):
        return {"id": self.id, "count": self.count}

    def to_csv(self) -> str:
        return join_csv([self.id, self.count])


def _model(ctx: ServingContext):
    return get_ready_model(ctx)


def _paging(req: Request) -> tuple[int, int]:
    how_many = req.q_int("howMany", 10)
    offset = req.q_int("offset", 0)
    if how_many <= 0 or offset < 0:
        raise OryxServingException(400, "howMany must be positive and offset nonnegative")
    return how_many, offset


def _rescorer(ctx: ServingContext, kind: str, req: Request, ids=()):
    provider = getattr(ctx.model_manager, "rescorer_provider", None)
    if provider is None:
        return None
    args = req.q_list("rescorerParams")
    if kind == "recommend":
        return provider.get_recommend_rescorer(list(ids), args)
    if kind == "anonymous":
        return provider.get_recommend_to_anonymous_rescorer(list(ids), args)
    if kind == "popular":
        return provider.get_most_popular_items_rescorer(args)
    if kind == "active":
        return provider.get_most_active_users_rescorer(args)
    return None


def _parse_item_value_pairs(segments: list[str]) -> list[tuple[str, float]]:
    """["I1=2.0", "I2"] -> [("I1", 2.0), ("I2", 1.0)] (reference anonymous
    endpoints accept itemID or itemID=strength)."""
    out = []
    for seg in segments:
        if "=" in seg:
            item, val = seg.split("=", 1)
            try:
                out.append((item, float(val)))
            except ValueError:
                raise OryxServingException(400, f"bad value in {seg!r}")
        else:
            out.append((seg, 1.0))
    return out


def _anonymous_user_vector(model, pairs: list[tuple[str, float]]) -> np.ndarray:
    """Fold-in temporary user vector from (item, strength) pairs
    (EstimateForAnonymous.buildTemporaryUserVector:73-87)."""
    solver = model.get_yty_solver()
    if solver is None:
        raise OryxServingException(503, "model not yet loaded")
    xu = None
    for item, value in pairs:
        yi = model.get_item_vector(item)
        if yi is None:
            continue
        updated = compute_updated_xu(solver, value, xu, yi, model.implicit)
        if updated is not None:
            xu = updated
    if xu is None:
        raise OryxServingException(400, "no valid items")
    return xu


def _page(results: list, how_many: int, offset: int) -> list:
    return results[offset : offset + how_many]


# -- recommendation ----------------------------------------------------------


@resource("GET", "/recommend/{userID}")
def recommend(ctx: ServingContext, req: Request):
    """als/Recommend.java:68-116."""
    model = _model(ctx)
    user = req.params["userID"]
    # reject unknown users before known-items/rescorer work (providers
    # must not be invoked with ids that don't exist)
    if model.get_user_vector(user) is None:
        raise OryxServingException(404, f"unknown user {user}")
    how_many, offset = _paging(req)
    consider_known = req.q_bool("considerKnownItems", False)
    exclude = set() if consider_known else model.get_known_items(user)
    rescorer = _rescorer(ctx, "recommend", req, [user])
    # top_n_for_user ships an int32 row index when the user is staged on
    # device (index submit)
    results = model.top_n_for_user(
        user, how_many + offset, exclude=exclude, rescorer=rescorer
    )
    if results is None:  # removed between the check and the scan
        raise OryxServingException(404, f"unknown user {user}")
    return [IDValue(i, v) for i, v in _page(results, how_many, offset)]


@resource("GET", "/recommendToMany/{userIDs:+}")
def recommend_to_many(ctx: ServingContext, req: Request):
    """Mean of the users' vectors (als/RecommendToMany.java:57)."""
    model = _model(ctx)
    users = req.params["userIDs"]
    vectors = [model.get_user_vector(u) for u in users]
    vectors = [v for v in vectors if v is not None]
    if not vectors:
        raise OryxServingException(404, "no known users")
    xu = np.mean(vectors, axis=0)
    how_many, offset = _paging(req)
    consider_known = req.q_bool("considerKnownItems", False)
    exclude = set()
    if not consider_known:
        for u in users:
            exclude |= model.get_known_items(u)
    rescorer = _rescorer(ctx, "recommend", req, users)
    results = model.top_n(xu, how_many + offset, exclude=exclude, rescorer=rescorer)
    return [IDValue(i, v) for i, v in _page(results, how_many, offset)]


@resource("GET", "/recommendToAnonymous/{itemValuePairs:+}")
def recommend_to_anonymous(ctx: ServingContext, req: Request):
    """Fold-in vector from item interactions (als/RecommendToAnonymous.java:59)."""
    model = _model(ctx)
    pairs = _parse_item_value_pairs(req.params["itemValuePairs"])
    xu = _anonymous_user_vector(model, pairs)
    how_many, offset = _paging(req)
    exclude = {i for i, _ in pairs}
    rescorer = _rescorer(ctx, "anonymous", req, [i for i, _ in pairs])
    results = model.top_n(xu, how_many + offset, exclude=exclude, rescorer=rescorer)
    return [IDValue(i, v) for i, v in _page(results, how_many, offset)]


@resource("GET", "/recommendWithContext/{userID}/{itemValuePairs:+}")
def recommend_with_context(ctx: ServingContext, req: Request):
    """User vector nudged by recent context items
    (als/RecommendWithContext.java:59)."""
    model = _model(ctx)
    user = req.params["userID"]
    xu = model.get_user_vector(user)
    if xu is None:
        raise OryxServingException(404, f"unknown user {user}")
    pairs = _parse_item_value_pairs(req.params["itemValuePairs"])
    solver = model.get_yty_solver()
    if solver is None:
        raise OryxServingException(503, "model not yet loaded")
    for item, value in pairs:
        yi = model.get_item_vector(item)
        if yi is None:
            continue
        updated = compute_updated_xu(solver, value, xu, yi, model.implicit)
        if updated is not None:
            xu = updated
    how_many, offset = _paging(req)
    exclude = model.get_known_items(user) | {i for i, _ in pairs}
    rescorer = _rescorer(ctx, "recommend", req, [user])
    results = model.top_n(xu, how_many + offset, exclude=exclude, rescorer=rescorer)
    return [IDValue(i, v) for i, v in _page(results, how_many, offset)]


# -- similarity --------------------------------------------------------------


@resource("GET", "/similarity/{itemIDs:+}")
def similarity(ctx: ServingContext, req: Request):
    """Average-cosine similar items (als/Similarity.java:60,
    CosineAverageFunction.java). Scored on device: candidates ranked by
    cosine against the mean of the normalized query vectors."""
    model = _model(ctx)
    items = req.params["itemIDs"]
    vecs = []
    for i in items:
        v = model.get_item_vector(i)
        if v is not None:
            n = np.linalg.norm(v)
            if n > 0:
                vecs.append(v / n)
    if not vecs:
        raise OryxServingException(404, "no known items")
    centroid = np.mean(vecs, axis=0)
    how_many, offset = _paging(req)
    rescorer = _rescorer(ctx, "anonymous", req, items)
    results = model.top_n(
        centroid, how_many + offset + len(items), exclude=set(items),
        rescorer=rescorer, cosine=True,
    )
    scale = float(np.linalg.norm(centroid))  # cos(c, mean) * |mean| = avg cosine
    results = [(i, v * scale) for i, v in results]
    return [IDValue(i, v) for i, v in _page(results, how_many, offset)]


@resource("GET", "/similarityToItem/{toItemID}/{itemIDs:+}")
def similarity_to_item(ctx: ServingContext, req: Request):
    """Cosine similarity of each item to one target (als/SimilarityToItem.java:44)."""
    model = _model(ctx)
    to_vec = model.get_item_vector(req.params["toItemID"])
    if to_vec is None:
        raise OryxServingException(404, "unknown item")
    out = []
    for item in req.params["itemIDs"]:
        v = model.get_item_vector(item)
        out.append(cosine_similarity(v, to_vec) if v is not None else 0.0)
    return out


# -- estimates ---------------------------------------------------------------


@resource("GET", "/estimate/{userID}/{itemIDs:+}")
def estimate(ctx: ServingContext, req: Request):
    """Dot-product estimates (als/Estimate.java:51)."""
    model = _model(ctx)
    xu = model.get_user_vector(req.params["userID"])
    if xu is None:
        raise OryxServingException(404, "unknown user")
    out = []
    for item in req.params["itemIDs"]:
        yi = model.get_item_vector(item)
        out.append(float(np.dot(xu, yi)) if yi is not None else 0.0)
    return out


@resource("GET", "/estimateForAnonymous/{toItemID}/{itemValuePairs:+}")
def estimate_for_anonymous(ctx: ServingContext, req: Request):
    """als/EstimateForAnonymous.java:47-87."""
    model = _model(ctx)
    to_vec = model.get_item_vector(req.params["toItemID"])
    if to_vec is None:
        raise OryxServingException(404, "unknown item")
    pairs = _parse_item_value_pairs(req.params["itemValuePairs"])
    xu = _anonymous_user_vector(model, pairs)
    return float(np.dot(xu, to_vec))


@resource("GET", "/because/{userID}/{itemID}")
def because(ctx: ServingContext, req: Request):
    """Known items most similar to the recommended item — 'why was this
    recommended' (als/Because.java:52)."""
    model = _model(ctx)
    user, item = req.params["userID"], req.params["itemID"]
    yi = model.get_item_vector(item)
    if yi is None:
        raise OryxServingException(404, "unknown item")
    known = model.get_known_items(user)
    if not known:
        raise OryxServingException(404, "no known items for user")
    how_many, offset = _paging(req)
    scored = []
    for k in known:
        v = model.get_item_vector(k)
        if v is not None:
            scored.append(IDValue(k, cosine_similarity(v, yi)))
    scored.sort(key=lambda r: -r.value)
    return _page(scored, how_many, offset)


# -- known items / popularity ------------------------------------------------


@resource("GET", "/knownItems/{userID}")
def known_items(ctx: ServingContext, req: Request):
    """als/KnownItems.java:35."""
    model = _model(ctx)
    return sorted(model.get_known_items(req.params["userID"]))


@resource("GET", "/mostActiveUsers")
def most_active_users(ctx: ServingContext, req: Request):
    """Users by known-item count (als/MostActiveUsers.java:47)."""
    model = _model(ctx)
    how_many, offset = _paging(req)
    rescorer = _rescorer(ctx, "active", req)
    counts = model.get_known_item_counts()
    return _top_counts(counts, how_many, offset, rescorer)


@resource("GET", "/mostPopularItems")
def most_popular_items(ctx: ServingContext, req: Request):
    """Items by how many users know them (als/MostPopularItems.java:52)."""
    model = _model(ctx)
    how_many, offset = _paging(req)
    rescorer = _rescorer(ctx, "popular", req)
    return _top_counts(model.get_item_counts(), how_many, offset, rescorer)


def _top_counts(counts: dict[str, int], how_many, offset, rescorer):
    """Rescorers filter candidates only; counts stay raw counts (the
    reference's mapTopCountsToIDCounts behavior)."""
    entries = [
        IDCount(id_, c)
        for id_, c in counts.items()
        if rescorer is None or not rescorer.is_filtered(id_)
    ]
    entries.sort(key=lambda e: (-e.count, e.id))
    return _page(entries, how_many, offset)


@resource("GET", "/mostSurprising/{userID}")
def most_surprising(ctx: ServingContext, req: Request):
    """Known items with the LOWEST estimated strength — interactions the
    model least expects (als/MostSurprising.java:54)."""
    model = _model(ctx)
    user = req.params["userID"]
    xu = model.get_user_vector(user)
    if xu is None:
        raise OryxServingException(404, "unknown user")
    known = model.get_known_items(user)
    how_many, offset = _paging(req)
    scored = []
    for k in known:
        v = model.get_item_vector(k)
        if v is not None:
            scored.append(IDValue(k, float(np.dot(xu, v))))
    scored.sort(key=lambda r: r.value)
    return _page(scored, how_many, offset)


@resource("GET", "/popularRepresentativeItems")
def popular_representative_items(ctx: ServingContext, req: Request):
    """A small diverse sample of items: the max-dot item along each of
    `features` random hyperplanes (als/PopularRepresentativeItems.java:43
    picks one item per LSH partition; random projections give the same
    'spread across item space' without LSH state)."""
    model = _model(ctx)
    ids, _, uploaded, _y_host, _parts = model._ensure_y_matrix()
    if not ids:
        return []
    from oryx_tpu.common import rng as rng_mod
    from oryx_tpu.ops import topn as topn_ops

    gen = rng_mod.get_random()
    out = []
    seen = set()
    for _ in range(model.features):
        probe = gen.standard_normal(model.features).astype(np.float32)
        idx, _scores = topn_ops.top_k_scores(uploaded, probe, 1)
        id_ = ids[int(idx[0])]
        if id_ not in seen:
            seen.add(id_)
            out.append(id_)
    return out


@resource("GET", "/item/allIDs")
def all_item_ids(ctx: ServingContext, req: Request):
    """als/AllItemIDs.java:34."""
    return sorted(_model(ctx).all_item_ids())


@resource("GET", "/user/allIDs")
def all_user_ids(ctx: ServingContext, req: Request):
    """als/AllUserIDs.java:34."""
    return sorted(_model(ctx).all_user_ids())


# -- writes ------------------------------------------------------------------


@resource("POST", "/pref/{userID}/{itemID}")
def set_preference(ctx: ServingContext, req: Request):
    """Body is the strength value; writes a 'user,item,value' input event
    (als/Preference.java:42-62)."""
    check_not_read_only(ctx)
    user, item = req.params["userID"], req.params["itemID"]
    body = req.text().strip()
    value = 1.0 if not body else _parse_float(body)
    send_input(ctx, join_csv([user, item, value]))
    return Response(204)


@resource("DELETE", "/pref/{userID}/{itemID}")
def delete_preference(ctx: ServingContext, req: Request):
    """Empty value = delete marker (als/Preference.java)."""
    check_not_read_only(ctx)
    user, item = req.params["userID"], req.params["itemID"]
    send_input(ctx, join_csv([user, item, ""]))
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is not None:
        model.remove_known_item(user, item)
    return Response(204)


def _parse_float(s: str) -> float:
    try:
        v = float(s)
    except ValueError:
        raise OryxServingException(400, f"bad value {s!r}")
    if math.isnan(v) or math.isinf(v):
        raise OryxServingException(400, f"bad value {s!r}")
    return v


@resource("POST", "/ingest")
def ingest(ctx: ServingContext, req: Request):
    """Bulk input: text, gzip, zip, or multipart (als/Ingest.java:61-72)."""
    check_not_read_only(ctx)
    for line in read_ingest_lines(req):
        send_input(ctx, line)
    return Response(204)


# ---------------------------------------------------------------------------
# Console (als/Console.java:28 — HTML page at / and /index.html)
# ---------------------------------------------------------------------------

from oryx_tpu.serving.console import ConsoleForm, console_response, render_console  # noqa: E402

_CONSOLE_FORMS = [
    ConsoleForm("Recommend to a user", "GET", "/recommend/{userID}",
                query=("howMany", "offset", "considerKnownItems")),
    ConsoleForm("Recommend to many users", "GET", "/recommendToMany/{userIDs:+}",
                query=("howMany", "considerKnownItems"), note="separate user IDs with /"),
    ConsoleForm("Recommend to anonymous", "GET", "/recommendToAnonymous/{itemValuePairs:+}",
                query=("howMany",), note="item=value pairs separated with /"),
    ConsoleForm("Similar items", "GET", "/similarity/{itemIDs:+}", query=("howMany",)),
    ConsoleForm("Similarity to item", "GET", "/similarityToItem/{toItemID}/{itemIDs:+}"),
    ConsoleForm("Estimate preference", "GET", "/estimate/{userID}/{itemIDs:+}"),
    ConsoleForm("Because", "GET", "/because/{userID}/{itemID}", query=("howMany",)),
    ConsoleForm("Known items", "GET", "/knownItems/{userID}"),
    ConsoleForm("Most popular items", "GET", "/mostPopularItems", query=("howMany",)),
    ConsoleForm("Most active users", "GET", "/mostActiveUsers", query=("howMany",)),
    ConsoleForm("Set preference", "POST", "/pref/{userID}/{itemID}", body=True,
                note="optional strength value in the body"),
    ConsoleForm("Ingest", "POST", "/ingest", body=True,
                note="user,item,strength CSV lines"),
    ConsoleForm("Ready?", "GET", "/ready"),
]

_CONSOLE_HTML = render_console("Oryx ALS serving console", _CONSOLE_FORMS)


@resource("GET", "/")
@resource("GET", "/index.html")
def console(ctx: ServingContext, req: Request):
    return console_response(_CONSOLE_HTML)
