"""ALS speed layer: incremental fold-in updates.

Rebuild of ALSSpeedModel (app/oryx-app/.../speed/als/ALSSpeedModel.java:
35-151) and ALSSpeedModelManager (.../ALSSpeedModelManager.java:51-217):
the model holds X/Y FeatureVectors plus the expected-ID sets from the
last batch MODEL (for load-fraction accounting), with cached XtX / YtY
solvers; per micro-batch, each aggregated (user,item,value) event updates
BOTH the user vector (against YtY) and the item vector (against XtX) via
the ALSUtils fold-in, publishing ["X",user,vec[,knownItems]] /
["Y",item,vec[,knownUsers]] deltas.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
from collections import deque
from typing import Iterable, Iterator

import numpy as np

from oryx_tpu.api.speed import SpeedModel, SpeedModelManager
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.als import data as als_data
from oryx_tpu.app.als.common import apply_up_lines, consume_blocks_columnar
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.records import InteractionBlock, Records
from oryx_tpu.common.text import json_str as _json_str, read_json
from oryx_tpu.common.vectormath import Solver, SingularMatrixSolverException, get_solver
from oryx_tpu.native.store import (
    format_update_messages,
    format_update_messages_multi,
    format_vectors_json,
    make_feature_vectors,
)

log = logging.getLogger(__name__)

# parse_batch may legitimately return None (empty batch), so the native
# parser signals "run the Python path instead" with a distinct sentinel
_NATIVE_DECLINED = object()


class ALSSpeedModel(SpeedModel):
    def __init__(
        self,
        features: int,
        implicit: bool,
        expected_user_ids: set[str],
        expected_item_ids: set[str],
    ) -> None:
        self.features = features
        self.implicit = implicit
        self.x = make_feature_vectors()
        self.y = make_feature_vectors()
        self._expected_users = set(expected_user_ids)
        self._expected_items = set(expected_item_ids)
        self._solver_lock = threading.Lock()
        self._xtx_solver: Solver | None = None
        self._yty_solver: Solver | None = None

    def set_user_vector(self, user: str, vector: np.ndarray) -> None:
        self.x.set_vector(user, vector)
        self._expected_users.discard(user)
        with self._solver_lock:
            self._xtx_solver = None

    def set_item_vector(self, item: str, vector: np.ndarray) -> None:
        self.y.set_vector(item, vector)
        self._expected_items.discard(item)
        with self._solver_lock:
            self._yty_solver = None

    def set_user_vectors(self, users: list[str], vectors: np.ndarray) -> None:
        """Batched set: one native store call, one expected-set update and
        one solver invalidation for the whole batch (the per-record form
        pays all three per delta — ruinous at 100K+ self-consumed
        deltas/s)."""
        self.x.set_batch(users, vectors)
        self._expected_users.difference_update(users)
        with self._solver_lock:
            self._xtx_solver = None

    def set_item_vectors(self, items: list[str], vectors: np.ndarray) -> None:
        self.y.set_batch(items, vectors)
        self._expected_items.difference_update(items)
        with self._solver_lock:
            self._yty_solver = None

    def get_xtx_solver(self) -> Solver | None:
        with self._solver_lock:
            if self._xtx_solver is None:
                self._xtx_solver = get_solver(self.x.get_vtv())
            return self._xtx_solver

    def get_yty_solver(self) -> Solver | None:
        with self._solver_lock:
            if self._yty_solver is None:
                self._yty_solver = get_solver(self.y.get_vtv())
            return self._yty_solver

    def retain_recent_and_ids(self, user_ids: set[str], item_ids: set[str]) -> None:
        self.x.retain_recent_and_ids(user_ids)
        self.y.retain_recent_and_ids(item_ids)
        # rotation changes both stores: cached Gramian solvers are stale
        with self._solver_lock:
            self._xtx_solver = None
            self._yty_solver = None

    def get_fraction_loaded(self) -> float:
        """Loaded fraction vs expected IDs (ALSSpeedModel.java:128-142)."""
        expected = len(self._expected_users) + len(self._expected_items)
        loaded = self.x.size() + self.y.size()
        if expected + loaded == 0:
            return 1.0
        return loaded / (loaded + expected)

    def __repr__(self) -> str:  # pragma: no cover
        return f"ALSSpeedModel[features={self.features}, X={self.x.size()}, Y={self.y.size()}]"


class ALSSpeedModelManager(SpeedModelManager):
    def __init__(self, config: Config) -> None:
        self.implicit = config.get_bool("oryx.als.implicit")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.fold_backend = config.get_string("oryx.speed.fold-in-backend")
        self.self_apply = config.get_bool("oryx.speed.self-apply")
        # byte-encoded copies of this instance's own published deltas,
        # publish order; the consume thread skips exact matches instead
        # of re-parsing them (the vectors were applied at build time).
        # Bounded: overflow just means those messages get re-applied.
        self._self_pending: deque[bytes] = deque()
        self._self_pending_cap = 600_000
        self.min_model_load_fraction = config.get_float(
            "oryx.speed.min-model-load-fraction"
        )
        if not 0.0 <= self.min_model_load_fraction <= 1.0:
            raise ValueError("oryx.speed.min-model-load-fraction must be in [0,1]")
        self.native_parse = config.get_bool("oryx.speed.parse.native")
        threads = config.get_optional_int("oryx.speed.parse.threads") or 0
        self.parse_threads = threads if threads > 0 else (os.cpu_count() or 1)
        # sharded pipeline state: shard count (configure_sharding), and the
        # shared PartitionedFoldInSession bound to the current Solver pair.
        # _fold_lock guards the (solvers, session) swap; each shard then
        # works its private slice without further synchronization.
        self._shards = 1
        self._fold_lock = threading.Lock()
        self._part_session = None
        self._part_session_solvers: tuple | None = None
        self.model: ALSSpeedModel | None = None

    def configure_sharding(self, shards: int) -> None:
        """Declare that ``shards`` pipeline chains will call
        :meth:`fold_parsed` concurrently (shard-private fold slices over
        one shared Gramian pair). With more than one shard the
        self-pending skip queue is retired: its exact-byte matching
        assumes this instance's publishes hit the UP partition in fold
        order, which concurrent per-shard publishers no longer guarantee
        — unmatched self-deltas simply re-apply (absolute vectors,
        idempotent). Native parse threads are divided among shards so K
        pinned chains don't oversubscribe the cores K-fold."""
        with self._fold_lock:
            self._shards = max(1, int(shards))
            shards = self._shards
        if shards > 1:
            self._self_pending_cap = 0
            self._self_pending.clear()
            self.parse_threads = max(1, self.parse_threads // shards)

    # -- update-topic consumption (ALSSpeedModelManager.consume:74-126) ------

    def consume_blocks(self, block_iterator) -> None:
        """Columnar consume: contiguous runs of "UP" records parse as one
        vectorized batch (the shared ``apply_up_lines`` fast path) and
        apply via the batched setters. Everything else — MODEL/MODEL-REF,
        escaped ids, malformed lines — falls back to the per-record
        consume in order."""
        consume_blocks_columnar(
            block_iterator,
            lambda: self.model is not None,
            self._apply_up_batch,
            self.consume,
        )

    def _apply_up_batch(self, lines: list[bytes]) -> None:
        pending = self._self_pending
        if pending:
            # skip this instance's own deltas coming back around the
            # topic: exact byte match against the publish-ordered queue
            # (single UP partition preserves order). Anything unmatched —
            # another producer's message, a rotation in between — applies
            # normally; a missed match merely re-applies an absolute
            # vector, which is idempotent.
            # fast path: the block is exactly the next run of our own
            # deltas (single UP partition, publish order) — one C-level
            # list compare instead of a deque pop + compare per record
            m = min(len(lines), len(pending))
            if lines[:m] == list(itertools.islice(pending, m)):
                for _ in range(m):
                    pending.popleft()
                lines = lines[m:]
            else:
                rest: list[bytes] = []
                for ln in lines:
                    if pending and ln == pending[0]:
                        pending.popleft()
                    else:
                        rest.append(ln)
                lines = rest
            if not lines:
                return
        model = self.model
        apply_up_lines(
            lines,
            model.features,
            model.set_user_vectors,
            model.set_item_vectors,
            lambda km: self.consume(iter([km])),
        )

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            key, message = km.key, km.message
            if key == "UP":
                if self.model is None:
                    continue  # no model to interpret against yet
                update = read_json(message)
                which, id_ = update[0], str(update[1])
                vector = np.asarray(update[2], dtype=np.float32)
                if which == "X":
                    self.model.set_user_vector(id_, vector)
                elif which == "Y":
                    self.model.set_item_vector(id_, vector)
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                features = int(app_pmml.get_required_extension_value(pmml, "features"))
                implicit = app_pmml.get_required_extension_value(pmml, "implicit") == "true"
                x_ids = set(app_pmml.get_extension_content(pmml, "XIDs") or [])
                y_ids = set(app_pmml.get_extension_content(pmml, "YIDs") or [])
                if (
                    self.model is None
                    or self.model.features != features
                    or self.model.implicit != implicit
                ):
                    self.model = ALSSpeedModel(features, implicit, x_ids, y_ids)
                else:
                    # same config: rotate, keeping recent writes + new model IDs
                    self.model.retain_recent_and_ids(x_ids, y_ids)
                # queued self-delta bytes predate this MODEL: their vectors
                # were applied to (or rotated out of) the pre-model state,
                # so skipping their round-trips now would drop legitimate
                # re-applications onto the fresh/rotated stores — and any
                # stale head blocks exact-match skips of post-model deltas
                self._self_pending.clear()
            else:
                raise ValueError(f"bad key {key}")

    # -- micro-batch deltas (ALSSpeedModelManager.buildUpdates:135-205) ------

    def build_updates(self, new_data: Iterable[KeyMessage]) -> Iterable[str]:
        model = self.model
        # fold-ins against a half-replayed model would publish junk deltas
        # (ALSSpeedModelManager.buildUpdates:136-138 gates identically)
        if model is None or model.get_fraction_loaded() < self.min_model_load_fraction:
            return []
        return self.fold_parsed(self.parse_batch(new_data))

    def parse_batch(self, new_data: Iterable[KeyMessage]):
        """Stage 1 of the staged micro-batch: parse + aggregate the raw
        events into a RatingMatrix. Model-independent, so the pipelined
        layer can run it on the parse worker while the fold worker is
        still busy with the previous batch. Returns None when the batch
        holds no events.

        Typed :class:`InteractionBlock` batches (binary columnar bus
        frames) skip text entirely — int codes flow straight into the
        shared aggregate core; a batch mixing typed and text blocks (or
        typed blocks with differing prefixes/timestamp presence) falls
        back through the blocks' rendered ``messages``, which is the
        exact same wire text the producer would have sent line-framed.
        """
        if isinstance(new_data, Records):
            blocks = list(new_data.blocks())
            if blocks and all(isinstance(b, InteractionBlock) for b in blocks):
                first = blocks[0]
                has_ts = first.timestamps is not None
                if all(
                    b.user_prefix == first.user_prefix
                    and b.item_prefix == first.item_prefix
                    and (b.timestamps is not None) == has_ts
                    for b in blocks
                ):
                    if len(blocks) == 1:
                        users, items, values = first.users, first.items, first.values
                        ts = first.timestamps
                    else:
                        users = np.concatenate([b.users for b in blocks])
                        items = np.concatenate([b.items for b in blocks])
                        values = np.concatenate([b.values for b in blocks])
                        ts = (
                            np.concatenate([b.timestamps for b in blocks])
                            if has_ts
                            else None
                        )
                    return als_data.rating_matrix_from_int_columns(
                        users, items, values, ts, self.implicit,
                        first.user_prefix, first.item_prefix,
                    )
            # native columnar parse: one GIL-released C++ pass per text
            # block straight to typed int columns (bit-identical to the
            # numpy path or it declines and we fall through)
            if self.native_parse:
                rm = self._parse_text_native([b.messages for b in blocks])
                if rm is not _NATIVE_DECLINED:
                    return rm
            # columnar text parse + aggregate: one numpy pass over the
            # micro-batch (same semantics as parse_interactions +
            # aggregate; the indexed form gives aggregated (user, item,
            # value) triples directly)
            cols = als_data.concat_columns(
                [als_data.parse_interaction_block(b.messages) for b in blocks]
            )
        else:
            msgs = [
                (km if isinstance(km, str) else km.message).encode("utf-8")
                for km in new_data
            ]
            if not msgs:
                return None
            if self.native_parse:
                rm = self._parse_text_native([msgs])
                if rm is not _NATIVE_DECLINED:
                    return rm
            cols = als_data.parse_interaction_block(msgs)
        rm = als_data.rating_matrix_from_columns(cols, self.implicit)
        return rm if len(rm.values) else None

    def _parse_text_native(self, message_arrays: list):
        """Native-parse every text block to typed int columns and build
        the RatingMatrix through the int fast path. Returns the sentinel
        ``_NATIVE_DECLINED`` when any block (or the library) declines —
        the caller then runs the Python parser for the WHOLE batch, so
        edge semantics (quotes, malformed-line ValueError, mixed
        prefixes) stay byte-for-byte Python's."""
        from oryx_tpu.native import parse as native_parse

        parts = []
        for msgs in message_arrays:
            if len(msgs) == 0:
                continue
            out = native_parse.parse_text_columns(msgs, threads=self.parse_threads)
            if out is None:
                return _NATIVE_DECLINED
            if parts and (
                out.user_prefix != parts[0].user_prefix
                or out.item_prefix != parts[0].item_prefix
            ):
                return _NATIVE_DECLINED  # blocks disagree on the prefixes
            parts.append(out)
        if not parts:
            return None  # no events in the batch
        if len(parts) == 1:
            users, items, values = parts[0].users, parts[0].items, parts[0].values
            ts = parts[0].timestamps
        else:
            users = np.concatenate([p.users for p in parts])
            items = np.concatenate([p.items for p in parts])
            values = np.concatenate([p.values for p in parts])
            ts = (
                np.concatenate(
                    [
                        p.timestamps
                        if p.timestamps is not None
                        else np.zeros(len(p.users), np.int64)
                        for p in parts
                    ]
                )
                if any(p.timestamps is not None for p in parts)
                else None
            )
        rm = als_data.rating_matrix_from_int_columns(
            users, items, values, ts, self.implicit,
            parts[0].user_prefix, parts[0].item_prefix,
        )
        return rm if len(rm.values) else None

    def _device_gramian(self, solver: Solver):
        """The solver's Gramian as a cached device array: solver caches
        invalidate exactly when the Gramian changes (writes, rotation),
        so a fresh Solver is the only event that re-pays the upload."""
        from oryx_tpu.ops import als as als_ops

        g = getattr(solver, "_device_gramian_cache", None)
        if g is None:
            g = als_ops.device_gramian(solver.matrix)
            solver._device_gramian_cache = g
        return g

    def _fold_session(self, yty: Solver, xtx: Solver, n: int, k: int, shard: int):
        """Shard ``shard``'s private fold-in slice over the shared
        :class:`~oryx_tpu.ops.als.PartitionedFoldInSession`. The session
        is bound to the current Solver PAIR (held by identity — solver
        caches invalidate exactly when the Gramians change, so a new pair
        means rebuild + one fresh device upload shared by all shards);
        only the pair swap is locked, the returned slice is touched by
        its shard alone."""
        from oryx_tpu.ops import als as als_ops

        with self._fold_lock:
            ps = self._part_session
            solvers = self._part_session_solvers
            if (
                ps is None
                or ps.shards != self._shards
                or solvers is None
                or solvers[0] is not yty
                or solvers[1] is not xtx
            ):
                ps = als_ops.PartitionedFoldInSession(
                    yty.matrix, xtx.matrix, self.implicit, self._shards,
                    backend=self.fold_backend,
                )
                if ps.resolved_backend(n, k) == "device":
                    # device-resident Gramians: uploaded once per Solver
                    # pair (i.e. only when vector writes or a rotation
                    # invalidated the cache) and shared by every shard's
                    # slice. Host/auto folds keep the float64 originals —
                    # their Cholesky runs in f64, and the device path
                    # casts to f32 regardless, so results are
                    # bit-identical to the unbatched fold either way.
                    ps.set_gramians(
                        self._device_gramian(yty), self._device_gramian(xtx)
                    )
                self._part_session = ps
                self._part_session_solvers = (yty, xtx)
        return ps.session(shard)

    def fold_parsed(self, rm, shard: int = 0) -> list[str]:
        """Stage 2: fold an aggregated RatingMatrix into the live model
        and render the update messages. Re-checks the load-fraction gate
        (the pipeline parses ahead of the model becoming ready). In the
        sharded pipeline each chain passes its ``shard`` index and folds
        its slice concurrently with the others."""
        model = self.model
        if rm is None or len(rm.values) == 0:
            return []
        if model is None or model.get_fraction_loaded() < self.min_model_load_fraction:
            return []
        try:
            yty = model.get_yty_solver()
            xtx = model.get_xtx_solver()
        except SingularMatrixSolverException as e:
            log.warning("model too degenerate to fold in updates: %s", e)
            return []
        if yty is None or xtx is None:
            return []
        # One data-parallel call for the whole micro-batch: every event
        # reads pre-batch state (updates travel via the update topic), so
        # there is no sequential dependency to honor — same contract as the
        # reference's parallelStream, but as a single batched solve. The
        # vector fetch and update serialization are likewise batched (one
        # native call each) — the per-event hot path has no Python in it.
        n = len(rm.values)
        # vocab-level gather: one native fetch per UNIQUE id, expanded to
        # per-event rows by a fancy-index copy — the store pays |vocab|
        # hash lookups and one id-payload pack instead of one per event
        user_ids_arr = np.asarray(rm.user_ids, dtype=object)
        item_ids_arr = np.asarray(rm.item_ids, dtype=object)
        xu_vocab, xu_ok = model.x.get_batch(user_ids_arr.tolist(), dim=model.features)
        yi_vocab, yi_ok = model.y.get_batch(item_ids_arr.tolist(), dim=model.features)
        xu, xu_valid = xu_vocab[rm.user_idx], xu_ok[rm.user_idx]
        yi, yi_valid = yi_vocab[rm.item_idx], yi_ok[rm.item_idx]
        values = rm.values
        session = self._fold_session(yty, xtx, n, model.features, shard)
        session.add_block(xu, xu_valid, yi, yi_valid, values)
        new_xu, x_upd, new_yi, y_upd = session.solve()
        x_rows = np.nonzero(x_upd)[0]
        y_rows = np.nonzero(y_upd)[0]
        known = not self.no_known_items
        # Coalesce per id before publishing: every event's update is an
        # ABSOLUTE vector computed from pre-batch state, so within one
        # micro-batch the last successful update per id fully determines
        # the applied end state — every consumer (speed self-consume,
        # serving, batch replay) applies set_*_vector last-wins. One
        # message per updated id (the last event's vector, X known-items
        # = union over the id's updated events) reaches the same state
        # with ~half the publish/apply/bus-byte cost at duplicate-heavy
        # event rates. (The reference publishes one message per event —
        # toUpdateJSON per parallelStream element — because its updates
        # evolve sequentially; batched pre-state fold-in has no such
        # intermediate states to preserve.)
        ux = rm.user_idx[x_rows]
        last_x = np.full(len(rm.user_ids), -1, np.int64)
        last_x[ux] = x_rows
        keep_users = np.nonzero(last_x >= 0)[0]
        rows_x = last_x[keep_users]
        iy = rm.item_idx[y_rows]
        last_y = np.full(len(rm.item_ids), -1, np.int64)
        last_y[iy] = y_rows
        keep_items = np.nonzero(last_y >= 0)[0]
        rows_y = last_y[keep_items]
        x_ids = user_ids_arr[keep_users].tolist()
        y_ids = item_ids_arr[keep_items].tolist()
        def group_other_ids(own_idx, other_names):
            """Per kept own-id, the (insertion-ordered, deduped) other ids
            of its updated events: one sort, then per-group dedupe."""
            order = np.argsort(own_idx, kind="stable")
            so = own_idx[order]
            names = other_names[order]
            if not len(so):
                return []
            bounds = np.nonzero(np.r_[True, so[1:] != so[:-1]])[0]
            ends_ = np.r_[bounds[1:], len(so)]
            return [
                list(dict.fromkeys(names[s:e].tolist())) for s, e in zip(bounds, ends_)
            ]

        known_lists: list[list[str]] = []
        y_known: list[list[str]] = []
        if known:
            # both sides union their events' counterpart ids (the X list
            # feeds serving known-items; the Y list keeps the per-event
            # wire contract's information for external subscribers)
            known_lists = group_other_ids(ux, item_ids_arr[rm.item_idx[x_rows]])
            y_known = group_other_ids(iy, user_ids_arr[rm.user_idx[y_rows]])
            x_msgs = format_update_messages_multi(new_xu[rows_x], x_ids, known_lists, "X")
            y_msgs = format_update_messages_multi(new_yi[rows_y], y_ids, y_known, "Y")
        else:
            x_msgs = format_update_messages(new_xu[rows_x], x_ids, [], "X", False)
            y_msgs = format_update_messages(new_yi[rows_y], y_ids, [], "Y", False)
        if x_msgs is not None and y_msgs is not None:
            out = x_msgs + y_msgs
        else:
            # pure-Python fallback when the native library is unavailable
            out = []
            for i, vec in enumerate(format_vectors_json(new_xu[rows_x])):
                out.append(self._assemble("X", x_ids[i], vec, known_lists[i] if known else None))
            for i, vec in enumerate(format_vectors_json(new_yi[rows_y])):
                out.append(self._assemble("Y", y_ids[i], vec, y_known[i] if known else None))
        if self.self_apply and model is self.model:
            # apply the deltas to this model NOW (they are absolute
            # vectors computed this batch) and queue their encoded forms
            # so the consume thread can skip the round-trip re-parse
            model.set_user_vectors(x_ids, new_xu[rows_x])
            model.set_item_vectors(y_ids, new_yi[rows_y])
            room = self._self_pending_cap - len(self._self_pending)
            if room > 0:
                self._self_pending.extend(m.encode("utf-8") for m in out[:room])
        return out

    def _assemble(
        self, matrix: str, id_: str, vec_json: str, known_ids: list[str] | None
    ) -> str:
        """Splice a pre-formatted vector JSON into the update message
        (["X"|"Y", id, vector(, knownIds)], ALSSpeedModelManager.
        toUpdateJSON:207-215)."""
        id_json = _json_str(id_)
        if known_ids is None:
            return f'["{matrix}",{id_json},{vec_json}]'
        ks = ",".join(_json_str(s) for s in known_ids)
        return f'["{matrix}",{id_json},{vec_json},[{ks}]]'

    def close(self) -> None:
        # drop the device-resident fold-in session: its per-shard Gramian
        # blocks pin HBM until the last reference dies, and a manager that
        # outlives its layer (fleet rotation) would otherwise hold them
        # for the life of the process
        with self._fold_lock:
            self._part_session = None
            self._part_session_solvers = None
