"""ALS input parsing and aggregation.

Rebuild of the data-prep stages of ALSUpdate (app/oryx-app-mllib/.../als/
ALSUpdate.java): input lines are ``user,item,value[,timestamp]`` (CSV or
JSON array; empty value = delete, parsed as NaN, ALSUpdate.java:260-278);
time-decay multiplies old strengths by factor^days (decayRating:292-298)
then prunes below the zero threshold; aggregation combines repeated
(user,item) pairs — implicit: sum with NaN poisoning (delete wins over
the aggregate, MLFunctions.SUM_WITH_NAN), explicit: last value in
timestamp order wins (aggregateScores:332-352) — and NaN aggregates are
dropped (deletes).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Iterable, NamedTuple

import numpy as np

from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.text import parse_line


class Interaction(NamedTuple):
    user: str
    item: str
    value: float  # NaN = delete marker
    timestamp_ms: int


_nan = math.nan


def parse_interactions(data: Iterable[KeyMessage | str]) -> list[Interaction]:
    """Parse lines, in input order. Lines missing a timestamp get 0 so
    pure-CSV triples still work in time-ordered contexts. Plain unquoted
    CSV (the wire-format fast path at 100k-event micro-batches) parses
    with a bare split; quoted CSV and JSON arrays go through parse_line."""
    out: list[Interaction] = []
    append = out.append
    for rec in data:
        line = rec if type(rec) is str else rec.message
        s = line.strip()
        if s and s[0] not in "[{" and '"' not in s:
            tokens = s.split(",")
        else:
            tokens = parse_line(s)
        if len(tokens) < 3:
            raise ValueError(f"bad ALS input: {line!r}")
        value = _nan if tokens[2] == "" else float(tokens[2])
        ts = int(float(tokens[3])) if len(tokens) > 3 and tokens[3] != "" else 0
        append(Interaction(tokens[0], tokens[1], value, ts))
    return out


def decay_interactions(
    interactions: list[Interaction],
    factor: float,
    zero_threshold: float,
    now_ms: int | None = None,
) -> list[Interaction]:
    if factor < 1.0:
        now = int(time.time() * 1000) if now_ms is None else now_ms
        decayed = []
        for it in interactions:
            if it.timestamp_ms >= now or math.isnan(it.value):
                decayed.append(it)
            else:
                days = (now - it.timestamp_ms) / 86_400_000.0
                decayed.append(
                    Interaction(it.user, it.item, it.value * factor**days, it.timestamp_ms)
                )
        interactions = decayed
    if zero_threshold > 0.0:
        interactions = [
            it for it in interactions if math.isnan(it.value) or it.value > zero_threshold
        ]
    return interactions


def aggregate(interactions: list[Interaction], implicit: bool) -> dict[tuple[str, str], float]:
    """Combine repeated (user,item) pairs; drop NaN aggregates (deletes)."""
    interactions = sorted(interactions, key=lambda it: it.timestamp_ms)
    agg: dict[tuple[str, str], float] = {}
    for it in interactions:
        key = (it.user, it.item)
        if implicit:
            prev = agg.get(key)
            # NaN anywhere poisons the sum => delete
            agg[key] = it.value if prev is None else prev + it.value
        else:
            agg[key] = it.value  # last wins
    return {k: v for k, v in agg.items() if not math.isnan(v)}


@dataclass
class RatingMatrix:
    """Indexed COO ready for the trainer."""

    user_ids: list[str]
    item_ids: list[str]
    user_idx: np.ndarray  # int32
    item_idx: np.ndarray  # int32
    values: np.ndarray  # float32

    @property
    def known_items(self) -> dict[str, set[str]]:
        """user -> item-id set, grouped with one argsort instead of a
        Python dict op per interaction."""
        if not len(self.user_idx):
            return {}
        order = np.argsort(self.user_idx, kind="stable")
        u_sorted = self.user_idx[order]
        bounds = np.flatnonzero(np.diff(u_sorted)) + 1
        groups = np.split(self.item_idx[order], bounds)
        firsts = u_sorted[np.concatenate(([0], bounds))]
        item_ids = self.item_ids
        return {
            self.user_ids[u]: {item_ids[j] for j in g.tolist()}
            for u, g in zip(firsts.tolist(), groups)
        }


def to_rating_matrix(agg: dict[tuple[str, str], float]) -> RatingMatrix:
    user_ids = sorted({u for u, _ in agg})
    item_ids = sorted({i for _, i in agg})
    u_index = {u: n for n, u in enumerate(user_ids)}
    i_index = {i: n for n, i in enumerate(item_ids)}
    n = len(agg)
    uu = np.empty(n, dtype=np.int32)
    ii = np.empty(n, dtype=np.int32)
    vv = np.empty(n, dtype=np.float32)
    for pos, ((u, i), v) in enumerate(agg.items()):
        uu[pos] = u_index[u]
        ii[pos] = i_index[i]
        vv[pos] = v
    return RatingMatrix(user_ids, item_ids, uu, ii, vv)


# ---------------------------------------------------------------------------
# Columnar (vectorized) pipeline
#
# The per-line functions above are the micro-batch path (speed layer, small
# generations). The batch trainer goes through these instead: whole blocks
# of input lines parse, decay, and aggregate as numpy array operations —
# the single-host stand-in for the reference's distributed RDD pipeline
# (BatchUpdateFunction.java:103-130 + MLFunctions aggregation), and the
# difference between minutes of Python parse and seconds of numpy at
# 100M-rating scale.
# ---------------------------------------------------------------------------


class InteractionColumns(NamedTuple):
    """Parallel arrays of interactions (bytes ids; NaN value = delete)."""

    users: np.ndarray  # S-dtype
    items: np.ndarray  # S-dtype
    values: np.ndarray  # float32
    timestamps: np.ndarray  # int64 ms


_EMPTY_COLUMNS = InteractionColumns(
    np.empty(0, "S1"), np.empty(0, "S1"), np.empty(0, np.float32), np.empty(0, np.int64)
)


def _extract_bytes(arr: np.ndarray, starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Vectorized variable-width substring gather: bytes arr[s:e) per row,
    returned as a fixed-width S array (NUL-padded)."""
    n = len(starts)
    if n == 0:
        return np.empty(0, dtype="S1")
    w = max(1, int(np.max(ends - starts)))
    idx = starts[:, None] + np.arange(w, dtype=np.int64)[None, :]
    mask = idx < ends[:, None]
    flat = np.where(mask, arr[np.minimum(idx, arr.size - 1)], 0).astype(np.uint8)
    return np.ascontiguousarray(flat).view(f"S{w}").ravel()


def parse_interaction_block(messages: np.ndarray | list[bytes]) -> InteractionColumns:
    """Vectorized parse of ``user,item,value[,timestamp]`` lines.

    `messages` is an S-dtype array (or list of bytes) of input lines. The
    whole block is parsed with numpy index arithmetic on one byte blob —
    no Python loop per line. Lines with quotes or JSON arrays fall back to
    the per-line parser (they cannot contain bare delimiter commas).
    """
    if isinstance(messages, np.ndarray):
        lines = messages.tolist()
    else:
        lines = list(messages)
    if not lines:
        return _EMPTY_COLUMNS
    blob = b"\n".join(lines) + b"\n"
    arr = np.frombuffer(blob, dtype=np.uint8)
    ends = np.flatnonzero(arr == 0x0A)
    if len(ends) != len(lines) or np.any(arr == 0x22):  # embedded \n or quote
        return _parse_block_slow(lines)
    starts = np.empty_like(ends)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    first = arr[np.minimum(starts, arr.size - 1)]
    if np.any((first == 0x5B) | (first == 0x7B)):  # [ or { => JSON lines
        return _parse_block_slow(lines)
    commas = np.flatnonzero(arr == 0x2C)
    c_lo = np.searchsorted(commas, starts)
    c_hi = np.searchsorted(commas, ends)
    counts = c_hi - c_lo
    if np.any(counts < 2):
        bad = int(np.argmax(counts < 2))
        raise ValueError(f"bad ALS input: {lines[bad]!r}")
    c1 = commas[c_lo]
    c2 = commas[c_lo + 1]
    has_ts = counts >= 3
    c3 = np.where(has_ts, commas[np.minimum(c_lo + 2, len(commas) - 1)], ends)
    users = _extract_bytes(arr, starts, c1)
    items = _extract_bytes(arr, c1 + 1, c2)
    vf = _extract_bytes(arr, c2 + 1, c3)
    empty_v = c3 == c2 + 1
    if empty_v.any():
        vf = vf.astype(f"S{max(3, vf.dtype.itemsize)}")
        vf[empty_v] = b"nan"  # empty value = delete marker
    try:
        values = vf.astype(np.float64).astype(np.float32)
    except ValueError:
        return _parse_block_slow(lines)  # oddball numerics: per-line errors
    if has_ts.any():
        tf = _extract_bytes(arr, np.where(has_ts, c3 + 1, ends), ends)
        empty_t = ~has_ts | (ends == c3 + 1)
        if empty_t.any():
            tf = tf.astype(f"S{max(1, tf.dtype.itemsize)}")
            tf[empty_t] = b"0"
        try:
            timestamps = tf.astype(np.float64).astype(np.int64)
        except ValueError:
            return _parse_block_slow(lines)
    else:
        timestamps = np.zeros(len(lines), dtype=np.int64)
    return InteractionColumns(users, items, values, timestamps)


def _parse_block_slow(lines: list[bytes]) -> InteractionColumns:
    """Per-line fallback (quoted CSV / JSON arrays) via parse_interactions."""
    inter = parse_interactions([ln.decode("utf-8", "replace") for ln in lines])
    return InteractionColumns(
        np.array([it.user.encode("utf-8") for it in inter], dtype="S"),
        np.array([it.item.encode("utf-8") for it in inter], dtype="S"),
        np.array([it.value for it in inter], dtype=np.float32),
        np.array([it.timestamp_ms for it in inter], dtype=np.int64),
    )


def concat_columns(parts: list[InteractionColumns]) -> InteractionColumns:
    parts = [p for p in parts if len(p.values)]
    if not parts:
        return _EMPTY_COLUMNS
    if len(parts) == 1:
        return parts[0]
    return InteractionColumns(
        np.concatenate([p.users for p in parts]),
        np.concatenate([p.items for p in parts]),
        np.concatenate([p.values for p in parts]),
        np.concatenate([p.timestamps for p in parts]),
    )


def decay_columns(
    cols: InteractionColumns,
    factor: float,
    zero_threshold: float,
    now_ms: int | None = None,
) -> InteractionColumns:
    """Vectorized twin of decay_interactions."""
    users, items, values, ts = cols
    if factor < 1.0 and len(values):
        now = int(time.time() * 1000) if now_ms is None else now_ms
        old = (ts < now) & ~np.isnan(values)
        if old.any():
            days = (now - ts[old]).astype(np.float64) / 86_400_000.0
            values = values.copy()
            values[old] = (values[old].astype(np.float64) * factor**days).astype(
                np.float32
            )
    if zero_threshold > 0.0 and len(values):
        keep = np.isnan(values) | (values > zero_threshold)
        if not keep.all():
            users, items, values, ts = users[keep], items[keep], values[keep], ts[keep]
    return InteractionColumns(users, items, values, ts)


def _aggregate_indexed(uinv, n_items, iinv, values, ts, implicit):
    """The shared aggregate core: pair (user,item) codes, combine repeated
    pairs (implicit: float64 sum with NaN poisoning; explicit: last in
    (timestamp, arrival) order wins), drop NaN aggregates (deletes).
    Returns (surviving user codes, item codes, aggregated values)."""
    n = len(values)
    pair = uinv.astype(np.int64) * n_items + iinv.astype(np.int64)
    pq, pinv = np.unique(pair, return_inverse=True)
    if implicit:
        agg = np.bincount(pinv, weights=values.astype(np.float64), minlength=len(pq))
        agg = agg.astype(np.float32)
    else:
        # group by pair, ordered by (timestamp, arrival); last of each wins
        order = np.lexsort((np.arange(n), ts, pinv))
        sp = pinv[order]
        last = np.empty(len(sp), dtype=bool)
        last[:-1] = sp[:-1] != sp[1:]
        last[-1] = True
        agg = values[order][last]
    keep = ~np.isnan(agg)
    pq, agg = pq[keep], agg[keep]
    return pq // n_items, pq % n_items, agg


def rating_matrix_from_columns(cols: InteractionColumns, implicit: bool) -> RatingMatrix:
    """Vectorized aggregate + index: same semantics as
    ``to_rating_matrix(aggregate(...))`` — implicit sums with NaN
    poisoning, explicit last-in-timestamp-order wins, NaN aggregates
    (deletes) dropped, vocab built from surviving pairs only."""
    users, items, values, ts = cols
    n = len(values)
    if n == 0:
        return RatingMatrix([], [], np.empty(0, np.int32), np.empty(0, np.int32), np.empty(0, np.float32))
    uq, uinv = np.unique(users, return_inverse=True)
    iq, iinv = np.unique(items, return_inverse=True)
    uu_codes, ii_codes, agg = _aggregate_indexed(uinv, len(iq), iinv, values, ts, implicit)
    u_used, uu = np.unique(uu_codes, return_inverse=True)
    i_used, ii = np.unique(ii_codes, return_inverse=True)
    user_ids = [b.decode("utf-8", "replace") for b in uq[u_used].tolist()]
    item_ids = [b.decode("utf-8", "replace") for b in iq[i_used].tolist()]
    return RatingMatrix(
        user_ids,
        item_ids,
        uu.astype(np.int32),
        ii.astype(np.int32),
        agg.astype(np.float32),
    )


def rating_matrix_from_int_columns(
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    timestamps: np.ndarray | None,
    implicit: bool,
    user_prefix: bytes = b"u",
    item_prefix: bytes = b"i",
) -> RatingMatrix:
    """Typed-transport twin of :func:`rating_matrix_from_columns`: int32 id
    codes straight off a columnar bus frame, aggregated by the SAME core.
    The S-id path would render "u%d"/"i%d" strings for every event and
    then parse them back; here strings are materialized ONLY for the ids
    that survive aggregation (one np.char.mod over the used vocab), so the
    per-event cost is pure integer arithmetic. Vocab order is numeric
    rather than lexicographic — RatingMatrix consumers index through
    user_ids/item_ids, so ordering is internal only."""
    n = len(values)
    if n == 0:
        return RatingMatrix([], [], np.empty(0, np.int32), np.empty(0, np.int32), np.empty(0, np.float32))
    values = np.asarray(values, dtype=np.float32)
    ts = (
        np.zeros(n, dtype=np.int64)
        if timestamps is None
        else np.asarray(timestamps, dtype=np.int64)
    )
    uq, uinv = np.unique(np.asarray(users), return_inverse=True)
    iq, iinv = np.unique(np.asarray(items), return_inverse=True)
    uu_codes, ii_codes, agg = _aggregate_indexed(uinv, len(iq), iinv, values, ts, implicit)
    u_used, uu = np.unique(uu_codes, return_inverse=True)
    i_used, ii = np.unique(ii_codes, return_inverse=True)
    up = user_prefix.decode("ascii", "replace")
    ip = item_prefix.decode("ascii", "replace")
    user_ids = np.char.mod(up + "%d", uq[u_used]).tolist()
    item_ids = np.char.mod(ip + "%d", iq[i_used]).tolist()
    return RatingMatrix(
        user_ids,
        item_ids,
        uu.astype(np.int32),
        ii.astype(np.int32),
        agg.astype(np.float32),
    )
