"""ALS input parsing and aggregation.

Rebuild of the data-prep stages of ALSUpdate (app/oryx-app-mllib/.../als/
ALSUpdate.java): input lines are ``user,item,value[,timestamp]`` (CSV or
JSON array; empty value = delete, parsed as NaN, ALSUpdate.java:260-278);
time-decay multiplies old strengths by factor^days (decayRating:292-298)
then prunes below the zero threshold; aggregation combines repeated
(user,item) pairs — implicit: sum with NaN poisoning (delete wins over
the aggregate, MLFunctions.SUM_WITH_NAN), explicit: last value in
timestamp order wins (aggregateScores:332-352) — and NaN aggregates are
dropped (deletes).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Iterable, NamedTuple

import numpy as np

from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.text import parse_line


class Interaction(NamedTuple):
    user: str
    item: str
    value: float  # NaN = delete marker
    timestamp_ms: int


_nan = math.nan


def parse_interactions(data: Iterable[KeyMessage | str]) -> list[Interaction]:
    """Parse lines, in input order. Lines missing a timestamp get 0 so
    pure-CSV triples still work in time-ordered contexts. Plain unquoted
    CSV (the wire-format fast path at 100k-event micro-batches) parses
    with a bare split; quoted CSV and JSON arrays go through parse_line."""
    out: list[Interaction] = []
    append = out.append
    for rec in data:
        line = rec if type(rec) is str else rec.message
        s = line.strip()
        if s and s[0] not in "[{" and '"' not in s:
            tokens = s.split(",")
        else:
            tokens = parse_line(s)
        if len(tokens) < 3:
            raise ValueError(f"bad ALS input: {line!r}")
        value = _nan if tokens[2] == "" else float(tokens[2])
        ts = int(float(tokens[3])) if len(tokens) > 3 and tokens[3] != "" else 0
        append(Interaction(tokens[0], tokens[1], value, ts))
    return out


def decay_interactions(
    interactions: list[Interaction],
    factor: float,
    zero_threshold: float,
    now_ms: int | None = None,
) -> list[Interaction]:
    if factor < 1.0:
        now = int(time.time() * 1000) if now_ms is None else now_ms
        decayed = []
        for it in interactions:
            if it.timestamp_ms >= now or math.isnan(it.value):
                decayed.append(it)
            else:
                days = (now - it.timestamp_ms) / 86_400_000.0
                decayed.append(
                    Interaction(it.user, it.item, it.value * factor**days, it.timestamp_ms)
                )
        interactions = decayed
    if zero_threshold > 0.0:
        interactions = [
            it for it in interactions if math.isnan(it.value) or it.value > zero_threshold
        ]
    return interactions


def aggregate(interactions: list[Interaction], implicit: bool) -> dict[tuple[str, str], float]:
    """Combine repeated (user,item) pairs; drop NaN aggregates (deletes)."""
    interactions = sorted(interactions, key=lambda it: it.timestamp_ms)
    agg: dict[tuple[str, str], float] = {}
    for it in interactions:
        key = (it.user, it.item)
        if implicit:
            prev = agg.get(key)
            # NaN anywhere poisons the sum => delete
            agg[key] = it.value if prev is None else prev + it.value
        else:
            agg[key] = it.value  # last wins
    return {k: v for k, v in agg.items() if not math.isnan(v)}


@dataclass
class RatingMatrix:
    """Indexed COO ready for the trainer."""

    user_ids: list[str]
    item_ids: list[str]
    user_idx: np.ndarray  # int32
    item_idx: np.ndarray  # int32
    values: np.ndarray  # float32

    @property
    def known_items(self) -> dict[str, set[str]]:
        known: dict[str, set[str]] = {}
        for u, i in zip(self.user_idx, self.item_idx):
            known.setdefault(self.user_ids[u], set()).add(self.item_ids[i])
        return known


def to_rating_matrix(agg: dict[tuple[str, str], float]) -> RatingMatrix:
    user_ids = sorted({u for u, _ in agg})
    item_ids = sorted({i for _, i in agg})
    u_index = {u: n for n, u in enumerate(user_ids)}
    i_index = {i: n for n, i in enumerate(item_ids)}
    n = len(agg)
    uu = np.empty(n, dtype=np.int32)
    ii = np.empty(n, dtype=np.int32)
    vv = np.empty(n, dtype=np.float32)
    for pos, ((u, i), v) in enumerate(agg.items()):
        uu[pos] = u_index[u]
        ii[pos] = i_index[i]
        vv[pos] = v
    return RatingMatrix(user_ids, item_ids, uu, ii, vv)
