"""ALS serving model: in-memory factors + batched on-device top-N.

Rebuild of ALSServingModel (app/oryx-app-serving/.../als/model/
ALSServingModel.java:58-496) and its manager (ALSServingModelManager.java:
46-176), redesigned TPU-first: where the reference shards the item matrix
into LSH partitions scanned by a thread pool (LocalitySensitiveHash.java,
TopNConsumer.java), this model keeps a packed device copy of Y and
computes top-N as ONE batched matvec + lax.top_k on the accelerator — an
exact scan that is faster than the reference's approximate LSH probe at
millions of items (SURVEY.md §2.12 'Request parallelism'). The packed
copy refreshes lazily when vectors change (the survey's 'periodic
re-upload of dirty shards' strategy for incremental state vs immutable
device arrays).

State mirrored from the reference: X and Y FeatureVectors, per-user
known-item sets, expected-ID sets driving get_fraction_loaded
(ALSServingModel.java:461-475), a cached YtY solver invalidated on Y
writes (:357-373), and retain-recent rotation (:382-441).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable, Iterator

import numpy as np

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.als.common import apply_up_lines, consume_blocks_columnar
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.lang import ReadWriteLock
from oryx_tpu.common.text import read_json
from oryx_tpu.common.vectormath import Solver, get_solver
from oryx_tpu.native.store import make_feature_vectors
from oryx_tpu.ops import topn as topn_ops
from oryx_tpu.serving.batcher import score_default

log = logging.getLogger(__name__)


class ALSServingModel(ServingModel):
    def __init__(
        self,
        features: int,
        implicit: bool,
        refresh_sec: float = 0.2,
        sample_rate: float = 1.0,
        score_dtype: str = "float32",
        shard_items: bool = False,
    ) -> None:
        self.features = features
        self.implicit = implicit
        # row-shard Y over all local devices (per-device top-k +
        # all_gather merge): the >1-HBM serving mode
        self.shard_items = shard_items
        # item-matrix dtype for device scoring: bfloat16 halves HBM traffic
        # (the serving bottleneck at millions of items) at ~1e-2 relative
        # score precision — near-tie ranks may swap, like LSH's trade-off
        self.score_dtype = score_dtype
        # LSH candidate pruning is opt-in (sample-rate < 1): the exact
        # device matvec is the TPU fast path, LSH the CPU-parity fallback
        # (ALSServingModel.java:58-124 partitions Y this way always)
        self.lsh = None
        if sample_rate < 1.0:
            import os

            from oryx_tpu.app.als.lsh import LocalitySensitiveHash

            self.lsh = LocalitySensitiveHash(sample_rate, features, os.cpu_count() or 1)
        self.x = make_feature_vectors()
        self.y = make_feature_vectors()
        self._known_lock = ReadWriteLock()
        self._known_items: dict[str, set[str]] = {}
        self._expected_lock = threading.Lock()
        self._expected_users: set[str] = set()
        self._expected_items: set[str] = set()
        self._solver_lock = threading.Lock()
        self._yty_solver: Solver | None = None
        # packed device copy of Y
        self._cache_lock = threading.Lock()
        self._y_dirty = True
        self._y_built_at = 0.0
        self._refresh_sec = refresh_sec
        self._y_ids: list[str] = []
        self._y_index: dict[str, int] = {}
        self._y_matrix = None  # device array [n, k]
        self._y_host: np.ndarray | None = None  # host copy, LSH path only
        self._y_partitions: np.ndarray | None = None  # LSH partition per row
        # incremental refresh state: ids written since the last build, and
        # whether membership may have shrunk (rotation) forcing a rebuild
        self._dirty_ids: set[str] = set()
        self._y_full_rebuild = True

    # -- vectors -------------------------------------------------------------

    def get_user_vector(self, user: str) -> np.ndarray | None:
        return self.x.get_vector(user)

    def get_item_vector(self, item: str) -> np.ndarray | None:
        return self.y.get_vector(item)

    def set_user_vector(self, user: str, vector: np.ndarray) -> None:
        self.x.set_vector(user, vector)
        with self._expected_lock:
            self._expected_users.discard(user)

    def set_item_vector(self, item: str, vector: np.ndarray) -> None:
        self.y.set_vector(item, vector)
        with self._expected_lock:
            self._expected_items.discard(item)
        with self._solver_lock:
            self._yty_solver = None
        with self._cache_lock:
            self._y_dirty = True
            self._dirty_ids.add(item)

    def set_user_vectors(self, users: list[str], vectors: np.ndarray) -> None:
        """Batched set: one native store call + one lock round for the
        whole batch (update-topic replay is one UP per factor row)."""
        self.x.set_batch(users, vectors)
        with self._expected_lock:
            self._expected_users.difference_update(users)

    def set_item_vectors(self, items: list[str], vectors: np.ndarray) -> None:
        self.y.set_batch(items, vectors)
        with self._expected_lock:
            self._expected_items.difference_update(items)
        with self._solver_lock:
            self._yty_solver = None
        with self._cache_lock:
            self._y_dirty = True
            self._dirty_ids.update(items)

    # -- known items (ALSServingModel.java:189-258) --------------------------

    def add_known_items(self, user: str, items: Iterable[str]) -> None:
        items = list(items)
        if not items:
            return
        with self._known_lock.write():
            self._known_items.setdefault(user, set()).update(items)

    def add_known_items_many(self, pairs: Iterable[tuple[str, list[str]]]) -> None:
        """Batched known-items merge under one write lock."""
        with self._known_lock.write():
            known = self._known_items
            for user, items in pairs:
                if items:
                    known.setdefault(user, set()).update(items)

    def get_known_items(self, user: str) -> set[str]:
        with self._known_lock.read():
            return set(self._known_items.get(user, ()))

    def remove_known_item(self, user: str, item: str) -> None:
        with self._known_lock.write():
            s = self._known_items.get(user)
            if s is not None:
                s.discard(item)

    def get_known_item_counts(self) -> dict[str, int]:
        with self._known_lock.read():
            return {u: len(s) for u, s in self._known_items.items()}

    def get_item_counts(self) -> dict[str, int]:
        """item -> number of users that know it, in one locked pass
        (ALSServingModel.getItemCounts analogue)."""
        counts: dict[str, int] = {}
        with self._known_lock.read():
            for items in self._known_items.values():
                for item in items:
                    counts[item] = counts.get(item, 0) + 1
        return counts

    # -- expected-ID accounting ----------------------------------------------

    def set_expected(self, user_ids: Iterable[str], item_ids: Iterable[str]) -> None:
        # computed outside the lock, published under it, so a concurrent
        # set_*_vector's discard can't resurrect an id we just removed
        users = set(user_ids) - set(self.x.ids())
        items = set(item_ids) - set(self.y.ids())
        with self._expected_lock:
            self._expected_users = users - set(self.x.ids())
            self._expected_items = items - set(self.y.ids())

    def get_fraction_loaded(self) -> float:
        with self._expected_lock:
            expected = len(self._expected_users) + len(self._expected_items)
        loaded = self.x.size() + self.y.size()
        if expected + loaded == 0:
            return 1.0
        return loaded / (loaded + expected)

    # -- rotation (retainRecentAnd*: 382-441) --------------------------------

    def retain_recent_and_user_ids(self, ids: set[str]) -> None:
        self.x.retain_recent_and_ids(ids)

    def retain_recent_and_item_ids(self, ids: set[str]) -> None:
        self.y.retain_recent_and_ids(ids)
        with self._solver_lock:
            self._yty_solver = None  # rotation invalidates the cached YtY
        with self._cache_lock:
            self._y_dirty = True
            self._y_full_rebuild = True  # membership may have shrunk

    def retain_recent_and_known_items(self, user_ids: set[str]) -> None:
        with self._known_lock.write():
            for u in [u for u in self._known_items if u not in user_ids]:
                del self._known_items[u]

    # -- solver --------------------------------------------------------------

    def get_yty_solver(self) -> Solver | None:
        with self._solver_lock:
            if self._yty_solver is None:
                self._yty_solver = get_solver(self.y.get_vtv())
            return self._yty_solver

    # -- device-side scoring ---------------------------------------------------

    def _try_incremental_refresh(self, dirty: list[str]) -> bool:
        """Scatter-update only the dirty rows of the device-resident Y
        (caller holds the cache lock). Returns False when a full rebuild
        is required: membership shrank, a dirty vector vanished, new ids
        exceed padded capacity, or the LSH host path is active."""
        vals, valid = self.y.get_batch(dirty, dim=self.features)
        if not np.all(valid):
            return False  # a dirty id has no vector anymore
        new_ids = [d for d in dirty if d not in self._y_index]
        if len(self._y_ids) + len(new_ids) > topn_ops.capacity(self._y_matrix):
            return False
        for d in new_ids:  # append into the padded region
            self._y_index[d] = len(self._y_ids)
            self._y_ids.append(d)
        rows = np.fromiter(
            (self._y_index[d] for d in dirty), dtype=np.int32, count=len(dirty)
        )
        self._y_matrix = topn_ops.update_rows(
            self._y_matrix, rows, vals, n_items=len(self._y_ids)
        )
        return True

    def _ensure_y_matrix(self, force: bool = False):
        with self._cache_lock:
            now = time.monotonic()
            if self._y_dirty and (force or now - self._y_built_at >= self._refresh_sec):
                dirty = list(self._dirty_ids)
                refreshed = (
                    self._y_matrix is not None
                    and not self._y_full_rebuild
                    and self.lsh is None
                    and not self.shard_items  # sharded layout rebuilds whole
                    and bool(dirty)
                    and self._try_incremental_refresh(dirty)
                )
                if not refreshed:
                    ids, mat = self.y.to_matrix()
                    self._y_ids = ids
                    self._y_index = {id_: i for i, id_ in enumerate(ids)}
                    if len(ids):
                        import jax.numpy as jnp

                        dtype = jnp.bfloat16 if self.score_dtype == "bfloat16" else jnp.float32
                        if self.shard_items:
                            from oryx_tpu.parallel.mesh import get_mesh

                            self._y_matrix = topn_ops.upload_sharded(
                                mat, get_mesh(), dtype=dtype
                            )
                        else:
                            self._y_matrix = topn_ops.upload(mat, dtype=dtype)
                    else:
                        self._y_matrix = None
                    if self.lsh is not None:
                        self._y_host = mat
                        self._y_partitions = (
                            self.lsh.partitions_for(mat) if len(ids) else None
                        )
                    self._y_full_rebuild = False
                self._dirty_ids.clear()
                self._y_dirty = False
                self._y_built_at = now
            # host/partition arrays are returned under the lock so one
            # request sees one consistent (ids, matrix, partitions) snapshot
            # even if a rebuild swaps them mid-flight
            return (
                self._y_ids,
                self._y_index,
                self._y_matrix,
                self._y_host,
                self._y_partitions,
            )

    def top_n(
        self,
        query: np.ndarray,
        how_many: int,
        exclude: set[str] | None = None,
        rescorer=None,
        cosine: bool = False,
    ) -> list[tuple[str, float]]:
        """Top-N items by dot (or cosine) score against `query`: one
        batched device matvec + top_k, replacing the reference's
        LSH-partitioned thread-pool scan (ALSServingModel.topN:289-335)."""
        ids, index, y_mat, y_host, y_partitions = self._ensure_y_matrix()
        if y_mat is None:
            return []
        # LSH pruning (sample-rate < 1): only rows whose partition falls in
        # the query's Hamming ball are scored, on host (the approximate
        # CPU-parity path; exact device scan otherwise)
        lsh_rows: np.ndarray | None = None
        if self.lsh is not None and y_partitions is not None:
            cand = self.lsh.candidate_indices(query)
            lsh_rows = np.flatnonzero(np.isin(y_partitions, cand))
            if len(lsh_rows) == 0:
                lsh_rows = None  # degenerate: fall back to the exact scan
        num_candidates = len(lsh_rows) if lsh_rows is not None else len(ids)
        exclude = exclude or set()
        margin = how_many + len(exclude)
        if rescorer is not None:
            margin = max(margin * 4, margin + 32)  # rescorer may filter many
        # widen the candidate window until how_many survive filtering or
        # every item has been considered (the reference streams all items,
        # ALSServingModel.topN:289-335, so filters can never starve results)
        while True:
            k = min(margin, num_candidates)
            if lsh_rows is not None:
                idx, scores = _host_top_k(y_host, lsh_rows, query, k, cosine=cosine)
            elif isinstance(y_mat, topn_ops.ShardedItemMatrix):
                # mesh-sharded scan: per-device top-k + all_gather merge
                bi, bv = topn_ops.top_k_sharded(y_mat, query, k, cosine=cosine)
                idx, scores = bi[0], bv[0]
            else:
                # continuous batching: concurrent requests against the same
                # Y snapshot coalesce into one device call
                idx, scores = score_default(y_mat, query, k, cosine=cosine)
            out: list[tuple[str, float]] = []
            for i, s in zip(idx, scores):
                id_ = ids[int(i)]
                if id_ in exclude:
                    continue
                score = float(s)
                if rescorer is not None:
                    if rescorer.is_filtered(id_):
                        continue
                    score = rescorer.rescore(id_, score)
                    if np.isnan(score):
                        continue
                out.append((id_, score))
                if len(out) == how_many and rescorer is None:
                    break
            if len(out) >= how_many or k >= num_candidates:
                break
            margin = margin * 4
        if rescorer is not None:
            out.sort(key=lambda t: -t[1])
        return out[:how_many]

    def all_item_ids(self) -> list[str]:
        return self.y.ids()

    def all_user_ids(self) -> list[str]:
        return self.x.ids()

    def __repr__(self) -> str:  # pragma: no cover
        return f"ALSServingModel[features={self.features}, X={self.x.size()}, Y={self.y.size()}]"


def _host_top_k(
    y_host: np.ndarray,
    rows: np.ndarray,
    query: np.ndarray,
    k: int,
    cosine: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Partial top-k over an LSH-pruned row subset, on host: the scored
    candidate set is already ~sample-rate of the items, so numpy argpartition
    beats a device round-trip at these sizes."""
    sub = y_host[rows]
    scores = sub @ np.asarray(query, dtype=np.float32)
    if cosine:
        qn = float(np.linalg.norm(query))
        norms = np.linalg.norm(sub, axis=1)
        scores = scores / np.maximum(norms * qn, 1e-12)
    k = max(1, min(int(k), len(rows)))
    part = np.argpartition(-scores, k - 1)[:k]
    order = part[np.argsort(-scores[part])]
    return rows[order], scores[order]


class ALSServingModelManager(AbstractServingModelManager):
    """Consume protocol identical to the speed manager plus known-items
    from UP payloads and rescorer loading
    (ALSServingModelManager.java:46-176)."""

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.implicit = config.get_bool("oryx.als.implicit")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.sample_rate = config.get_float("oryx.als.sample-rate")
        self.score_dtype = config.get_string("oryx.als.serving.score-dtype")
        self.shard_items = config.get_bool("oryx.als.serving.shard-items")
        if self.score_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"oryx.als.serving.score-dtype must be float32 or bfloat16, "
                f"got {self.score_dtype!r}"
            )
        self.rescorer_provider = _load_rescorer_providers(config)
        self.model: ALSServingModel | None = None
        self._consumed = 0

    def consume_blocks(self, block_iterator) -> None:
        """Columnar consume: contiguous "UP" runs parse vectorized and
        apply via the batched setters (replay of a factor publish is one
        UP per row — a million-record startup replay). X rows carrying
        known-item lists parse those too; anything escaped or unusual
        falls back to per-record consume in order."""
        consume_blocks_columnar(
            block_iterator,
            lambda: self.model is not None,
            self._apply_up_batch,
            self.consume,
        )

    def _apply_up_batch(self, lines: list[bytes]) -> None:
        model = self.model
        applied = apply_up_lines(
            lines,
            model.features,
            model.set_user_vectors,
            model.set_item_vectors,
            lambda km: self.consume(iter([km])),
            on_known=(
                None
                if self.no_known_items
                else lambda pairs: model.add_known_items_many(pairs)
            ),
            strict_tail=True,  # the known list is part of the wire contract
        )
        self._consumed += applied  # slow path self-counts

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            key, message = km.key, km.message
            if key == "UP":
                if self.model is None:
                    continue
                update = read_json(message)
                which, id_ = update[0], str(update[1])
                vector = np.asarray(update[2], dtype=np.float32)
                if which == "X":
                    self.model.set_user_vector(id_, vector)
                    if len(update) > 3 and not self.no_known_items:
                        self.model.add_known_items(id_, [str(i) for i in update[3]])
                elif which == "Y":
                    self.model.set_item_vector(id_, vector)
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                features = int(app_pmml.get_required_extension_value(pmml, "features"))
                implicit = app_pmml.get_required_extension_value(pmml, "implicit") == "true"
                x_ids = set(app_pmml.get_extension_content(pmml, "XIDs") or [])
                y_ids = set(app_pmml.get_extension_content(pmml, "YIDs") or [])
                if (
                    self.model is None
                    or self.model.features != features
                    or self.model.implicit != implicit
                ):
                    self.model = ALSServingModel(
                        features,
                        implicit,
                        sample_rate=self.sample_rate,
                        score_dtype=self.score_dtype,
                        shard_items=self.shard_items,
                    )
                    self.model.set_expected(x_ids, y_ids)
                else:
                    self.model.retain_recent_and_user_ids(x_ids)
                    self.model.retain_recent_and_item_ids(y_ids)
                    self.model.retain_recent_and_known_items(
                        x_ids | set(self.model.all_user_ids())
                    )
                    self.model.set_expected(x_ids, y_ids)
            else:
                raise ValueError(f"bad key {key}")
            self._consumed += 1
            if self._consumed % 10_000 == 0:
                log.info("%s updates consumed; model: %r", self._consumed, self.model)

    def get_model(self) -> ALSServingModel | None:
        return self.model


def _load_rescorer_providers(config: Config):
    """Load RescorerProvider chain from oryx.als.rescorer-provider-class
    (ALSServingModelManager.java:141-174)."""
    names = config.get_optional_strings("oryx.als.rescorer-provider-class")
    if not names:
        return None
    from oryx_tpu.app.als.rescorer import MultiRescorerProvider
    from oryx_tpu.common.lang import load_instance_of

    providers = [load_instance_of(n) for n in names]
    if len(providers) == 1:
        return providers[0]
    return MultiRescorerProvider(providers)
