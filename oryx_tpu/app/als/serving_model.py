"""ALS serving model: in-memory factors + batched on-device top-N.

Rebuild of ALSServingModel (app/oryx-app-serving/.../als/model/
ALSServingModel.java:58-496) and its manager (ALSServingModelManager.java:
46-176), redesigned TPU-first: where the reference shards the item matrix
into LSH partitions scanned by a thread pool (LocalitySensitiveHash.java,
TopNConsumer.java), this model keeps a packed device copy of Y and
computes top-N as ONE batched matvec + lax.top_k on the accelerator — an
exact scan that is faster than the reference's approximate LSH probe at
millions of items (SURVEY.md §2.12 'Request parallelism'). The packed
copy refreshes lazily when vectors change (the survey's 'periodic
re-upload of dirty shards' strategy for incremental state vs immutable
device arrays).

State mirrored from the reference: X and Y FeatureVectors, per-user
known-item sets, expected-ID sets driving get_fraction_loaded
(ALSServingModel.java:461-475), a cached YtY solver invalidated on Y
writes (:357-373), and retain-recent rotation (:382-441).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable, Iterator

import numpy as np

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.als.common import apply_up_lines, consume_blocks_columnar
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common import tracing
from oryx_tpu.common.lang import ReadWriteLock
from oryx_tpu.common.text import read_json
from oryx_tpu.common.vectormath import Solver, get_solver
from oryx_tpu.native.store import make_feature_vectors
from oryx_tpu.ops import ivf as ivf_ops
from oryx_tpu.ops import topn as topn_ops
from oryx_tpu.serving.batcher import score_default, score_indexed_default

log = logging.getLogger(__name__)


class ALSServingModel(ServingModel):
    def __init__(
        self,
        features: int,
        implicit: bool,
        refresh_sec: float = 0.2,
        sample_rate: float = 1.0,
        score_dtype: str = "float32",
        shard_items: bool = False,
        device_user_matrix: bool = True,
    ) -> None:
        self.features = features
        self.implicit = implicit
        # stage X on device next to Y so /recommend for a known user ships
        # an int32 row index instead of a query vector (index submit);
        # only meaningful for the exact-device-scan path
        self.device_user_matrix = device_user_matrix
        self._x_staging = bool(device_user_matrix) and sample_rate >= 1.0 and not shard_items
        # row-shard Y over all local devices (per-device top-k +
        # all_gather merge): the >1-HBM serving mode
        self.shard_items = shard_items
        # item-matrix dtype for device scoring: bfloat16 halves HBM traffic
        # (the serving bottleneck at millions of items) at ~1e-2 relative
        # score precision — near-tie ranks may swap, like LSH's trade-off.
        # int8 halves the SCANNED bytes again (row-quantized primary plane,
        # total memory ~bf16 counting the residual plane) and rescoring the
        # oversampled candidates against the residual keeps top-10 recall
        # >= 0.99 of float32 — see docs/serving-scan.md
        self.score_dtype = score_dtype
        # LSH candidate pruning is opt-in (sample-rate < 1): the exact
        # device matvec is the TPU fast path, LSH the CPU-parity fallback
        # (ALSServingModel.java:58-124 partitions Y this way always)
        self.lsh = None
        if sample_rate < 1.0:
            import os

            from oryx_tpu.app.als.lsh import LocalitySensitiveHash

            self.lsh = LocalitySensitiveHash(sample_rate, features, os.cpu_count() or 1)
        self.x = make_feature_vectors()
        self.y = make_feature_vectors()
        self._known_lock = ReadWriteLock()
        self._known_items: dict[str, set[str]] = {}
        self._expected_lock = threading.Lock()
        self._expected_users: set[str] = set()
        self._expected_items: set[str] = set()
        self._solver_lock = threading.Lock()
        self._yty_solver: Solver | None = None
        # packed device copy of Y
        self._cache_lock = threading.Lock()
        self._y_dirty = True
        self._y_built_at = 0.0
        self._refresh_sec = refresh_sec
        self._y_ids: list[str] = []
        self._y_index: dict[str, int] = {}
        self._y_matrix = None  # device array [n, k]
        self._y_host: np.ndarray | None = None  # host copy, LSH path only
        self._y_partitions: np.ndarray | None = None  # LSH partition per row
        # incremental refresh state: ids written since the last build, and
        # whether membership may have shrunk (rotation) forcing a rebuild
        self._dirty_ids: set[str] = set()
        self._y_full_rebuild = True
        # ANN maintenance handshake (serving/maintain.py): the build epoch
        # bumps on every full rebuild/index swap so a compaction whose
        # snapshot predates the current id space is discarded at install;
        # the pressure callback wakes the maintainer when a fold-in batch
        # crosses the overlay watermark or spills
        self._y_build_epoch = 0
        self._y_snapshot_epoch = -1
        # bumps on every rotation (retain_recent_and_item_ids): an index
        # adoption built from a pre-rotation store snapshot is discarded
        self._y_rotation_epoch = 0
        self._index_pressure_cb = None
        self._index_generation: str | None = None
        # device copy of X (query matrix for index-submitted /recommend)
        self._x_ids: list[str] = []
        self._x_index: dict[str, int] = {}
        self._x_matrix = None  # device [n, k] float32
        self._x_dirty_ids: set[str] = set()
        self._x_dirty = True
        self._x_full_rebuild = True
        self._x_built_at = 0.0
        self._x_capacity = 0
        self._x_building = False
        self._x_restage_thread: threading.Thread | None = None
        self._x_epoch = 0  # bumped by rotation: invalidates in-flight restages

    # -- vectors -------------------------------------------------------------

    def get_user_vector(self, user: str) -> np.ndarray | None:
        return self.x.get_vector(user)

    def get_item_vector(self, item: str) -> np.ndarray | None:
        return self.y.get_vector(item)

    def set_user_vector(self, user: str, vector: np.ndarray) -> None:
        self.x.set_vector(user, vector)
        with self._expected_lock:
            self._expected_users.discard(user)
        if self._x_staging:
            with self._cache_lock:
                self._x_dirty = True
                self._x_dirty_ids.add(user)

    def set_item_vector(self, item: str, vector: np.ndarray) -> None:
        self.y.set_vector(item, vector)
        with self._expected_lock:
            self._expected_items.discard(item)
        with self._solver_lock:
            self._yty_solver = None
        with self._cache_lock:
            self._y_dirty = True
            self._dirty_ids.add(item)

    def set_user_vectors(self, users: list[str], vectors: np.ndarray) -> None:
        """Batched set: one native store call + one lock round for the
        whole batch (update-topic replay is one UP per factor row)."""
        self.x.set_batch(users, vectors)
        with self._expected_lock:
            self._expected_users.difference_update(users)
        if self._x_staging:
            with self._cache_lock:
                self._x_dirty = True
                self._x_dirty_ids.update(users)

    def set_item_vectors(self, items: list[str], vectors: np.ndarray) -> None:
        self.y.set_batch(items, vectors)
        with self._expected_lock:
            self._expected_items.difference_update(items)
        with self._solver_lock:
            self._yty_solver = None
        with self._cache_lock:
            self._y_dirty = True
            self._dirty_ids.update(items)

    # -- known items (ALSServingModel.java:189-258) --------------------------

    def add_known_items(self, user: str, items: Iterable[str]) -> None:
        items = list(items)
        if not items:
            return
        with self._known_lock.write():
            self._known_items.setdefault(user, set()).update(items)

    def add_known_items_many(self, pairs: Iterable[tuple[str, list[str]]]) -> None:
        """Batched known-items merge under one write lock."""
        with self._known_lock.write():
            known = self._known_items
            for user, items in pairs:
                if items:
                    known.setdefault(user, set()).update(items)

    def get_known_items(self, user: str) -> set[str]:
        with self._known_lock.read():
            return set(self._known_items.get(user, ()))

    def remove_known_item(self, user: str, item: str) -> None:
        with self._known_lock.write():
            s = self._known_items.get(user)
            if s is not None:
                s.discard(item)

    def get_known_item_counts(self) -> dict[str, int]:
        with self._known_lock.read():
            return {u: len(s) for u, s in self._known_items.items()}

    def get_item_counts(self) -> dict[str, int]:
        """item -> number of users that know it, in one locked pass
        (ALSServingModel.getItemCounts analogue)."""
        counts: dict[str, int] = {}
        with self._known_lock.read():
            for items in self._known_items.values():
                for item in items:
                    counts[item] = counts.get(item, 0) + 1
        return counts

    # -- expected-ID accounting ----------------------------------------------

    def set_expected(self, user_ids: Iterable[str], item_ids: Iterable[str]) -> None:
        # computed outside the lock, published under it, so a concurrent
        # set_*_vector's discard can't resurrect an id we just removed
        users = set(user_ids) - set(self.x.ids())
        items = set(item_ids) - set(self.y.ids())
        with self._expected_lock:
            self._expected_users = users - set(self.x.ids())
            self._expected_items = items - set(self.y.ids())

    def get_fraction_loaded(self) -> float:
        with self._expected_lock:
            expected = len(self._expected_users) + len(self._expected_items)
        loaded = self.x.size() + self.y.size()
        if expected + loaded == 0:
            return 1.0
        return loaded / (loaded + expected)

    # -- rotation (retainRecentAnd*: 382-441) --------------------------------

    def retain_recent_and_user_ids(self, ids: set[str]) -> None:
        self.x.retain_recent_and_ids(ids)
        if self._x_staging:
            with self._cache_lock:
                self._x_dirty = True
                # membership may have SHRUNK: staged rows for removed users
                # must stop serving immediately (the vector path would 404),
                # so index submit disables until the rebuild lands — and an
                # in-flight restage built from the PRE-rotation store must
                # be discarded at swap time
                self._x_full_rebuild = True
                self._x_epoch += 1

    def retain_recent_and_item_ids(self, ids: set[str]) -> None:
        self.y.retain_recent_and_ids(ids)
        with self._solver_lock:
            self._yty_solver = None  # rotation invalidates the cached YtY
        with self._cache_lock:
            self._y_dirty = True
            self._y_full_rebuild = True  # membership may have shrunk
            self._y_rotation_epoch += 1

    def retain_recent_and_known_items(self, user_ids: set[str]) -> None:
        with self._known_lock.write():
            for u in [u for u in self._known_items if u not in user_ids]:
                del self._known_items[u]

    # -- solver --------------------------------------------------------------

    def get_yty_solver(self) -> Solver | None:
        with self._solver_lock:
            if self._yty_solver is None:
                self._yty_solver = get_solver(self.y.get_vtv())
            return self._yty_solver

    # -- device-side scoring ---------------------------------------------------

    def _try_incremental_refresh(self, dirty: list[str]) -> bool:
        """Scatter-update only the dirty rows of the device-resident Y
        (caller holds the cache lock). Returns False when a full rebuild
        is required: membership shrank, a dirty vector vanished, new ids
        exceed padded capacity, or the LSH host path is active."""
        vals, valid = self.y.get_batch(dirty, dim=self.features)
        if not np.all(valid):
            return False  # a dirty id has no vector anymore
        new_ids = [d for d in dirty if d not in self._y_index]
        if len(self._y_ids) + len(new_ids) > topn_ops.capacity(self._y_matrix):
            # an IVF index with a maintainer attached absorbs the growth:
            # the overlay spills its oldest entries to the compaction
            # queue instead of forcing a request-path re-cluster
            if not (
                isinstance(self._y_matrix, ivf_ops.IVFIndex)
                and self._index_pressure_cb is not None
            ):
                return False
        for d in new_ids:  # append into the padded region
            self._y_index[d] = len(self._y_ids)
            self._y_ids.append(d)
        rows = np.fromiter(
            (self._y_index[d] for d in dirty), dtype=np.int32, count=len(dirty)
        )
        # never raises on overflow: the IVF overlay degrades by spilling
        # its oldest entries to the maintainer's pending queue, so the
        # fold-in path stays O(batch) under any pressure — the background
        # compaction (serving/maintain.py) drains the spill, woken here
        # when the overlay crosses its watermark
        self._y_matrix = topn_ops.update_rows(
            self._y_matrix, rows, vals, n_items=len(self._y_ids)
        )
        cb = self._index_pressure_cb
        if (
            cb is not None
            and isinstance(self._y_matrix, ivf_ops.IVFIndex)
            and ivf_ops.needs_maintenance(self._y_matrix)
        ):
            cb()
        return True

    def _ensure_y_matrix(self, force: bool = False):
        with self._cache_lock:
            now = time.monotonic()
            if self._y_dirty and (force or now - self._y_built_at >= self._refresh_sec):
                dirty = list(self._dirty_ids)
                refreshed = (
                    self._y_matrix is not None
                    and not self._y_full_rebuild
                    and self.lsh is None
                    and not self.shard_items  # sharded layout rebuilds whole
                    and bool(dirty)
                    and self._try_incremental_refresh(dirty)
                )
                if not refreshed:
                    ids, mat = self.y.to_matrix()
                    self._y_ids = ids
                    self._y_index = {id_: i for i, id_ in enumerate(ids)}
                    if len(ids):
                        import jax.numpy as jnp

                        dtype = {
                            "bfloat16": jnp.bfloat16,
                            "int8": jnp.int8,
                        }.get(self.score_dtype, jnp.float32)
                        if self.shard_items:
                            from oryx_tpu.parallel.mesh import get_mesh

                            self._y_matrix = topn_ops.upload_sharded(
                                mat, get_mesh(), dtype=dtype
                            )
                        elif (
                            self.score_dtype == "int8"
                            and self.lsh is None
                            and ivf_ops.ann_active(len(ids))
                        ):
                            # ANN tier: cluster the rebuilt item matrix
                            # into an IVF routing table. Rebuilds ride the
                            # same MODEL/UP topic path as the exact scan —
                            # in-between fold-ins stay visible through the
                            # index's pending overlay (update_rows above).
                            # With tiering on, the host plane moves into
                            # the HBM->RAM->disk cell store right here.
                            self._y_matrix = ivf_ops.attach_tiered_plane(
                                ivf_ops.build_ivf(mat)
                            )
                        else:
                            self._y_matrix = topn_ops.upload(mat, dtype=dtype)
                    else:
                        self._y_matrix = None
                    if self.lsh is not None:
                        self._y_host = mat
                        self._y_partitions = (
                            self.lsh.partitions_for(mat) if len(ids) else None
                        )
                    self._y_full_rebuild = False
                    # id space changed: in-flight compaction snapshots are
                    # now stale and must be discarded at install
                    self._y_build_epoch += 1
                self._dirty_ids.clear()
                self._y_dirty = False
                self._y_built_at = now
            # host/partition arrays are returned under the lock so one
            # request sees one consistent (ids, matrix, partitions) snapshot
            # even if a rebuild swaps them mid-flight
            return (
                self._y_ids,
                self._y_index,
                self._y_matrix,
                self._y_host,
                self._y_partitions,
            )

    def _try_incremental_x_refresh(self, dirty: list[str]) -> bool:
        """Scatter-update the dirty rows of the device-resident X (caller
        holds the cache lock). First-time users APPEND into the padded
        device capacity — a steady trickle of new users must not force a
        full re-upload every refresh tick. False = rebuild required
        (capacity exhausted or a dirty user vanished)."""
        new = [u for u in dirty if u not in self._x_index]
        if len(self._x_ids) + len(new) > self._x_capacity:
            return False
        vals, valid = self.x.get_batch(dirty, dim=self.features)
        if not np.all(valid):
            return False  # a dirty user vanished: membership changed
        for u in new:
            self._x_index[u] = len(self._x_ids)
            self._x_ids.append(u)
        rows = np.fromiter(
            (self._x_index[u] for u in dirty), dtype=np.int32, count=len(dirty)
        )
        self._x_matrix = topn_ops.update_query_rows(self._x_matrix, rows, vals)
        return True

    # staged X bigger than this is not worth the HBM next to Y: fall back
    # to vector submit rather than risk OOMing a previously-fine deploy
    _X_STAGE_MAX_BYTES = 2 << 30

    def _rebuild_x_staging(self, pre_dirty: set[str], epoch: int) -> None:
        """Full X restage, run by the triggering request thread OUTSIDE
        the cache lock (to_matrix + a potentially multi-GB upload must
        not stall Y scoring); the swap happens under the lock and is
        DISCARDED if a rotation bumped the epoch mid-build (the snapshot
        predates it; the next tick rebuilds from the rotated store). Ids
        written during the build stay dirty and catch up on the next
        refresh tick; incremental scatters are held off while a build is
        in flight so the swap can never clobber one."""
        try:
            ids, mat = self.x.to_matrix()
            if len(ids) * self.features * 4 * 1.25 > self._X_STAGE_MAX_BYTES:
                log.info(
                    "device X (%d users x %d) exceeds the staging budget; "
                    "index submit disabled for this model",
                    len(ids), self.features,
                )
                with self._cache_lock:
                    # flip + drain under the same lock that set_user_vector
                    # appends dirty ids under, so no stale dirty set is
                    # retained for the model's lifetime after the disable
                    self._x_matrix = None
                    self._x_capacity = 0
                    self._x_staging = False
                    self._x_dirty_ids.clear()
                    self._x_dirty = False
                return
            if len(ids):
                # pad capacity so a trickle of new users appends via
                # scatter instead of re-uploading everything
                cap = max(64, int(len(ids) * 1.25))
                pad = np.zeros((cap - len(ids), self.features), np.float32)
                staged = topn_ops.upload_queries(
                    np.concatenate([mat, pad]) if cap > len(ids) else mat
                )
            else:
                staged, cap = None, 0
            with self._cache_lock:
                if self._x_epoch != epoch:
                    return  # rotation landed mid-build: discard the snapshot
                self._x_ids = list(ids)
                self._x_index = {id_: i for i, id_ in enumerate(ids)}
                self._x_matrix = staged
                self._x_capacity = cap
                self._x_full_rebuild = False
                self._x_dirty_ids -= pre_dirty
                self._x_dirty = bool(self._x_dirty_ids)
                self._x_built_at = time.monotonic()
        finally:
            # under the cache lock: _user_scan_row reads this flag under
            # the lock to decide whether a scatter is safe, and a
            # lock-free flip can let a scatter land mid-swap
            # (oryxlint lockset ORX101 caught the bare write)
            with self._cache_lock:
                self._x_building = False

    def _user_scan_row(self, user: str):
        """(x_matrix, row) for index submit, or (None, None) when the
        user isn't freshly staged. Row resolution happens under the cache
        lock so the row, the matrix snapshot, and the staleness check are
        mutually consistent; a pending full restage serves the vector
        path instead of blocking."""
        rebuild_dirty: set[str] | None = None
        with self._cache_lock:
            now = time.monotonic()
            if self._x_dirty and (now - self._x_built_at >= self._refresh_sec):
                dirty = list(self._x_dirty_ids)
                refreshed = (
                    not self._x_building  # a scatter into the old matrix
                    # would be clobbered by the in-flight restage's swap
                    and self._x_matrix is not None
                    and not self._x_full_rebuild
                    and bool(dirty)
                    and self._try_incremental_x_refresh(dirty)  # ms-scale scatter
                )
                if refreshed:
                    self._x_dirty_ids.clear()
                    self._x_dirty = False
                    self._x_built_at = now
                elif not self._x_building:
                    self._x_building = True
                    rebuild_dirty = set(self._x_dirty_ids)
                    rebuild_epoch = self._x_epoch
            stale = (
                self._x_matrix is None
                or self._x_full_rebuild  # rotation pending: rows may be gone
                or user in self._x_dirty_ids
            )
            row = None if stale else self._x_index.get(user)
            x_mat = self._x_matrix
        if rebuild_dirty is not None:
            # run the restage (to_matrix + up to multi-GB upload) on a
            # daemon thread: the request that trips the refresh tick falls
            # through to the vector path instead of paying seconds of
            # latency; _x_building (set under the lock above) already
            # serializes builds, so at most one thread runs this
            prev = self._x_restage_thread
            if prev is not None:
                # _x_building guarantees the previous restage's body has
                # finished; reap the thread object before replacing it
                prev.join(timeout=5.0)
            t = threading.Thread(
                target=self._rebuild_x_staging,
                args=(rebuild_dirty, rebuild_epoch),
                name="als-x-restage",
                daemon=True,
            )
            self._x_restage_thread = t  # joinable: tests + orderly close
            t.start()
            from oryx_tpu.common import ledger

            ledger.register("thread", t, live=threading.Thread.is_alive)
        if row is None:
            return None, None
        return x_mat, row

    def top_n_for_user(
        self,
        user: str,
        how_many: int,
        exclude: set[str] | None = None,
        rescorer=None,
        cosine: bool = False,
    ) -> list[tuple[str, float]] | None:
        """top_n for a known user id, or None when the user is unknown.

        With the device-resident X enabled (and the exact device scan in
        play), the request ships an int32 row index instead of a query
        vector — the serving twin of ``submit_top_k_multi_indexed``. A
        user whose vector changed since the last X refresh (or isn't
        staged yet) falls back to the fresh host vector, so results are
        never staler than the vector path's."""
        if self._x_staging:
            x_mat, row = self._user_scan_row(user)
            if row is not None:
                ids, _index, y_mat, _h, _p = self._ensure_y_matrix()
                if y_mat is not None and not isinstance(
                    y_mat, topn_ops.ShardedItemMatrix
                ):
                    return self._select_loop(
                        ids,
                        len(ids),
                        lambda k: score_indexed_default(
                            y_mat, x_mat, row, k, cosine=cosine
                        ),
                        how_many,
                        exclude,
                        rescorer,
                    )
        vec = self.get_user_vector(user)
        if vec is None:
            return None
        return self.top_n(vec, how_many, exclude=exclude, rescorer=rescorer, cosine=cosine)

    def top_n(
        self,
        query: np.ndarray,
        how_many: int,
        exclude: set[str] | None = None,
        rescorer=None,
        cosine: bool = False,
    ) -> list[tuple[str, float]]:
        """Top-N items by dot (or cosine) score against `query`: one
        batched device matvec + top_k, replacing the reference's
        LSH-partitioned thread-pool scan (ALSServingModel.topN:289-335)."""
        ids, index, y_mat, y_host, y_partitions = self._ensure_y_matrix()
        if y_mat is None:
            return []
        # LSH pruning (sample-rate < 1): only rows whose partition falls in
        # the query's Hamming ball are scored, on host (the approximate
        # CPU-parity path; exact device scan otherwise)
        lsh_rows: np.ndarray | None = None
        if self.lsh is not None and y_partitions is not None:
            cand = self.lsh.candidate_indices(query)
            lsh_rows = np.flatnonzero(np.isin(y_partitions, cand))
            if len(lsh_rows) == 0:
                lsh_rows = None  # degenerate: fall back to the exact scan
        num_candidates = len(lsh_rows) if lsh_rows is not None else len(ids)

        def score_fn(k: int):
            if lsh_rows is not None:
                return _host_top_k(y_host, lsh_rows, query, k, cosine=cosine)
            if isinstance(y_mat, topn_ops.ShardedItemMatrix):
                # mesh-sharded scan: per-device top-k + all_gather merge
                bi, bv = topn_ops.top_k_sharded(y_mat, query, k, cosine=cosine)
                return bi[0], bv[0]
            # continuous batching: concurrent requests against the same
            # Y snapshot coalesce into one device call
            return score_default(y_mat, query, k, cosine=cosine)

        return self._select_loop(
            ids, num_candidates, score_fn, how_many, exclude, rescorer
        )

    @staticmethod
    def _select_loop(
        ids, num_candidates, score_fn, how_many, exclude, rescorer
    ) -> list[tuple[str, float]]:
        """Candidate-window widening shared by the vector and index-submit
        paths: widen until how_many survive filtering or every item has
        been considered (the reference streams all items,
        ALSServingModel.topN:289-335, so filters can never starve
        results)."""
        exclude = exclude or set()
        margin = how_many + len(exclude)
        if rescorer is not None:
            margin = max(margin * 4, margin + 32)  # rescorer may filter many

        def filter_candidates(idx, scores) -> list[tuple[str, float]]:
            out: list[tuple[str, float]] = []
            for i, s in zip(idx, scores):
                if int(i) < 0:
                    # ANN starved-window padding: fewer finite candidates
                    # than k (tiny probed cells); nothing real was dropped
                    continue
                id_ = ids[int(i)]
                if id_ in exclude:
                    continue
                score = float(s)
                if rescorer is not None:
                    if rescorer.is_filtered(id_):
                        continue
                    score = rescorer.rescore(id_, score)
                    if np.isnan(score):
                        continue
                out.append((id_, score))
                if len(out) == how_many and rescorer is None:
                    break
            return out

        while True:
            k = min(margin, num_candidates)
            idx, scores = score_fn(k)
            if rescorer is not None:
                # child of the ambient serving.request span; sibling of
                # the batcher's serving.scan
                with tracing.span("serving.rescore", attrs={"k": int(k)}) as sp:
                    out = filter_candidates(idx, scores)
                    sp.set("kept", len(out))
            else:
                out = filter_candidates(idx, scores)
            if len(out) >= how_many or k >= num_candidates:
                break
            margin = margin * 4
        if rescorer is not None:
            out.sort(key=lambda t: -t[1])
        return out[:how_many]

    # -- ANN maintenance protocol (serving/maintain.py) ----------------------

    def set_index_pressure_callback(self, cb) -> None:
        """Wire the maintainer's wake-up: called (under the cache lock)
        when a fold-in batch crosses the overlay watermark or spills."""
        with self._cache_lock:
            self._index_pressure_cb = cb

    @property
    def index_generation(self) -> str | None:
        """The published index generation this model's layout came from,
        or None when the clustering is locally built."""
        return self._index_generation

    def note_published_index(self, generation_id: str) -> None:
        """This replica just PUBLISHED this generation (its installed
        layout is the generation): dedup the self-delivery off the
        update topic instead of rebuilding from our own centroids."""
        with self._cache_lock:
            self._index_generation = str(generation_id)

    def maintenance_snapshot(self, watermark: float = 0.5, force: bool = False):
        """(index, pending snapshot) for one background compaction pass,
        or None when there is nothing to compact (no IVF index, a forced
        rebuild pending, or overlay pressure below the watermark). The
        snapshot deep-copies the overlay's raw rows under the cache lock
        — O(overlay), never O(catalog) — so compaction runs off-lock
        against stable inputs while fold-ins keep landing."""
        with self._cache_lock:
            idx = self._y_matrix
            if not isinstance(idx, ivf_ops.IVFIndex):
                return None
            if self._y_full_rebuild:
                return None  # rotation owns the next layout
            if not force and not ivf_ops.needs_maintenance(idx, watermark=watermark):
                return None
            snap = ivf_ops.snapshot_pending(idx)
            self._y_snapshot_epoch = self._y_build_epoch
            return idx, snap

    def install_compacted(self, new_index, stats: dict) -> bool:
        """Swap a compacted index in (one pointer write under the cache
        lock). Fold-ins that landed after the snapshot are replayed onto
        the new layout first — detected by comparing each live overlay /
        spill entry's fold-in time against the snapshot's — so no update
        is lost across the swap. Returns False (result discarded) when a
        full rebuild or rotation changed the id space mid-compaction."""
        with self._cache_lock:
            cur = self._y_matrix
            if (
                not isinstance(cur, ivf_ops.IVFIndex)
                or self._y_full_rebuild
                or self._y_build_epoch != self._y_snapshot_epoch
            ):
                return False
            snap_born = stats.get("born") or {}
            feat = cur.features
            replay_ids: list[int] = []
            replay_rows: list[np.ndarray] = []
            if cur.ov_raw_host is not None:
                cur_born = cur.ov_born or {}
                for item, slot in cur.ov_map.items():
                    b = cur_born.get(item, 0.0)
                    if item not in snap_born or b > snap_born[item]:
                        replay_ids.append(int(item))
                        replay_rows.append(cur.ov_raw_host[slot, :feat].copy())
            for item, (raw, b) in (cur.pending_spill or {}).items():
                if item not in snap_born or b > snap_born[item]:
                    replay_ids.append(int(item))
                    replay_rows.append(np.asarray(raw)[:feat].copy())
            if replay_ids:
                new_index = ivf_ops.update_rows(
                    new_index,
                    np.asarray(replay_ids, np.int64),
                    np.stack(replay_rows),
                    n_items=len(self._y_ids),
                )
                stats["replayed"] = len(replay_ids)
            self._y_matrix = new_index
            self._y_snapshot_epoch = -1  # consumed
            return True

    def apply_index_generation(self, ref: str) -> bool:
        """Adopt a published index generation (INDEX-REF): rebuild the
        IVF layout over THIS replica's item store seeded with the
        generation's centroids — same cell geometry fleet-wide without
        shipping item planes — and swap with zero downtime (the build
        runs off-lock; requests keep scanning the old index until one
        pointer write under the cache lock). Returns True on swap."""
        from oryx_tpu.serving import maintain as maintain_mod

        loaded = maintain_mod.read_index_generation(ref)
        if loaded is None:
            return False
        gid, manifest, cents = loaded
        if self._index_generation == gid:
            return False  # duplicate delivery
        if int(manifest.get("features") or cents.shape[1]) != self.features:
            log.warning(
                "index generation %s features mismatch (%s != %d); skipped",
                gid, manifest.get("features"), self.features,
            )
            return False
        if self.lsh is not None or self.shard_items or self.score_dtype != "int8":
            return False  # index generations only drive the IVF scan mode
        with self._cache_lock:
            rot0 = self._y_rotation_epoch
            # ids dirty NOW are covered by the store snapshot below — the
            # build includes their current values, so they stop being
            # dirty once the swap lands (writes racing the build re-dirty)
            dirty0 = set(self._dirty_ids)
        ids, mat = self.y.to_matrix()
        if not ivf_ops.ann_active(len(ids)):
            return False
        new = ivf_ops.attach_tiered_plane(ivf_ops.build_ivf(mat, centroids=cents))
        with self._cache_lock:
            if self._y_rotation_epoch != rot0:
                # a rotation raced the build: its rebuild must win
                # (membership may have shrunk since our store snapshot)
                return False
            self._y_ids = list(ids)
            self._y_index = {id_: i for i, id_ in enumerate(ids)}
            self._y_matrix = new
            # built from the CURRENT store: any pending full rebuild is
            # satisfied by this layout
            self._y_full_rebuild = False
            self._y_build_epoch += 1
            self._y_snapshot_epoch = -1
            self._y_built_at = time.monotonic()
            # ids written after the to_matrix snapshot stay in _dirty_ids:
            # the next refresh tick folds them into the fresh overlay
            self._dirty_ids.difference_update(dirty0)
            self._y_dirty = bool(self._dirty_ids)
            self._index_generation = gid
        return True

    def all_item_ids(self) -> list[str]:
        return self.y.ids()

    def all_user_ids(self) -> list[str]:
        return self.x.ids()

    def close(self) -> None:
        """Orderly teardown: reap the in-flight X restage thread and drop
        the device-resident score matrices so a replaced model (fleet
        rotation, MODEL update with new hyperparams) releases its HBM
        instead of pinning it until GC notices. Idempotent."""
        t = self._x_restage_thread
        if t is not None:
            self._x_restage_thread = None
            t.join(timeout=10.0)
        with self._cache_lock:
            self._y_matrix = None
            self._y_host = None
            self._y_partitions = None
            self._x_matrix = None
            self._x_index = {}
            self._x_ids = []
            # a straggler request still holding this model rebuilds from
            # the vector stores instead of scoring against a dropped cache
            self._y_dirty = True
            self._y_full_rebuild = True
            self._x_dirty = True
            self._x_full_rebuild = True

    def __repr__(self) -> str:  # pragma: no cover
        return f"ALSServingModel[features={self.features}, X={self.x.size()}, Y={self.y.size()}]"


def _host_top_k(
    y_host: np.ndarray,
    rows: np.ndarray,
    query: np.ndarray,
    k: int,
    cosine: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Partial top-k over an LSH-pruned row subset, on host: the scored
    candidate set is already ~sample-rate of the items, so numpy argpartition
    beats a device round-trip at these sizes."""
    sub = y_host[rows]
    scores = sub @ np.asarray(query, dtype=np.float32)
    if cosine:
        qn = float(np.linalg.norm(query))
        norms = np.linalg.norm(sub, axis=1)
        scores = scores / np.maximum(norms * qn, 1e-12)
    k = max(1, min(int(k), len(rows)))
    part = np.argpartition(-scores, k - 1)[:k]
    order = part[np.argsort(-scores[part])]
    return rows[order], scores[order]


class ALSServingModelManager(AbstractServingModelManager):
    """Consume protocol identical to the speed manager plus known-items
    from UP payloads and rescorer loading
    (ALSServingModelManager.java:46-176)."""

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.implicit = config.get_bool("oryx.als.implicit")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.sample_rate = config.get_float("oryx.als.sample-rate")
        self.score_dtype = config.get_string("oryx.als.serving.score-dtype")
        self.shard_items = config.get_bool("oryx.als.serving.shard-items")
        self.device_user_matrix = config.get_bool(
            "oryx.als.serving.device-user-matrix"
        )
        if self.score_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"oryx.als.serving.score-dtype must be float32, bfloat16, or "
                f"int8, got {self.score_dtype!r}"
            )
        self.rescorer_provider = _load_rescorer_providers(config)
        self.model: ALSServingModel | None = None
        self._consumed = 0

    def consume_blocks(self, block_iterator) -> None:
        """Columnar consume: contiguous "UP" runs parse vectorized and
        apply via the batched setters (replay of a factor publish is one
        UP per row — a million-record startup replay). X rows carrying
        known-item lists parse those too; anything escaped or unusual
        falls back to per-record consume in order."""
        consume_blocks_columnar(
            block_iterator,
            lambda: self.model is not None,
            self._apply_up_batch,
            self.consume,
        )

    def _apply_up_batch(self, lines: list[bytes]) -> None:
        model = self.model
        applied = apply_up_lines(
            lines,
            model.features,
            model.set_user_vectors,
            model.set_item_vectors,
            lambda km: self.consume(iter([km])),
            on_known=(
                None
                if self.no_known_items
                else lambda pairs: model.add_known_items_many(pairs)
            ),
            strict_tail=True,  # the known list is part of the wire contract
        )
        self._consumed += applied  # slow path self-counts

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            key, message = km.key, km.message
            if key == "UP":
                if self.model is None:
                    continue
                update = read_json(message)
                which, id_ = update[0], str(update[1])
                vector = np.asarray(update[2], dtype=np.float32)
                if which == "X":
                    self.model.set_user_vector(id_, vector)
                    if len(update) > 3 and not self.no_known_items:
                        self.model.add_known_items(id_, [str(i) for i in update[3]])
                elif which == "Y":
                    self.model.set_item_vector(id_, vector)
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                features = int(app_pmml.get_required_extension_value(pmml, "features"))
                implicit = app_pmml.get_required_extension_value(pmml, "implicit") == "true"
                x_ids = set(app_pmml.get_extension_content(pmml, "XIDs") or [])
                y_ids = set(app_pmml.get_extension_content(pmml, "YIDs") or [])
                if (
                    self.model is None
                    or self.model.features != features
                    or self.model.implicit != implicit
                ):
                    old = self.model
                    self.model = ALSServingModel(
                        features,
                        implicit,
                        sample_rate=self.sample_rate,
                        score_dtype=self.score_dtype,
                        shard_items=self.shard_items,
                        device_user_matrix=self.device_user_matrix,
                    )
                    self.model.set_expected(x_ids, y_ids)
                    if old is not None:
                        # requests racing the swap hold their own model ref
                        # (get_model snapshots); teardown only reaps the
                        # restage thread and drops device matrices
                        old.close()
                else:
                    self.model.retain_recent_and_user_ids(x_ids)
                    self.model.retain_recent_and_item_ids(y_ids)
                    self.model.retain_recent_and_known_items(
                        x_ids | set(self.model.all_user_ids())
                    )
                    self.model.set_expected(x_ids, y_ids)
            elif key == "INDEX-REF":
                # ANN index generation (serving/maintain.py): rebuild this
                # replica's IVF layout seeded with the published centroids
                # and swap with zero downtime; unusable refs are dropped
                # (the local layout keeps serving)
                if self.model is not None:
                    try:
                        self.model.apply_index_generation(message)
                    except Exception:
                        log.warning(
                            "dropped unusable index generation %r", message,
                            exc_info=True,
                        )
            else:
                raise ValueError(f"bad key {key}")
            self._consumed += 1
            if self._consumed % 10_000 == 0:
                log.info("%s updates consumed; model: %r", self._consumed, self.model)

    def get_model(self) -> ALSServingModel | None:
        return self.model

    def close(self) -> None:
        model, self.model = self.model, None
        if model is not None:
            model.close()


def _load_rescorer_providers(config: Config):
    """Load RescorerProvider chain from oryx.als.rescorer-provider-class
    (ALSServingModelManager.java:141-174)."""
    names = config.get_optional_strings("oryx.als.rescorer-provider-class")
    if not names:
        return None
    from oryx_tpu.app.als.rescorer import MultiRescorerProvider
    from oryx_tpu.common.lang import load_instance_of

    providers = [load_instance_of(n) for n in names]
    if len(providers) == 1:
        return providers[0]
    return MultiRescorerProvider(providers)
