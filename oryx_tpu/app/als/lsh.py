"""Locality-sensitive hashing for approximate ALS top-N.

Behavioral port of the reference's LocalitySensitiveHash
(app/oryx-app-serving/.../als/model/LocalitySensitiveHash.java:26-188):
sign-of-dot-product bit hashing of item vectors into 2^h partitions, with
candidate partitions being every index within `max_bits_differing` Hamming
distance of the query's partition. The hash count is the smallest h whose
probed-partition fraction is <= the configured sample rate while the probe
count still keeps >= num_cores workers busy.

On TPU the exact batched matvec over all items is usually faster than any
pruning, so LSH is opt-in via oryx.als.sample-rate < 1.0 — the CPU-fallback
parity path (SURVEY.md §2.12: "LSH pruning becomes optional"). Partition
assignment here is vectorized over the whole item matrix instead of the
reference's per-vector loop.
"""

from __future__ import annotations

import math
from itertools import combinations

import numpy as np

from oryx_tpu.common import rng as rng_mod

MAX_HASHES = 16


def choose_hashes_and_bits(sample_rate: float, num_cores: int) -> tuple[int, int]:
    """Smallest hash count (and widest Hamming radius) such that the probed
    fraction of partitions is <= sample_rate while the number of probed
    partitions stays near num_cores (LocalitySensitiveHash.java:41-76;
    probe count may overshoot num_cores by one binomial step)."""
    bits_differing = 0
    for num_hashes in range(MAX_HASHES):
        bits_differing = 0
        partitions_to_try = 1
        while bits_differing < num_hashes and partitions_to_try < num_cores:
            bits_differing += 1
            partitions_to_try += math.comb(num_hashes, bits_differing)
        if bits_differing == num_hashes and partitions_to_try < num_cores:
            continue  # can't keep enough cores busy; add hashes
        if partitions_to_try <= sample_rate * (1 << num_hashes):
            return num_hashes, bits_differing
    return MAX_HASHES, bits_differing


def _choose_orthogonal_vectors(num_hashes: int, num_features: int) -> np.ndarray:
    """Random hash vectors picked greedily most-orthogonal by rejection:
    keep drawing until 1000 consecutive candidates fail to lower the total
    |cosine| against the already-chosen set (LocalitySensitiveHash.java:
    80-105)."""
    gen = rng_mod.get_random()
    chosen = np.zeros((num_hashes, num_features), dtype=np.float32)
    norms = np.zeros(num_hashes)
    for i in range(num_hashes):
        best_score = np.inf
        best = None
        since_best = 0
        while since_best < 1000:
            candidate = gen.standard_normal(num_features).astype(np.float32)
            cnorm = float(np.linalg.norm(candidate))
            if cnorm == 0.0:
                continue
            if i == 0:
                score = 0.0
            else:
                dots = np.abs(chosen[:i] @ candidate)
                score = float((dots / (norms[:i] * cnorm)).sum())
            if score < best_score:
                best = candidate
                if score == 0.0:
                    break
                best_score = score
                since_best = 0
            else:
                since_best += 1
        chosen[i] = best
        norms[i] = float(np.linalg.norm(best))
    return chosen


class LocalitySensitiveHash:
    def __init__(self, sample_rate: float, num_features: int, num_cores: int) -> None:
        self.num_hashes, self.max_bits_differing = choose_hashes_and_bits(
            sample_rate, num_cores
        )
        self.hash_vectors = _choose_orthogonal_vectors(self.num_hashes, num_features)
        # all 2^h indices ordered by popcount, the XOR-mask prototype for
        # candidate enumeration (LocalitySensitiveHash.java:108-117)
        masks: list[int] = []
        for bits in range(self.num_hashes + 1):
            masks.extend(
                sum(1 << b for b in combo)
                for combo in combinations(range(self.num_hashes), bits)
            )
        self._masks_by_popcount = np.asarray(masks, dtype=np.int64)
        self._num_candidates = sum(
            math.comb(self.num_hashes, i) for i in range(self.max_bits_differing + 1)
        )

    @property
    def num_partitions(self) -> int:
        return 1 << self.num_hashes

    def index_for(self, vector: np.ndarray) -> int:
        """Partition index: bit i set iff hash_i . v > 0
        (getIndexFor:142-150)."""
        if self.num_hashes == 0:
            return 0
        dots = self.hash_vectors @ np.asarray(vector, dtype=np.float32)
        return int(((dots > 0.0) << np.arange(self.num_hashes)).sum())

    def partitions_for(self, matrix: np.ndarray) -> np.ndarray:
        """Vectorized index_for over rows of an [n, k] matrix."""
        if self.num_hashes == 0:
            return np.zeros(len(matrix), dtype=np.int64)
        bits = (matrix @ self.hash_vectors.T) > 0.0
        return (bits << np.arange(self.num_hashes)).sum(axis=1).astype(np.int64)

    def candidate_indices(self, vector: np.ndarray) -> np.ndarray:
        """All partition indices within max_bits_differing Hamming distance
        of the query's partition (getCandidateIndices:156-177)."""
        main = self.index_for(vector)
        if self.num_hashes == self.max_bits_differing:
            return np.arange(self.num_partitions, dtype=np.int64)
        if self.max_bits_differing == 0:
            return np.asarray([main], dtype=np.int64)
        return self._masks_by_popcount[: self._num_candidates] ^ main
