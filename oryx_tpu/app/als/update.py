"""ALS batch trainer: the MLUpdate implementation.

Rebuild of ALSUpdate (app/oryx-app-mllib/.../als/ALSUpdate.java:65-506)
with the MLlib hot loop replaced by the JAX kernel in oryx_tpu.ops.als:

- build_model: parse -> decay -> aggregate -> indexed COO -> train_als on
  the device mesh; factors exported as gzip JSON-lines shards under X/
  and Y/ in the candidate dir (mfModelToPMML/saveFeaturesRDD:359-426
  artifact shape), PMML skeleton carries features/lambda/alpha/implicit
  and the expected-ID lists (XIDs/YIDs extensions) consumers use for
  load-fraction accounting and rotation.
- evaluate: implicit -> mean per-user AUC; explicit -> negated RMSE
  (ALSUpdate.evaluate:156-177).
- publish_additional_model_data: streams every Y row then every X row
  (with known items) to the update topic as "UP" messages
  (ALSUpdate.java:194-230; Y first, matching the comment at
  ALSSpeedModelManager.java:78-85).
- time-ordered train/test split (splitNewDataToTrainTest:237-254).
"""

from __future__ import annotations

import gzip
import json
import logging
from pathlib import Path
from typing import Iterable, Sequence
from xml.etree.ElementTree import Element

import numpy as np

from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.als import data as als_data
from oryx_tpu.bus.core import KeyMessage, TopicProducer
from oryx_tpu.common import pmml as pmml_io, rng
from oryx_tpu.common import storage
from oryx_tpu.common.records import ChainRecords, Records, as_records
from oryx_tpu.common.config import Config
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops import als as als_ops
from oryx_tpu.parallel.mesh import mesh_from_config

log = logging.getLogger(__name__)





class ALSUpdate(MLUpdate):
    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.iterations = config.get_int("oryx.als.iterations")
        self.implicit = config.get_bool("oryx.als.implicit")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.decay_factor = config.get_float("oryx.als.decay.factor")
        self.decay_zero_threshold = config.get_float("oryx.als.decay.zero-threshold")
        if not 0.0 < self.decay_factor <= 1.0:
            raise ValueError("decay factor must be in (0,1]")
        # Host-side neighbor packing knobs (oryx.ml.als.packing.*): worker
        # count "auto"|N, streamed-chunk size, and the shared-memory arena
        # budget for the multi-process path (ops/packing.py). Validated at
        # startup so a typo'd worker count fails the layer, not generation 40.
        workers = config.get("oryx.ml.als.packing.workers", "auto")
        if workers != "auto":
            workers = int(workers)
        self.packing = als_ops.PackingOptions(
            workers=workers,
            chunk_rows=config.get_int("oryx.ml.als.packing.chunk-rows"),
            shm_budget_mb=config.get_int("oryx.ml.als.packing.shared-mem-budget-mb"),
        )
        self._config = config

    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        c = self._config
        return [
            hp.from_config(c, "oryx.als.hyperparams.features"),
            hp.from_config(c, "oryx.als.hyperparams.lambda"),
            hp.from_config(c, "oryx.als.hyperparams.alpha"),
        ]

    # -- training ------------------------------------------------------------

    def _prepare(self, data: Iterable[KeyMessage]) -> als_data.RatingMatrix:
        """Columnar parse -> decay -> aggregate -> indexed COO, one
        micro-batch block at a time (common.records streams stored
        blocks, so nothing materializes a giant per-line Python list)."""
        parts: list[als_data.InteractionColumns] = []
        if isinstance(data, Records):
            for block in data.blocks():
                parts.append(als_data.parse_interaction_block(block.messages))
        else:
            msgs = [
                (rec if isinstance(rec, str) else rec.message).encode("utf-8")
                for rec in data
            ]
            if msgs:
                parts.append(als_data.parse_interaction_block(msgs))
        cols = als_data.concat_columns(parts)
        cols = als_data.decay_columns(cols, self.decay_factor, self.decay_zero_threshold)
        return als_data.rating_matrix_from_columns(cols, self.implicit)

    def build_model(
        self,
        train_data: list[KeyMessage],
        hyper_parameters: Sequence,
        candidate_path: Path,
    ) -> Element:
        features, lam, alpha = (
            int(hyper_parameters[0]),
            float(hyper_parameters[1]),
            float(hyper_parameters[2]),
        )
        if features <= 0 or lam < 0 or alpha <= 0:
            raise ValueError(f"bad hyperparams {hyper_parameters}")
        rm = self._prepare(train_data)
        if not rm.user_ids or not rm.item_ids:
            raise ValueError("no (user, item) interactions to train on")
        mesh = mesh_from_config(self._config)
        model = als_ops.train_als(
            rm.user_idx,
            rm.item_idx,
            rm.values,
            len(rm.user_ids),
            len(rm.item_ids),
            features=features,
            lam=lam,
            alpha=alpha,
            implicit=self.implicit,
            iterations=self.iterations,
            mesh=mesh,
            shard_factors=mesh is not None
            and bool(self._config.get("oryx.batch.compute.shard-factors", False)),
            matmul_dtype=self._config.get("oryx.batch.compute.matmul-dtype", None),
            init_y=self._warm_start_init_y(rm, features),
            packing=self.packing,
        )
        # dispatch hygiene: a warm generation whose degree buckets land on
        # the same pow2 shape signature reuses the compiled sweep (hits
        # grow, misses stay flat). A steadily climbing miss count means
        # bucket shapes are drifting every generation — worth a look.
        cache = als_ops.compiled_run_cache_info()
        log.info(
            "als compiled-run cache: %d hits, %d misses, %d programs resident",
            cache.hits, cache.misses, cache.currsize,
        )
        _save_features(candidate_path / "X", rm.user_ids, model.x)
        _save_features(candidate_path / "Y", rm.item_ids, model.y)
        return self._model_to_pmml(features, lam, alpha, rm)

    def _warm_start_init_y(
        self, rm: als_data.RatingMatrix, features: int
    ) -> np.ndarray | None:
        """Item-factor init from the champion generation's Y/ artifacts
        (MLUpdate.load_previous_model). Rows whose item survives into this
        generation start at the previous factor; new items get the usual
        small random init. Returns None (cold start) when there is no
        previous model, the feature count changed, or no item overlaps —
        warm-start is an optimization, never a correctness dependency."""
        if self.previous_model_dir is None:
            return None
        try:
            ids_y, y_prev = _load_features(storage.join(self.previous_model_dir, "Y"))
        except Exception:
            log.warning("unreadable previous Y factors; cold-starting", exc_info=True)
            return None
        if y_prev.size == 0 or y_prev.shape[1] != features:
            return None
        num_items = len(rm.item_ids)
        rows, found = _map_to_rows(
            rm.item_ids, np.arange(num_items, dtype=np.int32), ids_y
        )
        if not found.any():
            return None
        init = 0.1 * rng.get_random().standard_normal(
            (num_items, features)
        ).astype(np.float32)
        init[found] = y_prev[rows[found]]
        log.info(
            "warm-start from generation %s: %d/%d item factors carried over",
            self.previous_generation_id, int(found.sum()), num_items,
        )
        return init

    def _model_to_pmml(
        self, features: int, lam: float, alpha: float, rm: als_data.RatingMatrix
    ) -> Element:
        root = pmml_io.build_skeleton_pmml()
        app_pmml.add_extension(root, "X", "X/")
        app_pmml.add_extension(root, "Y", "Y/")
        app_pmml.add_extension(root, "features", features)
        app_pmml.add_extension(root, "lambda", lam)
        app_pmml.add_extension(root, "implicit", "true" if self.implicit else "false")
        if self.implicit:
            app_pmml.add_extension(root, "alpha", alpha)
        app_pmml.add_extension_content(root, "XIDs", rm.user_ids)
        app_pmml.add_extension_content(root, "YIDs", rm.item_ids)
        return root

    # -- evaluation ----------------------------------------------------------

    def evaluate(
        self,
        model: Element,
        model_parent_path: Path,
        test_data: list[KeyMessage],
        train_data: list[KeyMessage],
    ) -> float:
        ids_x, x = _load_features(storage.join(model_parent_path, "X"))
        ids_y, y = _load_features(storage.join(model_parent_path, "Y"))
        rm_test = self._prepare(test_data)
        # vectorized id -> model-row mapping (a per-pair Python dict walk
        # took minutes at 10M test pairs)
        uu, u_ok = _map_to_rows(rm_test.user_ids, rm_test.user_idx, ids_x)
        ii, i_ok = _map_to_rows(rm_test.item_ids, rm_test.item_idx, ids_y)
        keep = u_ok & i_ok
        if not keep.any():
            return float("nan")
        uu, ii = uu[keep], ii[keep]
        vv = rm_test.values[keep]
        if self.implicit:
            return als_ops.mean_auc(x, y, uu, ii, rng.get_random())
        return -als_ops.rmse(x, y, uu, ii, vv)

    # -- publish -------------------------------------------------------------

    def publish_additional_model_data(
        self,
        pmml: Element,
        new_data: list[KeyMessage],
        past_data: list[KeyMessage],
        model_parent_path: Path,
        model_update_topic: TopicProducer | None,
    ) -> None:
        if model_update_topic is None:
            return
        ids_y, y = _load_features(storage.join(model_parent_path, "Y"))
        # Y first: item vectors must exist before user fold-ins make sense
        _publish_factor_rows(model_update_topic, "Y", ids_y, y, None)
        ids_x, x = _load_features(storage.join(model_parent_path, "X"))
        known: dict[str, set[str]] | None = None
        if not self.no_known_items:
            rm = self._prepare(
                ChainRecords([as_records(new_data), as_records(past_data)])
            )
            known = rm.known_items
        _publish_factor_rows(model_update_topic, "X", ids_x, x, known)

    # -- split ---------------------------------------------------------------

    def split_new_data_to_train_test(
        self, new_data: list[KeyMessage]
    ) -> tuple[list[KeyMessage], list[KeyMessage]]:
        """Time-ordered split: the newest test-fraction is the test set
        (ALSUpdate.splitNewDataToTrainTest:237-254)."""
        if self.test_fraction <= 0.0:
            return list(new_data), []
        if self.test_fraction >= 1.0:
            return [], list(new_data)
        def ts_of(rec: KeyMessage) -> int:
            from oryx_tpu.common.text import parse_line

            tokens = parse_line(rec.message)
            return int(float(tokens[3])) if len(tokens) > 3 and tokens[3] != "" else 0

        ordered = sorted(new_data, key=ts_of)
        split = int(round(len(ordered) * (1.0 - self.test_fraction)))
        return ordered[:split], ordered[split:]


def _map_to_rows(
    ids: list[str], idx: np.ndarray, model_ids: list[str]
) -> tuple[np.ndarray, np.ndarray]:
    """Map per-interaction vocabulary indices to model-matrix rows:
    (rows int32, valid bool) with rows undefined where invalid (id not in
    the model). One sort + one searchsorted instead of a dict per pair."""
    if not ids or not len(model_ids):
        return np.zeros(len(idx), np.int32), np.zeros(len(idx), bool)
    vocab = np.array(ids, dtype="U")
    model = np.array(model_ids, dtype="U")
    order = np.argsort(model)
    pos = np.searchsorted(model[order], vocab)
    pos_clipped = np.minimum(pos, len(model) - 1)
    found = model[order][pos_clipped] == vocab  # [len(ids)]
    row_of_vocab = order[pos_clipped].astype(np.int32)  # valid only where found
    return row_of_vocab[idx], found[idx]


# -- publish helpers ---------------------------------------------------------

_PUBLISH_CHUNK = 8192


def _publish_factor_rows(
    producer: TopicProducer,
    tag: str,
    ids: list[str],
    matrix: np.ndarray,
    known: dict[str, set[str]] | None,
) -> None:
    """Chunked batch publish of ["X"|"Y", id, vector(, knownItems)] "UP"
    messages: vectors are JSON-formatted in bulk (native formatter when
    built) and each chunk ships via one `send_many` — one broker lock and
    one buffered write per chunk instead of one per row
    (cf. TopicProducerImpl.java:194-202 batching)."""
    from oryx_tpu.common.text import json_str
    from oryx_tpu.native.store import format_vectors_json

    for start in range(0, len(ids), _PUBLISH_CHUNK):
        chunk_ids = ids[start : start + _PUBLISH_CHUNK]
        vecs = format_vectors_json(matrix[start : start + _PUBLISH_CHUNK])
        if known is None:
            records = [
                ("UP", f'["{tag}",{json_str(i)},{v}]')
                for i, v in zip(chunk_ids, vecs)
            ]
        else:
            records = [
                (
                    "UP",
                    f'["{tag}",{json_str(i)},{v},'
                    f"{json.dumps(sorted(known.get(i, ())))}]",
                )
                for i, v in zip(chunk_ids, vecs)
            ]
        producer.send_many(records)


# -- factor-matrix artifacts -------------------------------------------------

_SHARD_ROWS = 500_000


def _save_features(dir_path: Path, ids: list[str], matrix: np.ndarray) -> None:
    """Gzip JSON-lines shards of [id, [floats]] (saveFeaturesRDD:415-426).

    Sharded by row count (part-0000N) like the reference's partitioned
    saveAsTextFile output, so a 40M-row factor matrix is many bounded
    files rather than one serial multi-GB gzip stream."""
    from oryx_tpu.native.store import format_vectors_json

    dir_path.mkdir(parents=True, exist_ok=True)
    n = len(ids)
    shard = 0
    for start in range(0, max(n, 1), _SHARD_ROWS):
        chunk_ids = ids[start : start + _SHARD_ROWS]
        with gzip.open(dir_path / f"part-{shard:05d}.json.gz", "wt", encoding="utf-8") as f:
            for id_, vec in zip(chunk_ids, format_vectors_json(matrix[start : start + _SHARD_ROWS])):
                f.write(f"[{json.dumps(id_)},{vec}]\n")
        shard += 1


def _load_features(dir_uri) -> tuple[list[str], np.ndarray]:
    """URI-aware: candidate dirs are local, promoted models may live on
    an object store (gs://...) — both read through common.storage."""
    ids: list[str] = []
    rows: list[list[float]] = []
    names = [
        n for n in storage.list_names(dir_uri)
        if n.startswith("part-") and n.endswith(".json.gz")
    ]
    for name in sorted(names):
        with storage.open_gzip_read(storage.join(dir_uri, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    id_, vec = json.loads(line)
                    ids.append(id_)
                    rows.append(vec)
    if not ids:
        return [], np.zeros((0, 0), dtype=np.float32)
    return ids, np.asarray(rows, dtype=np.float32)
