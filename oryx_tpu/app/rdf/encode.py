"""Feature binning/encoding for forest training.

The app-side bridge between raw schema'd input and the binned matrices
oryx_tpu.ops.forest trains on (the reference's analogous stage is
RDFUpdate.getDistinctValues + parseToLabeledPointRDD, RDFUpdate.java:
207-260):

- numeric features: quantile cut points (at most max-split-candidates,
  mirroring maxBins) with bin = index of first cut >= value; the split
  "bin <= b" becomes a NumericDecision threshold just above cut[b].
- categorical features: distinct values ordered by a target statistic
  (mean target for regression, P(first class) for classification — the
  classic ordered-split trick that makes subset splits threshold splits);
  the split "bin <= b" becomes a CategoricalDecision whose positive set
  is the categories ranked above b.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.common.text import parse_line


@dataclass
class FeatureBinning:
    """Per-predictor binning tables."""

    numeric_cuts: dict[int, np.ndarray]  # predictor idx -> sorted cut points
    category_rank: dict[int, np.ndarray]  # predictor idx -> rank per category id
    rank_to_category: dict[int, np.ndarray]  # predictor idx -> category id per rank
    num_bins: int


def parse_examples(
    data,
    schema: InputSchema,
    encodings: CategoricalValueEncodings,
    skip_unknown: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """(features [n, P] float64, targets [n]) with categorical features and
    categorical targets as encoded ids. With skip_unknown, malformed records
    — a categorical value absent from `encodings` (e.g. a test-split value
    never seen in training), a non-numeric token, or a short line — are
    dropped instead of raising (the speed layer feeds this raw client input
    from POST /train, so bad lines must not abort a micro-batch)."""
    rows, targets = [], []
    tfi = schema.target_feature_index
    for rec in data:
        row = np.empty(schema.num_predictors)
        target = None
        try:
            tokens = parse_line(rec.message if hasattr(rec, "message") else rec)
            for i in range(schema.num_features):
                if not schema.is_active(i):
                    continue
                tok = tokens[i]
                v = (
                    float(encodings.index_for(i, tok))
                    if schema.is_categorical(i)
                    else float(tok)
                )
                if i == tfi:
                    target = v
                row[schema.feature_to_predictor_index(i)] = v
        except (KeyError, ValueError, IndexError):
            if skip_unknown:
                continue
            raise
        rows.append(row)
        targets.append(target)
    if not rows:
        return np.zeros((0, schema.num_predictors)), np.zeros(0)
    return np.stack(rows), np.asarray(targets)


def build_encodings(data, schema: InputSchema) -> CategoricalValueEncodings:
    """Distinct categorical values, in stable sorted order
    (RDFUpdate.getDistinctValues:207-225)."""
    cat_idx = {
        i
        for i in range(schema.num_features)
        if schema.is_active(i) and schema.is_categorical(i)
    }
    values: dict[int, set] = {i: set() for i in cat_idx}
    for rec in data:
        tokens = parse_line(rec.message if hasattr(rec, "message") else rec)
        for i in values:
            values[i].add(tokens[i])
    return CategoricalValueEncodings({i: sorted(v) for i, v in values.items()})


def build_binning(
    features: np.ndarray,
    targets: np.ndarray,
    schema: InputSchema,
    max_split_candidates: int,
    classification: bool,
) -> FeatureBinning:
    p = features.shape[1]
    numeric_cuts: dict[int, np.ndarray] = {}
    category_rank: dict[int, np.ndarray] = {}
    rank_to_category: dict[int, np.ndarray] = {}
    max_b = 2
    cat_predictors = {
        schema.feature_to_predictor_index(i)
        for i in range(schema.num_features)
        if schema.is_active(i) and schema.is_categorical(i) and not schema.is_target(i)
    }
    tfi = schema.target_feature_index
    target_pred = schema.feature_to_predictor_index(tfi) if tfi is not None else None
    for j in range(p):
        if j == target_pred:
            continue
        col = features[:, j]
        if j in cat_predictors:
            cats = np.unique(col).astype(int)
            # order categories by target statistic
            stat = np.asarray(
                [
                    (targets[col == c] == 0).mean() if classification else targets[col == c].mean()
                    for c in cats
                ]
            )
            order = cats[np.argsort(stat, kind="stable")]
            rank = np.zeros(int(cats.max()) + 1, dtype=np.int32)
            rank[order] = np.arange(len(order))
            category_rank[j] = rank
            rank_to_category[j] = order.astype(np.int32)
            max_b = max(max_b, len(order))
        else:
            uniq = np.unique(col)
            if len(uniq) <= 1:
                cuts = uniq[:1] if len(uniq) else np.asarray([0.0])
            elif len(uniq) <= max_split_candidates:
                cuts = (uniq[:-1] + uniq[1:]) / 2.0
            else:
                qs = np.quantile(col, np.linspace(0, 1, max_split_candidates + 1)[1:-1])
                cuts = np.unique(qs)
            numeric_cuts[j] = cuts
            max_b = max(max_b, len(cuts) + 1)
    return FeatureBinning(numeric_cuts, category_rank, rank_to_category, max_b)


def bin_features(features: np.ndarray, binning: FeatureBinning) -> np.ndarray:
    n, p = features.shape
    out = np.zeros((n, p), dtype=np.int32)
    for j in range(p):
        if j in binning.numeric_cuts:
            out[:, j] = np.searchsorted(binning.numeric_cuts[j], features[:, j], side="left")
        elif j in binning.category_rank:
            rank = binning.category_rank[j]
            ids = np.clip(features[:, j].astype(np.int64), 0, len(rank) - 1)
            out[:, j] = rank[ids]
    return out
