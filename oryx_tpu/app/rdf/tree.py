"""Portable decision-forest representation, evaluated without the trainer.

Rebuild of the app/oryx-app-common rdf family (SURVEY.md §2.7):
Decision (rdf/decision/{NumericDecision,CategoricalDecision}.java),
TreeNode/DecisionNode/TerminalNode, DecisionTree (findTerminal:53,
findByID:66 — node IDs are PMML-compatible strings), DecisionForest
(weighted vote + feature importances, rdf/tree/DecisionForest.java:30-85)
and the prediction types (classreg/predict/{NumericPrediction,
CategoricalPrediction,WeightedPrediction}.java). The speed layer updates
leaf statistics in place via find_by_id + TerminalNode.update.

Node ID scheme: root "r", then "-" appended for the negative (left)
branch and "+" for the positive branch, matching the reference's
PMML-compatible string IDs.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

# ---------------------------------------------------------------------------
# Predictions
# ---------------------------------------------------------------------------


class NumericPrediction:
    """Mean target with observation count (NumericPrediction.java)."""

    def __init__(self, prediction: float, count: int) -> None:
        self.prediction = float(prediction)
        self.count = int(count)

    def update(self, value: float, count: int = 1) -> None:
        total = self.count + count
        self.prediction = (self.prediction * self.count + value * count) / total
        self.count = total

    def __repr__(self) -> str:  # pragma: no cover
        return f"NumericPrediction({self.prediction:.4f}, n={self.count})"


class CategoricalPrediction:
    """Per-category counts; predicted category = argmax
    (CategoricalPrediction.java)."""

    def __init__(self, counts: Sequence[float]) -> None:
        self.counts = np.asarray(counts, dtype=np.float64)

    @property
    def count(self) -> int:
        return int(self.counts.sum())

    @property
    def most_probable_index(self) -> int:
        return int(np.argmax(self.counts))

    @property
    def probabilities(self) -> np.ndarray:
        total = self.counts.sum()
        if total <= 0:
            return np.full(len(self.counts), 1.0 / len(self.counts))
        return self.counts / total

    def update(self, category: int, count: int = 1) -> None:
        self.counts[category] += count

    def __repr__(self) -> str:  # pragma: no cover
        return f"CategoricalPrediction({self.counts.tolist()})"


def weighted_vote(predictions: list, weights: list[float]):
    """Merge per-tree predictions into a forest prediction
    (WeightedPrediction.java)."""
    if not predictions:
        raise ValueError("no predictions")
    if isinstance(predictions[0], CategoricalPrediction):
        probs = sum(w * p.probabilities for p, w in zip(predictions, weights))
        return CategoricalPrediction(probs / sum(weights) * 1000.0)
    total_w = sum(weights)
    mean = sum(w * p.prediction for p, w in zip(predictions, weights)) / total_w
    return NumericPrediction(mean, sum(p.count for p in predictions))


# ---------------------------------------------------------------------------
# Decisions and nodes
# ---------------------------------------------------------------------------


@dataclass
class NumericDecision:
    """feature <= threshold is positive? No: mirror reference semantics —
    positive when value >= threshold (NumericDecision.java uses >=
    threshold as positive); missing defaults to `default_decision`."""

    feature: int
    threshold: float
    default_decision: bool = False

    def is_positive(self, features: Sequence) -> bool:
        v = features[self.feature]
        if v is None or (isinstance(v, float) and math.isnan(v)):
            return self.default_decision
        return float(v) >= self.threshold


@dataclass
class CategoricalDecision:
    """Positive when the category id is in `category_ids`
    (CategoricalDecision.java)."""

    feature: int
    category_ids: frozenset[int]
    default_decision: bool = False

    def is_positive(self, features: Sequence) -> bool:
        v = features[self.feature]
        if v is None or (isinstance(v, float) and math.isnan(v)):
            return self.default_decision
        return int(v) in self.category_ids


@dataclass
class TerminalNode:
    id: str
    prediction: NumericPrediction | CategoricalPrediction
    record_count: int = 0

    def is_terminal(self) -> bool:
        return True

    def update(self, value_or_category, count: int = 1) -> None:
        """Fold new observations into leaf stats (TerminalNode.update —
        the speed layer's leaf refresh)."""
        if isinstance(self.prediction, CategoricalPrediction):
            self.prediction.update(int(value_or_category), count)
        else:
            self.prediction.update(float(value_or_category), count)
        self.record_count += count


@dataclass
class DecisionNode:
    id: str
    decision: NumericDecision | CategoricalDecision
    negative: "DecisionNode | TerminalNode"
    positive: "DecisionNode | TerminalNode"
    record_count: int = 0

    def is_terminal(self) -> bool:
        return False


class DecisionTree:
    """One tree (DecisionTree.java:38-95)."""

    def __init__(self, root: DecisionNode | TerminalNode) -> None:
        self.root = root

    def find_terminal(self, features: Sequence) -> TerminalNode:
        node = self.root
        while not node.is_terminal():
            node = node.positive if node.decision.is_positive(features) else node.negative
        return node

    def find_terminals_batch(self, features) -> list[TerminalNode]:
        """Terminal node for every row of a [n, P] float array (NaN =
        missing) — each tree node is visited once per batch with its
        predicate evaluated vectorized over the rows that reached it,
        instead of a Python walk per example (the speed layer's leaf
        refresh runs whole micro-batches through this)."""
        features = np.asarray(features, dtype=np.float64)
        n = len(features)
        out: list[TerminalNode | None] = [None] * n
        stack: list = [(self.root, np.arange(n))]
        while stack:
            node, rows = stack.pop()
            if not len(rows):
                continue
            if node.is_terminal():
                for r in rows.tolist():
                    out[r] = node
                continue
            d = node.decision
            col = features[rows, d.feature]
            missing = np.isnan(col)
            if isinstance(d, NumericDecision):
                with np.errstate(invalid="ignore"):
                    pos = col >= d.threshold
            else:
                ids = np.where(missing, -1, col).astype(np.int64)
                pos = np.isin(ids, np.fromiter(d.category_ids, dtype=np.int64))
            pos = np.where(missing, d.default_decision, pos)
            stack.append((node.positive, rows[pos]))
            stack.append((node.negative, rows[~pos]))
        return out

    def find_by_id(self, node_id: str) -> DecisionNode | TerminalNode | None:
        """Walk by ID structure: '-'/'+' suffixes encode the path."""
        node = self.root
        if node_id == node.id:
            return node
        path = node_id[len(node.id) :]
        for step in path:
            if node.is_terminal():
                return None
            node = node.negative if step == "-" else node.positive
        return node if node.id == node_id else None

    def predict(self, features: Sequence):
        return self.find_terminal(features).prediction

    def nodes(self):
        stack = [self.root]
        while stack:
            n = stack.pop()
            yield n
            if not n.is_terminal():
                stack.append(n.negative)
                stack.append(n.positive)


class DecisionForest:
    """Weighted forest (DecisionForest.java:30-85)."""

    def __init__(
        self,
        trees: list[DecisionTree],
        weights: list[float] | None = None,
        feature_importances: np.ndarray | None = None,
    ) -> None:
        self.trees = trees
        self.weights = weights if weights is not None else [1.0] * len(trees)
        self.feature_importances = feature_importances

    def predict(self, features: Sequence):
        return weighted_vote([t.predict(features) for t in self.trees], self.weights)
