"""RDF batch trainer.

Rebuild of RDFUpdate (app/oryx-app-mllib/.../rdf/RDFUpdate.java:89-559)
on the TPU histogram trainer (oryx_tpu.ops.forest): distinct categorical
values -> encodings, quantile/ordered binning, level-wise forest growth
on device, conversion of the flat heap arrays into portable
DecisionTrees with real thresholds/category sets, per-node recordCounts
and feature importances (the reference re-runs training data down the
trees for these, RDFUpdate.treeNodeExampleCounts:269-; here the node
stats fall out of the histogram pass), PMML MiningModel/Segmentation
output, and accuracy / negated-RMSE evaluation against the app-tier
forest (batch/mllib/rdf/Evaluation.java:54)."""

from __future__ import annotations

import logging
import math
from pathlib import Path
from typing import Iterable, Sequence
from xml.etree.ElementTree import Element

import numpy as np

from oryx_tpu.app.rdf import encode, forest_pmml, tree as T
from oryx_tpu.parallel.mesh import mesh_from_config
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops import forest as forest_ops

log = logging.getLogger(__name__)


class RDFUpdate(MLUpdate):
    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_trees = config.get_int("oryx.rdf.num-trees")
        self.min_node_size = config.get_int("oryx.rdf.hyperparams.min-node-size")
        self.min_info_gain = config.get_float("oryx.rdf.hyperparams.min-info-gain-nats")
        self.hist_mode = config.get_string("oryx.ml.rdf.hist-mode")
        if self.hist_mode not in ("auto", "matmul", "scalar", "reference"):
            raise ValueError(f"unknown oryx.ml.rdf.hist-mode {self.hist_mode!r}")
        self.schema = InputSchema(config)
        if not self.schema.has_target():
            raise ValueError("rdf requires a target feature")
        self.classification = self.schema.is_categorical(self.schema.target_feature)
        self._config = config

    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        c = self._config
        return [
            hp.from_config(c, "oryx.rdf.hyperparams.max-split-candidates"),
            hp.from_config(c, "oryx.rdf.hyperparams.max-depth"),
            hp.from_config(c, "oryx.rdf.hyperparams.impurity"),
        ]

    def build_model(
        self,
        train_data: list[KeyMessage],
        hyper_parameters: Sequence,
        candidate_path: Path,
    ) -> Element:
        # Warm-start (MLUpdate.load_previous_model) is a deliberate no-op
        # for RDF: level-wise histogram growth rebuilds every tree from
        # the root, and seeding structure from a previous forest would
        # bias split selection without saving any device work (unlike ALS
        # factors / k-means centers, tree structure is not an iterate that
        # later sweeps refine). self.previous_model stays available should
        # an incremental variant (e.g. warm residual boosting) land.
        max_split_candidates = int(hyper_parameters[0])
        max_depth = int(hyper_parameters[1])
        impurity = str(hyper_parameters[2])
        if max_split_candidates < 2 or max_depth < 1:
            raise ValueError(f"bad hyperparams {hyper_parameters}")

        encodings = encode.build_encodings(train_data, self.schema)
        features, targets = encode.parse_examples(train_data, self.schema, encodings)
        binning = encode.build_binning(
            features, targets, self.schema, max_split_candidates, self.classification
        )
        binned = encode.bin_features(features, binning)
        tfi = self.schema.target_feature_index
        num_classes = encodings.category_count(tfi) if self.classification else None

        target_pred = self.schema.feature_to_predictor_index(tfi)
        arrays = forest_ops.train_forest(
            binned,
            targets.astype(np.int32) if self.classification else targets,
            num_bins=binning.num_bins,
            num_classes=num_classes,
            num_trees=self.num_trees,
            max_depth=max_depth,
            min_node_size=float(self.min_node_size),
            min_info_gain=self.min_info_gain,
            impurity=impurity,
            exclude_features={target_pred},
            mesh=mesh_from_config(self._config),
            hist_mode=self.hist_mode,
        )
        importances = forest_ops.feature_importances(arrays, features.shape[1])
        forest = arrays_to_forest(arrays, binning, importances)
        return forest_pmml.forest_to_pmml(forest, self.schema, encodings)

    def evaluate(
        self,
        model: Element,
        model_parent_path: Path,
        test_data: list[KeyMessage],
        train_data: list[KeyMessage],
    ) -> float:
        forest, encodings = forest_pmml.pmml_to_forest(model, self.schema)
        data = test_data if test_data else train_data
        if not data:
            return float("nan")
        features, targets = encode.parse_examples(
            data, self.schema, encodings, skip_unknown=True
        )
        if len(targets) == 0:
            return float("nan")
        if self.classification:
            correct = 0
            for row, target in zip(features, targets):
                pred = forest.predict(row)
                if pred.most_probable_index == int(target):
                    correct += 1
            return correct / len(targets)
        se = 0.0
        for row, target in zip(features, targets):
            pred = forest.predict(row)
            se += (pred.prediction - target) ** 2
        return -math.sqrt(se / len(targets))


def arrays_to_forest(
    arrays: forest_ops.ForestArrays,
    binning: encode.FeatureBinning,
    importances: np.ndarray | None = None,
) -> T.DecisionForest:
    """Convert flat heap arrays to portable DecisionTrees, mapping bins
    back to thresholds / category sets."""
    trees = []
    for t in range(arrays.num_trees):
        trees.append(T.DecisionTree(_node_from_heap(arrays, t, 0, "r", binning)))
    return T.DecisionForest(trees, [1.0] * len(trees), importances)


def _node_from_heap(arrays, t: int, heap: int, node_id: str, binning):
    feat = int(arrays.split_feature[t, heap])
    stats = arrays.node_stats[t, heap]
    count = arrays.node_counts[t, heap]
    if feat < 0:
        if arrays.num_classes is not None:
            return T.TerminalNode(node_id, T.CategoricalPrediction(stats), int(count))
        w, wy = stats[0], stats[1]
        mean = wy / w if w > 0 else 0.0
        return T.TerminalNode(node_id, T.NumericPrediction(mean, int(w)), int(count))
    b = int(arrays.split_bin[t, heap])
    if feat in binning.numeric_cuts:
        cuts = binning.numeric_cuts[feat]
        cut = cuts[min(b, len(cuts) - 1)]
        decision = T.NumericDecision(feat, float(np.nextafter(cut, np.inf)))
    else:
        order = binning.rank_to_category[feat]
        positive = frozenset(int(c) for c in order[b + 1 :])
        decision = T.CategoricalDecision(feat, positive)
    negative = _node_from_heap(arrays, t, 2 * heap + 1, node_id + "-", binning)
    positive_child = _node_from_heap(arrays, t, 2 * heap + 2, node_id + "+", binning)
    return T.DecisionNode(node_id, decision, negative, positive_child, int(count))
