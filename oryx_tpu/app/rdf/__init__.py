"""Random decision forest application: histogram-based TPU training,
portable forest inference, leaf-stat speed updates, prediction serving
(reference rdf components in SURVEY.md §2.7-2.10).
"""
