"""RDF speed layer: leaf-statistic refresh from new examples.

Rebuild of RDFSpeedModel (app/oryx-app/.../speed/rdf/RDFSpeedModel.java:
28-58) and RDFSpeedModelManager (.../RDFSpeedModelManager.java:59-153):
run each new example down every tree to its terminal node, group by
(treeID, nodeID), and emit per-leaf updates — classification:
``[treeID, nodeID, {category: count...}]``; regression:
``[treeID, nodeID, mean, count]``.
"""

from __future__ import annotations

import logging
from typing import Iterable, Iterator

import numpy as np

from oryx_tpu.api.speed import SpeedModel, SpeedModelManager
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.rdf import encode, forest_pmml, tree as T
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import join_json, read_json

log = logging.getLogger(__name__)


class RDFSpeedModel(SpeedModel):
    def __init__(self, forest: T.DecisionForest, encodings) -> None:
        self.forest = forest
        self.encodings = encodings

    def get_fraction_loaded(self) -> float:
        return 1.0


class RDFSpeedModelManager(SpeedModelManager):
    def __init__(self, config: Config) -> None:
        self.schema = InputSchema(config)
        if not self.schema.has_target():
            raise ValueError("rdf requires a target feature")
        self.classification = self.schema.is_categorical(self.schema.target_feature)
        self.model: RDFSpeedModel | None = None

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            key, message = km.key, km.message
            if key == "UP":
                pass  # leaf updates are applied by serving; speed ignores its own
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                forest, encodings = forest_pmml.pmml_to_forest(pmml, self.schema)
                self.model = RDFSpeedModel(forest, encodings)
            else:
                raise ValueError(f"bad key {key}")

    def build_updates(self, new_data: Iterable[KeyMessage]) -> Iterable[str]:
        model = self.model
        if model is None:
            return []
        features, targets = encode.parse_examples(
            new_data, self.schema, model.encodings, skip_unknown=True
        )
        tfi = self.schema.target_feature_index
        # (treeID, nodeID) -> stats; one vectorized descent per tree
        # (find_terminals_batch), not a Python walk per (example, tree)
        by_leaf: dict[tuple[int, str], list] = {}
        for tree_id, tree in enumerate(model.forest.trees):
            leaves = tree.find_terminals_batch(features)
            for leaf, target in zip(leaves, targets):
                key = (tree_id, leaf.id)
                if self.classification:
                    counts = by_leaf.setdefault(key, [{}])[0]
                    cat = model.encodings.value_for(tfi, int(target))
                    counts[cat] = counts.get(cat, 0) + 1
                else:
                    cur = by_leaf.setdefault(key, [0.0, 0])
                    cur[0] += float(target)
                    cur[1] += 1
        out = []
        for (tree_id, node_id), stats in by_leaf.items():
            if self.classification:
                out.append(join_json([tree_id, node_id, stats[0]]))
            else:
                total, count = stats
                out.append(join_json([tree_id, node_id, total / count, count]))
        return out

    def close(self) -> None:
        pass
