"""Forest <-> PMML MiningModel conversion.

Rebuild of RDFPMMLUtils (app/oryx-app-common/.../rdf/RDFPMMLUtils.java)
and the PMML-emitting half of RDFUpdate.rdfModelToPMML: a MiningModel
with a Segmentation of one TreeModel per tree; Nodes carry id (the
"r"/"-"/"+" path scheme of oryx_tpu.app.rdf.tree), recordCount, score,
and ScoreDistribution for classification; predicates are SimplePredicate
(numeric) or SimpleSetPredicate (categorical).
"""

from __future__ import annotations

import numpy as np
from xml.etree.ElementTree import Element

from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.rdf import tree as T
from oryx_tpu.app.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.common import pmml as pmml_io


def forest_to_pmml(
    forest: T.DecisionForest,
    schema: InputSchema,
    encodings: CategoricalValueEncodings,
) -> Element:
    """Document layout matches RDFUpdate.rdfModelToPMML:369-423 +
    toTreeModel:424-516 element-for-element: DataDictionary then a
    MiningModel (multi-tree) or bare TreeModel (single tree), Segments
    carrying an un-schema'd TreeModel with splitCharacteristic=
    binarySplit and missingValueStrategy=defaultChild, root Extensions
    last. Feature importances ride MiningField importance attributes (the
    reference's channel) plus the round-trip `importances` extension."""
    root = pmml_io.build_skeleton_pmml()
    app_pmml.build_data_dictionary(root, schema, encodings)
    classification = schema.target_feature is not None and schema.is_categorical(
        schema.target_feature
    )
    function = "classification" if classification else "regression"
    importances = (
        list(forest.feature_importances) if forest.feature_importances is not None else None
    )
    tree_attrs = {
        "splitCharacteristic": "binarySplit",
        "missingValueStrategy": "defaultChild",
    }
    if len(forest.trees) == 1:
        tm = pmml_io.sub(root, "TreeModel", {"functionName": function, **tree_attrs})
        app_pmml.build_mining_schema(tm, schema, importances)
        _write_node(tm, forest.trees[0].root, None, schema, encodings, classification)
    else:
        mm = pmml_io.sub(root, "MiningModel", {"functionName": function})
        app_pmml.build_mining_schema(mm, schema, importances)
        seg = pmml_io.sub(
            mm,
            "Segmentation",
            {
                "multipleModelMethod": "weightedMajorityVote"
                if classification
                else "weightedAverage"
            },
        )
        for i, (tree, weight) in enumerate(zip(forest.trees, forest.weights)):
            s = pmml_io.sub(seg, "Segment", {"id": str(i), "weight": repr(float(weight))})
            pmml_io.sub(s, "True")
            # segment TreeModels carry no MiningSchema or functionName of
            # their own, exactly like the reference's inner toTreeModel
            tm = pmml_io.sub(s, "TreeModel", dict(tree_attrs))
            _write_node(tm, tree.root, None, schema, encodings, classification)
    if forest.feature_importances is not None:
        app_pmml.add_extension_content(
            root, "importances", [repr(float(v)) for v in forest.feature_importances]
        )
    return root


def _node_count(node) -> float:
    return float(node.prediction.count if node.is_terminal() else node.record_count)


def _write_node(parent, node, predicate_writer, schema, encodings, classification) -> None:
    attrs = {"id": node.id, "recordCount": repr(_node_count(node))}
    if node.is_terminal() and not classification:
        # classification leaves carry only ScoreDistributions, exactly
        # like toTreeModel:458-487 (no score attribute)
        attrs["score"] = repr(float(node.prediction.prediction))
    if not node.is_terminal():
        # defaultChild = the heavier branch, the reference's missing-value
        # routing (toTreeModel:494-499)
        heavier_positive = _node_count(node.positive) > _node_count(node.negative)
        attrs["defaultChild"] = node.positive.id if heavier_positive else node.negative.id
    el = pmml_io.sub(parent, "Node", attrs)
    if predicate_writer is None:
        pmml_io.sub(el, "True")
    else:
        predicate_writer(el)
    if node.is_terminal():
        if classification:
            tfi = schema.target_feature_index
            total = max(1.0, float(node.prediction.counts.sum()))
            for ci, cnt in enumerate(node.prediction.counts):
                if cnt <= 0:
                    continue  # zero-probability rows omitted (toTreeModel:478)
                sd = pmml_io.sub(
                    el,
                    "ScoreDistribution",
                    {"value": encodings.value_for(tfi, ci), "recordCount": repr(float(cnt))},
                )
                sd.set("confidence", repr(float(cnt) / total))
        return
    d = node.decision
    feature_index = schema.predictor_to_feature_index(d.feature)
    name = schema.feature_names[feature_index]
    if isinstance(d, T.NumericDecision):
        def neg(el2, name=name, d=d):
            pmml_io.sub(el2, "SimplePredicate", {"field": name, "operator": "lessThan", "value": repr(d.threshold)})

        def pos(el2, name=name, d=d):
            pmml_io.sub(el2, "SimplePredicate", {"field": name, "operator": "greaterOrEqual", "value": repr(d.threshold)})
    else:
        pos_values = [encodings.value_for(feature_index, c) for c in sorted(d.category_ids)]

        def neg(el2, name=name, vals=pos_values):
            sp = pmml_io.sub(el2, "SimpleSetPredicate", {"field": name, "booleanOperator": "isNotIn"})
            arr = pmml_io.sub(sp, "Array", {"n": str(len(vals)), "type": "string"})
            arr.text = " ".join(_quote(v) for v in vals)

        def pos(el2, name=name, vals=pos_values):
            sp = pmml_io.sub(el2, "SimpleSetPredicate", {"field": name, "booleanOperator": "isIn"})
            arr = pmml_io.sub(sp, "Array", {"n": str(len(vals)), "type": "string"})
            arr.text = " ".join(_quote(v) for v in vals)

    # the positive (predicate-carrying) child comes FIRST: PMML evaluates
    # predicates in document order, and the negative child's True would
    # otherwise always match (RDFUpdate.toTreeModel:500-505 — "Right node
    # is 'positive', so carries the predicate. It must evaluate first")
    _write_node(el, node.positive, pos, schema, encodings, classification)
    _write_node(el, node.negative, neg, schema, encodings, classification)


def _quote(v: str) -> str:
    return f'"{v}"' if (" " in v or not v) else v


def _unquote_array(text: str) -> list[str]:
    import re

    # group(1) may legitimately be '' (a quoted empty-string category), so
    # test against None rather than truthiness
    return [
        m.group(1) if m.group(1) is not None else m.group(2)
        for m in re.finditer(r'"([^"]*)"|(\S+)', text or "")
    ]


def pmml_to_forest(
    root: Element, schema: InputSchema
) -> tuple[T.DecisionForest, CategoricalValueEncodings]:
    """Inverse of forest_to_pmml (RDFPMMLUtils.read)."""
    encodings = app_pmml.build_categorical_encodings(root, schema)
    mm = pmml_io.find(root, "MiningModel")
    classification = schema.target_feature is not None and schema.is_categorical(
        schema.target_feature
    )
    tfi = schema.target_feature_index
    num_classes = encodings.category_count(tfi) if classification else 0
    trees, weights = [], []
    importances = app_pmml.get_extension_content(root, "importances")
    if mm is None:
        # single-tree documents carry a bare TreeModel (RDFUpdate:383-384)
        tm = pmml_io.find(root, "TreeModel")
        if tm is None:
            raise ValueError("no MiningModel or TreeModel in PMML")
        node_el = pmml_io.find(tm, "Node")
        trees.append(
            T.DecisionTree(_read_node(node_el, schema, encodings, classification, num_classes))
        )
        weights.append(1.0)
    else:
        seg = pmml_io.find(mm, "Segmentation")
        for s in pmml_io.findall(seg, "Segment"):
            weights.append(float(s.get("weight", "1")))
            tm = pmml_io.find(s, "TreeModel")
            node_el = pmml_io.find(tm, "Node")
            trees.append(T.DecisionTree(_read_node(node_el, schema, encodings, classification, num_classes)))
    fi = np.asarray([float(v) for v in importances]) if importances else None
    return T.DecisionForest(trees, weights, fi), encodings


def _read_node(el, schema, encodings, classification, num_classes):
    children = pmml_io.findall(el, "Node")
    node_id = el.get("id")
    if not children:
        rc = float(el.get("recordCount", "0"))
        if classification:
            counts = np.zeros(num_classes)
            for sd in pmml_io.findall(el, "ScoreDistribution"):
                tfi = schema.target_feature_index
                counts[encodings.index_for(tfi, sd.get("value"))] = float(sd.get("recordCount"))
            return T.TerminalNode(node_id, T.CategoricalPrediction(counts), int(rc))
        return T.TerminalNode(
            node_id, T.NumericPrediction(float(el.get("score", "0")), int(rc)), int(rc)
        )
    assert len(children) == 2, "binary trees expected"
    # identify the positive child by its predicate OPERATOR (greaterThan/
    # greaterOrEqual/isIn positive; lessThan/lessOrEqual/isNotIn/True
    # negative), like RDFPMMLUtils.translateFromPMML:206-224 — element
    # order alone inverts branches on persisted documents whose writer
    # put the negative predicate first (only a True-vs-predicate check
    # can't tell, since both children may carry real predicates).
    p0, p1 = _child_polarity(children[0]), _child_polarity(children[1])
    if p1 > p0:
        pos_el, neg_el = children[1], children[0]
    else:
        # includes the indeterminate tie: the reference writes the
        # positive (predicate-evaluated-first) child in document order
        pos_el, neg_el = children[0], children[1]
    decision = _read_predicate(pos_el, schema, encodings)
    negative = _read_node(neg_el, schema, encodings, classification, num_classes)
    positive = _read_node(pos_el, schema, encodings, classification, num_classes)
    return T.DecisionNode(
        node_id, decision, negative, positive, int(float(el.get("recordCount", "0")))
    )


def _child_polarity(el) -> int:
    """+1 if this child's predicate marks it the positive branch, -1 the
    negative, 0 indeterminate. greaterThan/greaterOrEqual and isIn are
    positive by the writer's convention; lessThan/lessOrEqual, isNotIn
    and a bare True (producers that predicate only one child) negative."""
    sp = pmml_io.find(el, "SimplePredicate")
    if sp is not None:
        op = sp.get("operator")
        if op in ("greaterThan", "greaterOrEqual"):
            return 1
        if op in ("lessThan", "lessOrEqual"):
            return -1
        return 0
    ssp = pmml_io.find(el, "SimpleSetPredicate")
    if ssp is not None:
        op = ssp.get("booleanOperator")
        if op == "isIn":
            return 1
        if op == "isNotIn":
            return -1
        return 0
    if pmml_io.find(el, "True") is not None:
        return -1
    return 0


def _read_predicate(el, schema, encodings):
    sp = pmml_io.find(el, "SimplePredicate")
    if sp is not None:
        feature_index = schema.feature_names.index(sp.get("field"))
        return T.NumericDecision(
            schema.feature_to_predictor_index(feature_index), float(sp.get("value"))
        )
    ssp = pmml_io.find(el, "SimpleSetPredicate")
    if ssp is None:
        raise ValueError("node missing predicate")
    feature_index = schema.feature_names.index(ssp.get("field"))
    arr = pmml_io.find(ssp, "Array")
    values = _unquote_array(arr.text)
    ids = frozenset(encodings.index_for(feature_index, v) for v in values)
    return T.CategoricalDecision(schema.feature_to_predictor_index(feature_index), ids)
