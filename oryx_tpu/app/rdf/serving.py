"""RDF serving: model, manager, and classification/regression endpoints.

Rebuild of RDFServingModel (app/oryx-app-serving/.../rdf/model/
RDFServingModel.java:34-90) + RDFServingModelManager (consume applies
speed-layer leaf updates via DecisionTree.findByID + TerminalNode.update)
and the endpoints: GET/POST /predict (classreg/Predict.java:51), POST
/train (classreg/Train.java), GET /classificationDistribution
(rdf/ClassificationDistribution.java:53), GET /feature/importance[/{i}]
(rdf/FeatureImportance.java:46-63).
"""

from __future__ import annotations

import logging
from typing import Iterator

import numpy as np

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.rdf import encode, forest_pmml, tree as T
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.app.serving_common import check_not_read_only, get_ready_model, send_input
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.lang import ReadWriteLock
from oryx_tpu.common.text import parse_line, read_json
from oryx_tpu.serving.web import OryxServingException, Request, Response, ServingContext, resource

log = logging.getLogger(__name__)


class RDFServingModel(ServingModel):
    def __init__(self, forest: T.DecisionForest, encodings, schema: InputSchema) -> None:
        self.forest = forest
        self.encodings = encodings
        self.schema = schema
        self.classification = schema.is_categorical(schema.target_feature)
        # traversal is read-mostly: concurrent /predict share the read side,
        # only leaf updates take the write side (reference RDFServingModel
        # guards the forest with an AutoReadWriteLock the same way)
        self._lock = ReadWriteLock()

    def get_fraction_loaded(self) -> float:
        return 1.0

    def _features_from(self, datum: str) -> np.ndarray:
        tokens = parse_line(datum)
        row = np.empty(self.schema.num_predictors)
        for i in range(self.schema.num_features):
            if not self.schema.is_active(i):
                continue
            p = self.schema.feature_to_predictor_index(i)
            if self.schema.is_target(i):
                row[p] = np.nan
                continue
            tok = tokens[i] if i < len(tokens) else ""
            if tok == "":
                # missing value: routed by the decision's default branch
                # (Predict supports missing fields via default_decision)
                row[p] = np.nan
                continue
            try:
                row[p] = (
                    float(self.encodings.index_for(i, tok))
                    if self.schema.is_categorical(i)
                    else float(tok)
                )
            except (KeyError, ValueError):
                raise OryxServingException(400, f"bad datum field {tok!r}")
        return row

    def predict(self, datum: str):
        with self._lock.read():
            return self.forest.predict(self._features_from(datum))

    def update_leaf(self, tree_id: int, node_id: str, payload) -> None:
        with self._lock.write():
            if tree_id >= len(self.forest.trees):
                return
            node = self.forest.trees[tree_id].find_by_id(node_id)
            if node is None or not node.is_terminal():
                return
            tfi = self.schema.target_feature_index
            if self.classification:
                for cat, count in payload.items():
                    try:
                        node.update(self.encodings.index_for(tfi, cat), int(count))
                    except KeyError:
                        pass  # unseen category: not representable in this model
            else:
                mean, count = payload
                node.update(float(mean), int(count))


class RDFServingModelManager(AbstractServingModelManager):
    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.schema = InputSchema(config)
        if not self.schema.has_target():
            raise ValueError("rdf requires a target feature")
        self.model: RDFServingModel | None = None

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            key, message = km.key, km.message
            if key == "UP":
                if self.model is None:
                    continue
                update = read_json(message)
                tree_id, node_id = int(update[0]), str(update[1])
                payload = update[2] if self.model.classification else (update[2], update[3])
                self.model.update_leaf(tree_id, node_id, payload)
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                forest, encodings = forest_pmml.pmml_to_forest(pmml, self.schema)
                self.model = RDFServingModel(forest, encodings, self.schema)
            else:
                raise ValueError(f"bad key {key}")

    def get_model(self) -> RDFServingModel | None:
        return self.model


def _predict_value(model: RDFServingModel, datum: str):
    pred = model.predict(datum)
    if model.classification:
        tfi = model.schema.target_feature_index
        return model.encodings.value_for(tfi, pred.most_probable_index)
    return pred.prediction


@resource("GET", "/predict/{datum}")
def predict(ctx: ServingContext, req: Request):
    """classreg/Predict.java."""
    model = get_ready_model(ctx)
    return _predict_value(model, req.params["datum"])


@resource("POST", "/predict")
def predict_many(ctx: ServingContext, req: Request):
    model = get_ready_model(ctx)
    return [
        _predict_value(model, line.strip())
        for line in req.text().splitlines()
        if line.strip()
    ]


@resource("GET", "/classificationDistribution/{datum}")
def classification_distribution(ctx: ServingContext, req: Request):
    """rdf/ClassificationDistribution.java: category -> probability."""
    model = get_ready_model(ctx)
    if not model.classification:
        raise OryxServingException(400, "not a classification model")
    pred = model.predict(req.params["datum"])
    tfi = model.schema.target_feature_index
    probs = pred.probabilities
    return {
        model.encodings.value_for(tfi, i): float(p) for i, p in enumerate(probs)
    }


@resource("GET", "/feature/importance")
def feature_importance(ctx: ServingContext, req: Request):
    """rdf/FeatureImportance.java: all importances by feature name."""
    model = get_ready_model(ctx)
    fi = model.forest.feature_importances
    if fi is None:
        raise OryxServingException(404, "no importances in model")
    out = {}
    for i, name in enumerate(model.schema.feature_names):
        if model.schema.is_active(i) and not model.schema.is_target(i):
            out[name] = float(fi[model.schema.feature_to_predictor_index(i)])
    return out


@resource("GET", "/feature/importance/{index}")
def feature_importance_one(ctx: ServingContext, req: Request):
    model = get_ready_model(ctx)
    fi = model.forest.feature_importances
    if fi is None:
        raise OryxServingException(404, "no importances in model")
    try:
        return float(fi[int(req.params["index"])])
    except (ValueError, IndexError):
        raise OryxServingException(400, "bad predictor index")


@resource("POST", "/train")
def train(ctx: ServingContext, req: Request) -> Response:
    """Queue new labeled examples to the input topic (classreg/Train.java)."""
    check_not_read_only(ctx)
    for line in req.text().splitlines():
        if line.strip():
            send_input(ctx, line.strip())
    return Response(204)


# ---------------------------------------------------------------------------
# Console (rdf/Console.java:28)
# ---------------------------------------------------------------------------

from oryx_tpu.serving.console import ConsoleForm, console_response, render_console  # noqa: E402

_CONSOLE_HTML = render_console(
    "Oryx random decision forest serving console",
    [
        ConsoleForm("Predict", "GET", "/predict/{datum}",
                    note="CSV example; blank target field"),
        ConsoleForm("Classification distribution", "GET",
                    "/classificationDistribution/{datum}"),
        ConsoleForm("Feature importance", "GET", "/feature/importance"),
        ConsoleForm("Train", "POST", "/train", body=True,
                    note="one labeled CSV example per line"),
        ConsoleForm("Ready?", "GET", "/ready"),
    ],
)


@resource("GET", "/")
@resource("GET", "/index.html")
def console(ctx: ServingContext, req: Request):
    return console_response(_CONSOLE_HTML)
