"""Shared serving-resource helpers for all apps.

Rebuild of AbstractOryxResource (app/oryx-app-serving/.../serving/
AbstractOryxResource.java:54-182): the model-readiness gate against
oryx.serving.min-model-load-fraction (503 until loaded), input-topic
send helper, read-only guard, and compressed/multipart ingest body
decoding (Ingest accepts raw text, gzip, zip, and multipart forms).
"""

from __future__ import annotations

import gzip
import io
import zipfile

from oryx_tpu.serving.web import OryxServingException, Request, ServingContext


def get_ready_model(ctx: ServingContext):
    """The serving model, or 503 while insufficiently loaded
    (AbstractOryxResource.getServingModel:75-97)."""
    manager = ctx.model_manager
    model = manager.get_model() if manager is not None else None
    if model is not None:
        min_fraction = ctx.config.get_float("oryx.serving.min-model-load-fraction")
        if model.get_fraction_loaded() >= min_fraction:
            return model
    raise OryxServingException(503, "model not available yet")


def check_not_read_only(ctx: ServingContext) -> None:
    if ctx.model_manager is not None and ctx.model_manager.is_read_only():
        raise OryxServingException(403, "read-only instance")


def send_input(ctx: ServingContext, line: str) -> None:
    """Write one event line to the input topic
    (AbstractOryxResource.sendInput:65-69; keyed by line hash)."""
    if ctx.input_producer is None:
        raise OryxServingException(503, "no input topic configured")
    ctx.input_producer.send(format(abs(hash(line)) & 0xFFFFFFFF, "x"), line)


def read_ingest_lines(req: Request) -> list[str]:
    """Decode an ingest body: plain text, gzip, zip archive, or a
    multipart form of any of those (AbstractOryxResource.java:99-132)."""
    content_type = req.headers.get("Content-Type", "").lower()
    bodies: list[bytes] = []
    if content_type.startswith("multipart/"):
        bodies = _parse_multipart(req)
    else:
        body = req.body
        # Content-Encoding: gzip is already undone by the HTTP layer; this
        # handles a gzip content-TYPE (a .csv.gz file POSTed directly)
        if content_type.endswith("gzip"):
            body = gzip.decompress(body)
        elif content_type.endswith("zip"):
            bodies.extend(_unzip(body))
            body = b""
        if body:
            bodies.append(body)
    lines: list[str] = []
    for b in bodies:
        for line in b.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if line:
                lines.append(line)
    if not lines and not bodies:
        raise OryxServingException(400, "no content")
    return lines


def _unzip(body: bytes) -> list[bytes]:
    out = []
    with zipfile.ZipFile(io.BytesIO(body)) as zf:
        for name in zf.namelist():
            out.append(zf.read(name))
    return out


def _parse_multipart(req: Request) -> list[bytes]:
    import email
    import email.policy

    content_type = req.headers.get("Content-Type", "")
    msg = email.message_from_bytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + req.body,
        policy=email.policy.HTTP,
    )
    out: list[bytes] = []
    for part in msg.iter_parts():
        payload = part.get_payload(decode=True)
        if payload is None:
            continue
        filename = (part.get_filename() or "").lower()
        ctype = (part.get_content_type() or "").lower()
        if filename.endswith(".gz") or "gzip" in ctype:
            payload = gzip.decompress(payload)
            out.append(payload)
        elif filename.endswith(".zip") or "zip" in ctype:
            out.extend(_unzip(payload))
        else:
            out.append(payload)
    if not out:
        raise OryxServingException(400, "no multipart content")
    return out
