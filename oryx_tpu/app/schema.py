"""Input schema: feature naming/typing declared in config.

Rebuild of InputSchema (app/oryx-app-common/.../schema/InputSchema.java:
37-282) and CategoricalValueEncodings (.../CategoricalValueEncodings.java:
33-100): feature names (or a count), id/ignored feature sets, numeric vs
categorical typing (declare one set, the complement gets the other type),
target feature, and the feature-index <-> predictor-index maps that skip
id/ignored columns.
"""

from __future__ import annotations

from typing import Mapping, Sequence

from oryx_tpu.common.config import Config, ConfigError


class InputSchema:
    def __init__(self, config: Config) -> None:
        names = config.get_strings("oryx.input-schema.feature-names")
        if not names:
            num = config.get_int("oryx.input-schema.num-features")
            if num <= 0:
                raise ConfigError("input-schema requires feature-names or num-features")
            names = [str(i) for i in range(num)]
        if len(set(names)) != len(names):
            raise ConfigError(f"duplicate feature names: {names}")
        self.feature_names: list[str] = names

        id_f = set(config.get_optional_strings("oryx.input-schema.id-features") or [])
        ignored = set(config.get_optional_strings("oryx.input-schema.ignored-features") or [])
        self._id_features = id_f
        self._ignored = ignored

        numeric = config.get_optional_strings("oryx.input-schema.numeric-features")
        categorical = config.get_optional_strings("oryx.input-schema.categorical-features")
        if (numeric is None) == (categorical is None):
            raise ConfigError("set exactly one of numeric-features / categorical-features")
        active = [n for n in names if n not in id_f and n not in ignored]
        # type declarations must name ACTIVE features only: the reference
        # rejects declared sets that aren't subsets of the actives
        # (InputSchema.java:89-101). Silently intersecting instead would
        # hide typos — a misspelled feature name drops the declaration and
        # flips the feature to the complementary type without a word.
        declared = set(numeric) if numeric is not None else set(categorical)
        extra = declared - set(active)
        if extra:
            which = "numeric" if numeric is not None else "categorical"
            raise ConfigError(
                f"{which}-features {sorted(extra)} are not active features "
                f"(active: {sorted(active)})"
            )
        if numeric is not None:
            self._numeric = declared
            self._categorical = {n for n in active if n not in self._numeric}
        else:
            self._categorical = declared
            self._numeric = {n for n in active if n not in self._categorical}

        self.target_feature = config.get_optional_string("oryx.input-schema.target-feature")
        if self.target_feature is not None and self.target_feature not in active:
            raise ConfigError(f"target feature {self.target_feature} is not active")

        # feature index <-> predictor index (predictors = active non-target
        # plus target? reference: predictors are all active features incl.
        # target; the target has a predictor index too, InputSchema.java:98-119)
        self._feature_to_predictor: dict[int, int] = {}
        self._predictor_to_feature: dict[int, int] = {}
        p = 0
        for i, n in enumerate(names):
            if n in id_f or n in ignored:
                continue
            self._feature_to_predictor[i] = p
            self._predictor_to_feature[p] = i
            p += 1
        self.num_predictors = p

    # -- queries (InputSchema.java API surface) -----------------------------

    @property
    def num_features(self) -> int:
        return len(self.feature_names)

    def is_id(self, name_or_index: str | int) -> bool:
        return self._name(name_or_index) in self._id_features

    def is_active(self, name_or_index: str | int) -> bool:
        n = self._name(name_or_index)
        return n not in self._id_features and n not in self._ignored

    def is_numeric(self, name_or_index: str | int) -> bool:
        return self._name(name_or_index) in self._numeric

    def is_categorical(self, name_or_index: str | int) -> bool:
        return self._name(name_or_index) in self._categorical

    def is_target(self, name_or_index: str | int) -> bool:
        return self.target_feature is not None and self._name(name_or_index) == self.target_feature

    def has_target(self) -> bool:
        return self.target_feature is not None

    @property
    def target_feature_index(self) -> int | None:
        if self.target_feature is None:
            return None
        return self.feature_names.index(self.target_feature)

    def feature_to_predictor_index(self, feature_index: int) -> int:
        return self._feature_to_predictor[feature_index]

    def predictor_to_feature_index(self, predictor_index: int) -> int:
        return self._predictor_to_feature[predictor_index]

    def _name(self, name_or_index: str | int) -> str:
        if isinstance(name_or_index, int):
            return self.feature_names[name_or_index]
        return name_or_index

    def __repr__(self) -> str:  # pragma: no cover
        return f"InputSchema({self.feature_names})"


class CategoricalValueEncodings:
    """Per-categorical-feature string<->int bimaps
    (CategoricalValueEncodings.java:33-100). Keyed by feature index."""

    def __init__(self, distinct_values: Mapping[int, Sequence[str]]) -> None:
        self._value_to_index: dict[int, dict[str, int]] = {}
        self._index_to_value: dict[int, dict[int, str]] = {}
        for feat, values in distinct_values.items():
            v2i = {v: i for i, v in enumerate(values)}
            self._value_to_index[feat] = v2i
            self._index_to_value[feat] = {i: v for v, i in v2i.items()}

    def index_for(self, feature: int, value: str) -> int:
        return self._value_to_index[feature][value]

    def value_for(self, feature: int, index: int) -> str:
        return self._index_to_value[feature][index]

    def value_to_index_map(self, feature: int) -> dict[str, int]:
        return dict(self._value_to_index.get(feature, {}))

    def index_to_value_map(self, feature: int) -> dict[int, str]:
        return dict(self._index_to_value.get(feature, {}))

    def category_counts(self) -> dict[int, int]:
        return {f: len(m) for f, m in self._value_to_index.items()}

    def category_count(self, feature: int) -> int:
        return len(self._value_to_index[feature])
