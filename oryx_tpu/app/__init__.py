"""App tier: the packaged end-to-end ML applications (ALS, k-means, RDF)
plus shared schema/PMML glue — rebuild of app/oryx-app-common,
app/oryx-app-mllib, app/oryx-app and app/oryx-app-serving
(SURVEY.md §2.7-2.10).
"""
