"""K-means speed layer: centroid drift from new points.

Rebuild of KMeansSpeedModel (app/oryx-app/.../speed/kmeans/
KMeansSpeedModel.java:31-63) and KMeansSpeedModelManager (.../
KMeansSpeedModelManager.java:47-127): assign each new point to its
nearest cluster, reduce per cluster to (sum, count), move each centroid
by weighted running mean, emit ``[clusterID, [center], count]`` UP
messages (KMeansSpeedModelManager.java:85-125).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterable, Iterator

import numpy as np

from oryx_tpu.api.speed import SpeedModel, SpeedModelManager
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.kmeans import common as km
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import join_json, parse_line, read_json

log = logging.getLogger(__name__)


class KMeansSpeedModel(SpeedModel):
    """In-memory clusters; always fully loaded once a model arrives."""

    def __init__(self, clusters: list[km.ClusterInfo]) -> None:
        self._lock = threading.Lock()
        self._clusters = {c.id: c for c in clusters}

    def get_cluster(self, cluster_id: int) -> km.ClusterInfo | None:
        with self._lock:
            return self._clusters.get(cluster_id)

    def clusters(self) -> list[km.ClusterInfo]:
        with self._lock:
            return list(self._clusters.values())

    def set_cluster(self, cluster: km.ClusterInfo) -> None:
        with self._lock:
            self._clusters[cluster.id] = cluster

    def update(self, cluster_id: int, point_sum: np.ndarray, count: int) -> None:
        with self._lock:
            c = self._clusters.get(cluster_id)
            if c is not None:
                c.update(point_sum, count)

    def get_fraction_loaded(self) -> float:
        return 1.0


class KMeansSpeedModelManager(SpeedModelManager):
    def __init__(self, config: Config) -> None:
        self.schema = InputSchema(config)
        km.check_numeric_only(self.schema)
        self.model: KMeansSpeedModel | None = None

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for kmsg in update_iterator:
            key, message = kmsg.key, kmsg.message
            if key == "UP":
                if self.model is None:
                    continue
                cluster_id, center, count = read_json(message)
                self.model.set_cluster(
                    km.ClusterInfo(int(cluster_id), np.asarray(center, np.float64), int(count))
                )
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                self.model = KMeansSpeedModel(km.pmml_to_clusters(pmml))
            else:
                raise ValueError(f"bad key {key}")

    def build_updates(self, new_data: Iterable[KeyMessage]) -> Iterable[str]:
        model = self.model
        if model is None:
            return []
        clusters = model.clusters()
        if not clusters:
            return []
        dim = clusters[0].center.shape
        points: list[np.ndarray] = []
        for rec in new_data:
            # raw client input (POST /add): a malformed line must not abort
            # the whole micro-batch
            try:
                point = km.features_from_tokens(parse_line(rec.message), self.schema)
                if point.shape != dim:
                    raise ValueError(f"bad dimension {point.shape}")
            except (ValueError, IndexError, KeyError):
                log.warning("skipping bad input line: %r", rec.message[:200])
                continue
            points.append(point)
        if not points:
            return []
        # one batched nearest-cluster assignment + bincount reduction for
        # the whole micro-batch (this is the layer's hot path; the
        # per-point closest_cluster walk was VERDICT r3 weak #7)
        from oryx_tpu.ops.kmeans import assign_clusters

        pts = np.stack(points)
        centers = np.stack([c.center for c in clusters])
        assign, _ = assign_clusters(pts, centers)  # float64 end to end
        out = []
        for slot in np.unique(assign):
            rows = assign == slot
            cid = clusters[int(slot)].id
            model.update(cid, pts[rows].sum(axis=0), int(rows.sum()))
            updated = model.get_cluster(cid)
            out.append(join_json([cid, [float(v) for v in updated.center], updated.count]))
        return out

    def close(self) -> None:
        pass
