"""K-means batch trainer.

Rebuild of KMeansUpdate (app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:
68-234): numeric-only schema check, `runs` independent restarts per
candidate with the best cost winning (MLlib's `runs` parameter,
KMeansUpdate.java:70-81), ClusteringModel PMML with cluster sizes, and
an evaluation strategy chosen by config (SSE / DAVIES_BOULDIN / DUNN /
SILHOUETTE, KMeansUpdate.evaluate:139-178 — metrics where lower is
better are negated so MLUpdate can always maximize).
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Iterable, Sequence
from xml.etree.ElementTree import Element

import numpy as np

from oryx_tpu.app.kmeans import common as km
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_line
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.ops import kmeans as km_ops

log = logging.getLogger(__name__)

EVAL_STRATEGIES = ("SSE", "DAVIES_BOULDIN", "DUNN", "SILHOUETTE")


class KMeansUpdate(MLUpdate):
    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.iterations = config.get_int("oryx.kmeans.iterations")
        self.init_strategy = config.get_string("oryx.kmeans.initialization-strategy")
        self.runs = config.get_int("oryx.kmeans.runs")
        self.eval_strategy = config.get_string("oryx.kmeans.evaluation-strategy").upper()
        self.minibatch_size = config.get_optional_int("oryx.ml.kmeans.minibatch-size")
        if self.eval_strategy not in EVAL_STRATEGIES:
            raise ValueError(f"unknown evaluation-strategy {self.eval_strategy}")
        if self.init_strategy not in ("k-means||", "random"):
            raise ValueError(f"unknown initialization-strategy {self.init_strategy}")
        if self.minibatch_size is not None and self.minibatch_size <= 0:
            raise ValueError("oryx.ml.kmeans.minibatch-size must be positive")
        self.schema = InputSchema(config)
        km.check_numeric_only(self.schema)
        self._config = config

    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        return [hp.from_config(self._config, "oryx.kmeans.hyperparams.k")]

    def _points(self, data: Iterable[KeyMessage]) -> np.ndarray:
        rows = [
            km.features_from_tokens(parse_line(rec.message), self.schema) for rec in data
        ]
        if not rows:
            return np.zeros((0, self.schema.num_predictors))
        return np.stack(rows)

    def build_model(
        self,
        train_data: list[KeyMessage],
        hyper_parameters: Sequence,
        candidate_path: Path,
    ) -> Element:
        k = int(hyper_parameters[0])
        if k <= 1:
            raise ValueError("k must be > 1")
        points = self._points(train_data)
        if len(points) == 0:
            raise ValueError("no points to cluster")
        from oryx_tpu.parallel.mesh import mesh_from_config

        mesh = mesh_from_config(self._config)
        # warm-start: run 0 seeds Lloyd from the champion's centers (the
        # remaining runs stay independent restarts, so a drifted previous
        # model can't trap every run in its basin); train_kmeans falls
        # back to cold init when k or the feature dim changed
        warm_centers = self._warm_start_centers()
        best = None
        for run in range(max(1, self.runs)):
            centers, counts, cost = km_ops.train_kmeans(
                points,
                k,
                iterations=self.iterations,
                init=self.init_strategy,
                mesh=mesh,
                initial_centers=warm_centers if run == 0 else None,
                minibatch_size=self.minibatch_size,
            )
            log.info("k-means run %d: cost=%.4f", run, cost)
            if best is None or cost < best[2]:
                best = (centers, counts, cost)
        centers, counts, _ = best
        clusters = [
            km.ClusterInfo(i, centers[i].astype(np.float64), int(counts[i]))
            for i in range(len(centers))
        ]
        return km.clusters_to_pmml(clusters, self.schema)

    def _warm_start_centers(self) -> np.ndarray | None:
        """Champion centers from MLUpdate.load_previous_model's PMML, or
        None for a cold start."""
        if self.previous_model is None:
            return None
        try:
            clusters = km.pmml_to_clusters(self.previous_model)
            centers = np.stack([c.center for c in clusters]).astype(np.float32)
        except Exception:
            log.warning("unreadable previous centers; cold-starting", exc_info=True)
            return None
        log.info(
            "warm-start from generation %s: seeding %d centers",
            self.previous_generation_id, len(centers),
        )
        return centers

    def evaluate(
        self,
        model: Element,
        model_parent_path: Path,
        test_data: list[KeyMessage],
        train_data: list[KeyMessage],
    ) -> float:
        clusters = km.pmml_to_clusters(model)
        points = self._points(test_data if test_data else train_data)
        if len(points) == 0:
            return float("nan")
        centers = np.stack([c.center for c in clusters])
        if self.eval_strategy == "SSE":
            return -km_ops.sum_squared_error(points, centers)  # lower better
        if self.eval_strategy == "DAVIES_BOULDIN":
            return -km_ops.davies_bouldin_index(points, centers)  # lower better
        if self.eval_strategy == "DUNN":
            return km_ops.dunn_index(points, centers)
        return km_ops.silhouette_coefficient(points, centers)
