"""K-means clustering application: batch trainer, speed-layer centroid
drift, serving model + REST endpoints (reference kmeans components in
SURVEY.md §2.7-2.10).
"""
