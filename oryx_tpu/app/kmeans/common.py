"""Shared k-means app logic.

Rebuild of app/oryx-app-common kmeans/: ClusterInfo (id/center/count with
running-mean update, ClusterInfo.java:26-71), nearest-cluster assignment
(KMeansUtils.java), feature parsing against the InputSchema, and
ClusteringModel PMML read/write (KMeansPMMLUtils.java).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Iterable, Sequence
from xml.etree.ElementTree import Element

import numpy as np

from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.common import pmml as pmml_io


@dataclass
class ClusterInfo:
    """One cluster: stable id, center, and member count; update() folds a
    batch of points into the center as a weighted running mean
    (ClusterInfo.update:52)."""

    id: int
    center: np.ndarray
    count: int

    def update(self, point_sum: np.ndarray, point_count: int) -> None:
        total = self.count + point_count
        if total <= 0:
            return
        self.center = (self.center * self.count + np.asarray(point_sum, dtype=np.float64)) / total
        self.count = total


def distance(a: np.ndarray, b: np.ndarray) -> float:
    """Euclidean (EuclideanDistanceFn.java)."""
    return float(np.linalg.norm(np.asarray(a, np.float64) - np.asarray(b, np.float64)))


def closest_cluster(clusters: Sequence[ClusterInfo], point: np.ndarray) -> tuple[ClusterInfo, float]:
    """(nearest cluster, distance) (KMeansUtils.closestCluster)."""
    if not clusters:
        raise ValueError("no clusters")
    centers = np.stack([c.center for c in clusters]).astype(np.float64)
    p = np.asarray(point, np.float64)
    d = np.linalg.norm(centers - p[None, :], axis=1)
    i = int(np.argmin(d))
    return clusters[i], float(d[i])


def features_from_tokens(tokens: Sequence[str], schema: InputSchema) -> np.ndarray:
    """Active numeric features from an input line (KMeansUtils
    .featuresFromTokens); schema must be all-numeric for k-means
    (KMeansUpdate.java:82-86 check)."""
    out = []
    for i, tok in enumerate(tokens[: schema.num_features]):
        if schema.is_active(i):
            out.append(float(tok))
    return np.asarray(out, dtype=np.float64)


def check_numeric_only(schema: InputSchema) -> None:
    for i in range(schema.num_features):
        if schema.is_active(i) and not schema.is_numeric(i):
            raise ValueError("k-means requires an all-numeric input schema")


# -- PMML ClusteringModel ----------------------------------------------------


def clusters_to_pmml(clusters: Sequence[ClusterInfo], schema: InputSchema) -> Element:
    """ClusteringModel with per-cluster size and center Array
    (KMeansPMMLUtils.clusteringModelToPMML / KMeansUpdate.kMeansModelToPMML:
    184-221)."""
    root = pmml_io.build_skeleton_pmml()
    app_pmml.build_data_dictionary(root, schema)
    # no modelName: the reference constructs ClusteringModel(<function>,
    # <modelClass>, <n>, ...) without one (KMeansUpdate.java:214-221)
    model = pmml_io.sub(
        root,
        "ClusteringModel",
        {
            "functionName": "clustering",
            "modelClass": "centerBased",
            "numberOfClusters": str(len(clusters)),
        },
    )
    app_pmml.build_mining_schema(model, schema)
    cm = pmml_io.sub(model, "ComparisonMeasure", {"kind": "distance"})
    pmml_io.sub(cm, "squaredEuclidean")
    for i, name in enumerate(schema.feature_names):
        if schema.is_active(i):
            pmml_io.sub(
                model, "ClusteringField", {"field": name, "centerField": "true"}
            )
    for c in clusters:
        cl = pmml_io.sub(model, "Cluster", {"id": str(c.id), "size": str(int(c.count))})
        arr = pmml_io.sub(
            cl, "Array", {"n": str(len(c.center)), "type": "real"}
        )
        arr.text = " ".join(repr(float(v)) for v in c.center)
    return root


def pmml_to_clusters(root: Element) -> list[ClusterInfo]:
    """Inverse of clusters_to_pmml (KMeansPMMLUtils.read)."""
    model = pmml_io.find(root, "ClusteringModel")
    if model is None:
        raise ValueError("no ClusteringModel in PMML")
    out: list[ClusterInfo] = []
    for cl in pmml_io.findall(model, "Cluster"):
        arr = pmml_io.find(cl, "Array")
        center = np.asarray([float(t) for t in (arr.text or "").split()], dtype=np.float64)
        out.append(ClusterInfo(int(cl.get("id")), center, int(cl.get("size", "0"))))
    return out
