"""K-means serving: model, manager, and endpoints.

Rebuild of KMeansServingModel (app/oryx-app-serving/.../kmeans/model/
KMeansServingModel.java:34-83) + manager, and the clustering endpoints:
GET /assign (clustering/Assign.java:52), POST /add (clustering/Add.java:
43), GET /distanceToNearest (kmeans/DistanceToNearest.java:40).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator

import numpy as np

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.app import pmml as app_pmml
from oryx_tpu.app.kmeans import common as km
from oryx_tpu.app.schema import InputSchema
from oryx_tpu.app.serving_common import check_not_read_only, get_ready_model, send_input
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config
from oryx_tpu.common.text import parse_line, read_json
from oryx_tpu.serving.web import OryxServingException, Request, Response, ServingContext, resource

log = logging.getLogger(__name__)


class KMeansServingModel(ServingModel):
    def __init__(self, clusters: list[km.ClusterInfo], schema: InputSchema) -> None:
        self._lock = threading.Lock()
        self._clusters = {c.id: c for c in clusters}
        self.schema = schema

    def get_fraction_loaded(self) -> float:
        return 1.0  # loads all at once (KMeansServingModel is whole-model)

    def clusters(self) -> list[km.ClusterInfo]:
        with self._lock:
            return list(self._clusters.values())

    def closest_cluster(self, point: np.ndarray) -> tuple[km.ClusterInfo, float]:
        return km.closest_cluster(self.clusters(), point)

    def update(self, cluster_id: int, center: np.ndarray, count: int) -> None:
        with self._lock:
            self._clusters[cluster_id] = km.ClusterInfo(cluster_id, center, count)


class KMeansServingModelManager(AbstractServingModelManager):
    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.schema = InputSchema(config)
        km.check_numeric_only(self.schema)
        self.model: KMeansServingModel | None = None

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for kmsg in update_iterator:
            key, message = kmsg.key, kmsg.message
            if key == "UP":
                if self.model is None:
                    continue
                cluster_id, center, count = read_json(message)
                self.model.update(int(cluster_id), np.asarray(center, np.float64), int(count))
            elif key in ("MODEL", "MODEL-REF"):
                pmml = app_pmml.read_pmml_from_update_message(key, message)
                if pmml is None:
                    log.warning("dropped unreadable model update")
                    continue
                self.model = KMeansServingModel(km.pmml_to_clusters(pmml), self.schema)
            else:
                raise ValueError(f"bad key {key}")

    def get_model(self) -> KMeansServingModel | None:
        return self.model


def _point_from_path(model: KMeansServingModel, datum: str) -> np.ndarray:
    try:
        point = km.features_from_tokens(parse_line(datum), model.schema)
    except (ValueError, IndexError):
        raise OryxServingException(400, f"bad input {datum!r}")
    if len(point) != model.schema.num_predictors:
        raise OryxServingException(
            400, f"expected {model.schema.num_predictors} features, got {len(point)}"
        )
    return point


@resource("GET", "/assign/{datum}")
def assign(ctx: ServingContext, req: Request):
    """Nearest cluster id for one datum (clustering/Assign.java)."""
    model = get_ready_model(ctx)
    cluster, _ = model.closest_cluster(_point_from_path(model, req.params["datum"]))
    return str(cluster.id)


@resource("POST", "/assign")
def assign_many(ctx: ServingContext, req: Request):
    """One cluster id per body line."""
    model = get_ready_model(ctx)
    out = []
    for line in req.text().splitlines():
        if line.strip():
            cluster, _ = model.closest_cluster(_point_from_path(model, line.strip()))
            out.append(str(cluster.id))
    return out


@resource("GET", "/distanceToNearest/{datum}")
def distance_to_nearest(ctx: ServingContext, req: Request):
    """kmeans/DistanceToNearest.java."""
    model = get_ready_model(ctx)
    _, dist = model.closest_cluster(_point_from_path(model, req.params["datum"]))
    return dist


@resource("POST", "/add")
def add(ctx: ServingContext, req: Request) -> Response:
    """Queue new data points to the input topic (clustering/Add.java)."""
    check_not_read_only(ctx)
    for line in req.text().splitlines():
        if line.strip():
            send_input(ctx, line.strip())
    return Response(204)


# ---------------------------------------------------------------------------
# Console (kmeans/Console.java:28)
# ---------------------------------------------------------------------------

from oryx_tpu.serving.console import ConsoleForm, console_response, render_console  # noqa: E402

_CONSOLE_HTML = render_console(
    "Oryx k-means serving console",
    [
        ConsoleForm("Assign to cluster", "GET", "/assign/{datum}",
                    note="comma-separated numeric point"),
        ConsoleForm("Distance to nearest", "GET", "/distanceToNearest/{datum}"),
        ConsoleForm("Add points", "POST", "/add", body=True,
                    note="one CSV point per line"),
        ConsoleForm("Ready?", "GET", "/ready"),
    ],
)


@resource("GET", "/")
@resource("GET", "/index.html")
def console(ctx: ServingContext, req: Request):
    return console_response(_CONSOLE_HTML)
