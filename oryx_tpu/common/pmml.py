"""PMML 4.2-compatible model artifact read/write.

Rebuild of the reference's PMMLUtils (framework/oryx-common/src/main/java/
com/cloudera/oryx/common/pmml/PMMLUtils.java:41-140): build a skeleton PMML
document, read/write files, and round-trip to a string — PMML is the model
interchange format flowing over the update topic as "MODEL" messages or
referenced from "MODEL-REF" paths. Implemented on xml.etree (no external
JAXB-equivalent needed); app-level helpers for extensions, mining schemas,
and model-type-specific content live in oryx_tpu.app.pmml.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from pathlib import Path

PMML_NAMESPACE = "http://www.dmg.org/PMML-4_2"
PMML_VERSION = "4.2.1"

ET.register_namespace("", PMML_NAMESPACE)

__all__ = [
    "PMML_NAMESPACE",
    "PMML_VERSION",
    "q",
    "build_skeleton_pmml",
    "read_pmml",
    "write_pmml",
    "to_string",
    "from_string",
    "sub",
    "find",
    "findall",
]


def q(tag: str) -> str:
    """Qualified tag name in the PMML namespace."""
    return f"{{{PMML_NAMESPACE}}}{tag}"


def build_skeleton_pmml(app_name: str = "oryx_tpu") -> ET.Element:
    """New PMML root with Header/Application/Timestamp.

    Mirrors PMMLUtils.buildSkeletonPMML (PMMLUtils.java:50-66).
    """
    import datetime

    root = ET.Element(q("PMML"), {"version": PMML_VERSION})
    header = ET.SubElement(root, q("Header"))
    from oryx_tpu import __version__

    ET.SubElement(header, q("Application"), {"name": app_name, "version": __version__})
    ts = ET.SubElement(header, q("Timestamp"))
    ts.text = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    return root


def sub(parent: ET.Element, tag: str, attrib: dict | None = None, text: str | None = None) -> ET.Element:
    e = ET.SubElement(parent, q(tag), attrib or {})
    if text is not None:
        e.text = text
    return e


def find(root: ET.Element, path: str) -> ET.Element | None:
    """Find by slash-separated local tag names (namespace applied)."""
    return root.find("/".join(q(p) for p in path.split("/")))


def findall(root: ET.Element, path: str) -> list[ET.Element]:
    return root.findall("/".join(q(p) for p in path.split("/")))


def local_name(elem: ET.Element) -> str:
    tag = elem.tag
    return tag.rsplit("}", 1)[-1] if "}" in tag else tag


def to_string(root: ET.Element) -> str:
    return ET.tostring(root, encoding="unicode")


def from_string(text: str) -> ET.Element:
    return ET.fromstring(text)


def write_pmml(root: ET.Element, path: str | Path) -> None:
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    ET.ElementTree(root).write(str(path), encoding="utf-8", xml_declaration=True)


def read_pmml(path: str | Path) -> ET.Element:
    return ET.parse(str(path)).getroot()
