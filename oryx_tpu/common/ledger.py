"""Runtime resource ledger: live-resource accounting via weakrefs.

The dynamic half of the static lifecycle pass (oryx_tpu/analysis/
lifecycle.py, ORX501-ORX506): every long-lived resource the framework
acquires — supervised threads, bus consumers, shm rings, device-resident
fold-in sessions — registers itself here at construction. The ledger
holds only weak references, so registration never extends a lifetime;
a resource leaves the ledger either when it is garbage-collected or
when its liveness probe reports it released (closed flag set, thread
finished).

Consumers of the ledger:

- ``/metrics``: :func:`refresh` publishes ``resources.<kind>.live``
  gauges into the process metrics registry, so operators can watch a
  replica's thread/consumer/ring population stay flat across weeks of
  rotations — the production-facing leak alarm.
- tests: the autouse ``_resource_ledger`` fixture (tests/conftest.py)
  snapshots the ledger around every chaos/fleet/pipeline test and
  asserts the suite's teardown released everything it acquired — the
  dynamic oracle that validates the static pass, exactly as the
  lock-order watchdog validates ORX201.

Registration is on by default and costs one weakref + one dict insert
per resource acquisition (never on a per-event path); set
``ORYX_RESOURCE_LEDGER=0`` to compile it out at import time.

Probes take the object and return True while the resource is still
held (``live(obj) -> bool``). They must not capture the object in a
closure — the ledger passes the dereferenced weakref — or the ledger
itself would keep the resource alive. A resource registered without a
probe counts as live for as long as it is strongly referenced; that is
the right semantic for GC-released resources like fold-in sessions.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Callable

__all__ = ["ResourceLedger", "enabled", "ledger", "register"]


def enabled() -> bool:
    return os.environ.get("ORYX_RESOURCE_LEDGER", "1") != "0"


class ResourceLedger:
    """Weakref ledger of acquired-but-not-yet-released resources."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        # id -> (kind, weakref, probe|None)
        self._entries: dict[int, tuple[str, weakref.ref, Callable | None]] = {}

    def register(self, kind: str, obj, live: Callable | None = None) -> None:
        """Track ``obj`` under ``kind``. ``live(obj)`` (optional) reports
        whether the resource is still held; without it the resource is
        live while strongly referenced."""
        with self._lock:
            key = self._next
            self._next += 1
            try:
                ref = weakref.ref(obj, lambda _r, k=key: self._drop(k))
            except TypeError:
                return  # objects without weakref support are not tracked
            self._entries[key] = (kind, ref, live)

    def _drop(self, key: int) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def counts(self) -> dict[str, int]:
        """Live resources per kind. Entries whose probe reports released
        are pruned as a side effect, so repeated calls stay cheap."""
        with self._lock:
            entries = list(self._entries.items())
        out: dict[str, int] = {}
        dead: list[int] = []
        for key, (kind, ref, live) in entries:
            obj = ref()
            if obj is None:
                dead.append(key)
                continue
            try:
                if live is not None and not live(obj):
                    dead.append(key)
                    continue
            except Exception:
                dead.append(key)  # probe raised: the object is torn down
                continue
            out[kind] = out.get(kind, 0) + 1
        if dead:
            with self._lock:
                for key in dead:
                    self._entries.pop(key, None)
        return out

    def live(self, kind: str | None = None) -> int:
        c = self.counts()
        return sum(c.values()) if kind is None else c.get(kind, 0)

    def snapshot(self) -> dict[str, int]:
        return self.counts()

    def refresh(self) -> dict[str, int]:
        """Publish ``resources.<kind>.live`` gauges into the process
        metrics registry (and zero gauges for kinds that emptied since
        the last refresh). Returns the counts."""
        from oryx_tpu.common import metrics

        counts = self.counts()
        known = getattr(self, "_gauge_kinds", set())
        for kind in known - set(counts):
            metrics.registry.gauge(f"resources.{kind}.live").set(0)
        for kind, n in counts.items():
            metrics.registry.gauge(f"resources.{kind}.live").set(n)
        self._gauge_kinds = known | set(counts)
        return counts


ledger = ResourceLedger()
"""Process-global ledger (each layer is its own process)."""


def register(kind: str, obj, live: Callable | None = None) -> None:
    """Module-level convenience: no-op when ORYX_RESOURCE_LEDGER=0."""
    if enabled():
        ledger.register(kind, obj, live)
