"""Concurrency and class-loading helpers.

Rebuilds, from the reference's framework/oryx-common:
- AutoReadWriteLock (lang/AutoReadWriteLock.java): a reader-writer lock with
  context-manager acquire, guarding all in-memory model state.
- ExecUtils (lang/ExecUtils.java:32-121): bounded-parallelism helpers used
  for parallel hyperparameter candidates and partition scans.
- ClassUtils (lang/ClassUtils.java:24-130): instantiate user classes named
  in config — here by Python import path — trying a (Config) constructor
  first, then no-arg (reference BatchLayer.java:153-184 usage).
- JVMUtils ordered shutdown (lang/JVMUtils.java:26-60) via atexit.
"""

from __future__ import annotations

import atexit
import importlib
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, TypeVar

T = TypeVar("T")

__all__ = [
    "ReadWriteLock",
    "do_in_parallel",
    "collect_in_parallel",
    "load_instance_of",
    "close_at_shutdown",
]


class ReadWriteLock:
    """Writer-preference reader-writer lock with context managers.

    with lock.read():  ... shared ...
    with lock.write(): ... exclusive ...
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    class _Guard:
        def __init__(self, acquire: Callable[[], None], release: Callable[[], None]):
            self._acquire, self._release = acquire, release

        def __enter__(self):
            self._acquire()
            return self

        def __exit__(self, *exc):
            self._release()
            return False

    def read(self) -> "_Guard":
        return self._Guard(self._acquire_read, self._release_read)

    def write(self) -> "_Guard":
        return self._Guard(self._acquire_write, self._release_write)

    def _acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def _release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def _acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def _release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


def do_in_parallel(num_tasks: int, fn: Callable[[int], Any], parallelism: int = 1) -> None:
    """Run fn(0..num_tasks-1), at most `parallelism` at a time."""
    collect_in_parallel(num_tasks, fn, parallelism)


def collect_in_parallel(
    num_tasks: int, fn: Callable[[int], T], parallelism: int = 1
) -> list[T]:
    """Run fn(i) for i in range(num_tasks) with bounded parallelism and
    return results in index order. First raised exception propagates."""
    parallelism = max(1, min(parallelism, num_tasks)) if num_tasks else 1
    if parallelism == 1:
        return [fn(i) for i in range(num_tasks)]
    with ThreadPoolExecutor(max_workers=parallelism) as pool:
        return list(pool.map(fn, range(num_tasks)))


def load_class(name: str) -> type:
    """Resolve 'pkg.mod:Class' or 'pkg.mod.Class' to a class object."""
    if ":" in name:
        mod_name, cls_name = name.split(":", 1)
    else:
        mod_name, _, cls_name = name.rpartition(".")
        if not mod_name:
            raise ValueError(f"cannot resolve class name {name!r}")
    mod = importlib.import_module(mod_name)
    try:
        obj: Any = mod
        for part in cls_name.split("."):
            obj = getattr(obj, part)
        return obj
    except AttributeError as e:
        raise ImportError(f"no class {cls_name!r} in module {mod_name!r}") from e


def load_instance_of(name: str, *args: Any) -> Any:
    """Instantiate a config-named class, preferring ctor(*args) when the
    signature accepts it, else no-arg (ClassUtils.loadInstanceOf semantics,
    reference ClassUtils.java:59-95). Signature is checked up front so a
    TypeError raised *inside* a matching constructor propagates instead of
    being masked by a silent no-arg retry."""
    import inspect

    cls = load_class(name)
    if args:
        try:
            inspect.signature(cls).bind(*args)
        except TypeError:
            return cls()
        except ValueError:  # no introspectable signature (C types): just try
            pass
        return cls(*args)
    return cls()


_shutdown_lock = threading.Lock()
_closeables: list[Any] = []
_hook_registered = False


def close_at_shutdown(closeable: Any) -> None:
    """Register an object with .close() to be closed at interpreter exit,
    in reverse registration order (JVMUtils.closeAtShutdown analogue)."""
    global _hook_registered
    with _shutdown_lock:
        _closeables.append(closeable)
        if not _hook_registered:
            atexit.register(_run_shutdown)
            _hook_registered = True


def _run_shutdown() -> None:
    with _shutdown_lock:
        items = list(reversed(_closeables))
        _closeables.clear()
    for c in items:
        try:
            c.close()
        except Exception:  # pragma: no cover - best effort at exit
            pass
