"""Layered HOCON-style configuration.

Reimplements the behavior the reference gets from Typesafe Config +
``ConfigUtils`` (reference: framework/oryx-common/src/main/java/com/cloudera/
oryx/common/settings/ConfigUtils.java:37-160 and resources/reference.conf).
Config is the framework's dependency-injection mechanism: fully-qualified
class names and tuning values all come from one layered tree, and a config
can be serialized to a string and reparsed so it can be shipped to another
process (the reference ships it into the Tomcat servlet context this way,
ServingLayer.java:275-276).

This is a from-scratch HOCON *subset* parser supporting the features the
framework's own conf files use: ``#``/``//`` comments, nested objects,
dotted keys, ``=`` or ``:`` separators, lists, quoted/unquoted strings,
numbers, booleans, ``null``, ``${path}`` / ``${?path}`` substitutions, and
string-value concatenation (e.g. ``${base}"/data/"``).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Iterator

__all__ = [
    "Config",
    "ConfigError",
    "parse_hocon",
    "from_string",
    "from_file",
    "get_default",
    "overlay_on",
    "set_default_overlay",
    "serialize",
    "key_value_to_properties",
]


class ConfigError(Exception):
    """Missing key, type mismatch, or parse failure."""


_MISSING = object()


# ---------------------------------------------------------------------------
# Tokenizer / parser
# ---------------------------------------------------------------------------

_PUNCT = set("{}[],=:")
_UNQUOTED_FORBIDDEN = set('{}[],=:#"\n\r$')


class _Sub:
    """An unresolved ``${path}`` substitution."""

    __slots__ = ("path", "optional")

    def __init__(self, path: str, optional: bool) -> None:
        self.path = path
        self.optional = optional

    def __repr__(self) -> str:  # pragma: no cover
        return f"${{{'?' if self.optional else ''}{self.path}}}"


class _Concat:
    """A value built from several adjacent tokens (string concatenation)."""

    __slots__ = ("parts",)

    def __init__(self, parts: list[Any]) -> None:
        self.parts = parts


class _Fallback:
    """An optional substitution shadowing an earlier value: ``a = ${?x}``
    over an existing ``a`` keeps the existing value when x is absent
    (HOCON fall-through semantics)."""

    __slots__ = ("sub", "fallback")

    def __init__(self, sub: "_Sub", fallback: Any) -> None:
        self.sub = sub
        self.fallback = fallback


def _tokenize(text: str) -> list[Any]:
    """Tokens: punctuation chars, "\n", ("str", s), ("raw", s), _Sub, and
    ("ws",) markers recording whitespace between adjacent value tokens (so
    string concatenation preserves separators, per HOCON)."""
    toks: list[Any] = []
    pending_ws = False

    def emit(tok: Any) -> None:
        nonlocal pending_ws
        if pending_ws and toks and _is_value_token(toks[-1]) and _is_value_token(tok):
            toks.append(("ws",))
        pending_ws = False
        toks.append(tok)

    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r":
            pending_ws = True
            i += 1
        elif c == "\n":
            emit("\n")
            i += 1
        elif c == "#" or text.startswith("//", i):
            while i < n and text[i] != "\n":
                i += 1
        elif c == '"':
            if text.startswith('"""', i):
                end = text.find('"""', i + 3)
                if end < 0:
                    raise ConfigError("unterminated triple-quoted string")
                emit(("str", text[i + 3 : end]))
                i = end + 3
            else:
                j = i + 1
                buf = []
                while j < n and text[j] != '"':
                    if text[j] == "\\" and j + 1 < n:
                        esc = text[j + 1]
                        if esc == "u":
                            if j + 6 > n:
                                raise ConfigError("malformed \\u escape")
                            try:
                                buf.append(chr(int(text[j + 2 : j + 6], 16)))
                            except ValueError as e:
                                raise ConfigError(f"malformed \\u escape: {text[j:j+6]!r}") from e
                            j += 6
                        else:
                            buf.append(
                                {
                                    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                                    '"': '"', "\\": "\\", "/": "/",
                                }.get(esc, esc)
                            )
                            j += 2
                    else:
                        buf.append(text[j])
                        j += 1
                if j >= n:
                    raise ConfigError("unterminated string")
                emit(("str", "".join(buf)))
                i = j + 1
        elif c == "$":
            if text.startswith("${", i):
                end = text.find("}", i)
                if end < 0:
                    raise ConfigError("unterminated substitution")
                inner = text[i + 2 : end].strip()
                optional = inner.startswith("?")
                if optional:
                    inner = inner[1:].strip()
                emit(_Sub(inner, optional))
                i = end + 1
            else:
                # a literal '$' inside an unquoted value
                j = i + 1
                while j < n and text[j] not in _UNQUOTED_FORBIDDEN:
                    j += 1
                emit(("raw", text[i:j].strip()))
                i = j
        elif c in _PUNCT:
            emit(c)
            i += 1
        else:
            j = i
            while j < n and text[j] not in _UNQUOTED_FORBIDDEN:
                j += 1
            raw = text[i:j].strip()
            if raw:
                emit(("raw", raw))
            i = j if j > i else i + 1
    return toks


def _is_value_token(tok: Any) -> bool:
    return isinstance(tok, _Sub) or (
        isinstance(tok, tuple) and len(tok) == 2 and tok[0] in ("str", "raw")
    )


def _coerce_raw(raw: str) -> Any:
    if raw == "null":
        return None
    if raw == "true":
        return True
    if raw == "false":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


class _Parser:
    def __init__(self, toks: list[Any]) -> None:
        self.toks = toks
        self.pos = 0

    def peek(self) -> Any:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> Any:
        tok = self.peek()
        self.pos += 1
        return tok

    def skip_newlines(self) -> None:
        while self.peek() in ("\n", ","):
            self.pos += 1

    def parse_root(self) -> dict:
        self.skip_newlines()
        if self.peek() == "{":
            obj = self.parse_object()
        else:
            obj = self.parse_object_body(root=True)
        self.skip_newlines()
        if self.peek() is not None:
            raise ConfigError(f"trailing content at token {self.peek()!r}")
        return obj

    def parse_object(self) -> dict:
        assert self.next() == "{"
        obj = self.parse_object_body(root=False)
        if self.next() != "}":
            raise ConfigError("expected '}'")
        return obj

    def parse_object_body(self, root: bool) -> dict:
        obj: dict = {}
        while True:
            self.skip_newlines()
            tok = self.peek()
            if tok is None:
                if root:
                    return obj
                raise ConfigError("unexpected end of input in object")
            if tok == "}":
                return obj
            key = self.parse_key()
            tok = self.peek()
            if tok == "{":
                # object value without separator: key { ... }  (also merges)
                value = self.parse_object()
            else:
                sep = self.next()
                if sep not in ("=", ":"):
                    raise ConfigError(f"expected '=' or ':' after key {key!r}, got {sep!r}")
                while self.peek() == "\n":
                    self.pos += 1
                value = self.parse_value()
            _put_path(obj, key, value)

    def parse_key(self) -> list[str]:
        parts: list[str] = []
        while True:
            tok = self.peek()
            if tok == ("ws",):
                self.next()
                continue
            if isinstance(tok, tuple) and tok[0] in ("raw", "str"):
                self.next()
                text = tok[1]
                if tok[0] == "raw":
                    parts.extend(p for p in text.split(".") if p)
                else:
                    parts.append(text)
            else:
                break
        if not parts:
            raise ConfigError(f"expected key, got {self.peek()!r}")
        return parts

    def parse_value(self) -> Any:
        parts: list[Any] = []
        while True:
            tok = self.peek()
            if tok is None or tok in ("\n", ",", "}", "]"):
                break
            if tok == "{":
                parts.append(self.parse_object())
            elif tok == "[":
                parts.append(self.parse_list())
            elif isinstance(tok, _Sub):
                self.next()
                parts.append(tok)
            elif tok == ("ws",):
                self.next()
                parts.append(" ")  # preserved separator inside a concatenation
            elif isinstance(tok, tuple):
                self.next()
                kind, text = tok
                parts.append(_coerce_raw(text) if kind == "raw" else text)
            else:
                raise ConfigError(f"unexpected token {tok!r} in value")
        if not parts:
            raise ConfigError("empty value")
        if len(parts) == 1:
            return parts[0]
        return _Concat(parts)

    def parse_list(self) -> list:
        assert self.next() == "["
        items: list[Any] = []
        while True:
            self.skip_newlines()
            if self.peek() == "]":
                self.next()
                return items
            if self.peek() is None:
                raise ConfigError("unterminated list")
            items.append(self.parse_value())


def _put_path(obj: dict, path: list[str], value: Any) -> None:
    node = obj
    for part in path[:-1]:
        child = node.get(part)
        if not isinstance(child, dict):
            child = {}
            node[part] = child
        node = child
    last = path[-1]
    existing = node.get(last, _MISSING)
    if isinstance(existing, dict) and isinstance(value, dict):
        _deep_merge(existing, value)
    elif isinstance(value, _Sub) and value.optional and existing is not _MISSING:
        node[last] = _Fallback(value, existing)
    else:
        node[last] = value


def _deep_merge(base: dict, overlay: dict) -> dict:
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_merge(base[k], v)
        elif isinstance(v, _Sub) and v.optional and k in base:
            base[k] = _Fallback(copy.deepcopy(v), base[k])
        else:
            base[k] = copy.deepcopy(v)
    return base


def _lookup(root: dict, path: str) -> Any:
    node: Any = root
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return _MISSING
        node = node[part]
    return node


def _resolve(root: dict) -> None:
    """Resolve ${path} substitutions iteratively (handles forward refs)."""

    for _ in range(20):
        changed, unresolved = _resolve_pass(root, root)
        if not unresolved:
            return
        if not changed:
            raise ConfigError(f"unresolvable substitution(s): {unresolved}")
    raise ConfigError("substitution cycle detected")


def _resolve_pass(node: Any, root: dict) -> tuple[bool, list[str]]:
    changed = False
    unresolved: list[str] = []

    def resolve_value(v: Any) -> tuple[Any, bool]:
        """Return (new_value, resolved?). An absent optional ${?path}
        resolves to _MISSING: the key is then removed entirely (HOCON
        semantics — it must not clobber a lower layer's value)."""
        if isinstance(v, _Sub):
            target = _lookup(root, v.path)
            if target is _MISSING or isinstance(target, (_Sub, _Concat)):
                if v.optional and target is _MISSING:
                    return _MISSING, True
                unresolved.append(v.path)
                return v, False
            return copy.deepcopy(target), True
        if isinstance(v, _Fallback):
            target = _lookup(root, v.sub.path)
            if target is _MISSING:
                return resolve_value(v.fallback)
            if isinstance(target, (_Sub, _Concat, _Fallback)):
                unresolved.append(v.sub.path)
                return v, False
            return copy.deepcopy(target), True
        if isinstance(v, _Concat):
            new_parts = []
            ok = True
            for p in v.parts:
                np, pok = resolve_value(p)
                ok = ok and pok
                new_parts.append(np)
            if not ok:
                return _Concat(new_parts), False
            real = [p for p in new_parts if p is not _MISSING]
            # whitespace separators don't defeat object merging:
            # `z = ${x} ${y}` over two objects merges them (HOCON)
            non_ws = [p for p in real if not (isinstance(p, str) and p.strip() == "")]
            if non_ws and all(isinstance(p, dict) for p in non_ws):
                merged: dict = {}
                for p in non_ws:
                    _deep_merge(merged, p)
                return merged, True
            return "".join("" if p is None or p is _MISSING else str(p) for p in real), True
        return v, True

    if isinstance(node, dict):
        for k, v in list(node.items()):
            if isinstance(v, (dict, list)):
                c, u = _resolve_pass(v, root)
                changed = changed or c
                unresolved.extend(u)
            elif isinstance(v, (_Sub, _Concat, _Fallback)):
                nv, ok = resolve_value(v)
                if ok:
                    if nv is _MISSING:
                        del node[k]
                    else:
                        node[k] = nv
                    changed = True
                elif nv is not v:
                    node[k] = nv
    elif isinstance(node, list):
        drop: list[int] = []
        for i, v in enumerate(list(node)):
            if isinstance(v, (dict, list)):
                c, u = _resolve_pass(v, root)
                changed = changed or c
                unresolved.extend(u)
            elif isinstance(v, (_Sub, _Concat, _Fallback)):
                nv, ok = resolve_value(v)
                if ok:
                    if nv is _MISSING:
                        drop.append(i)
                    else:
                        node[i] = nv
                    changed = True
                elif nv is not v:
                    node[i] = nv
        for i in reversed(drop):
            del node[i]
    return changed, unresolved


def parse_hocon(text: str, resolve: bool = True) -> dict:
    parser = _Parser(_tokenize(text))
    root = parser.parse_root()
    if resolve:
        _resolve(root)
    return root


# ---------------------------------------------------------------------------
# Config object
# ---------------------------------------------------------------------------


def _render_scalar(v: Any) -> str:
    """HOCON-style string rendering: booleans are true/false."""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class Config:
    """Immutable view over a nested config dict with dotted-path access."""

    def __init__(self, data: dict) -> None:
        self._data = data

    # -- raw access ---------------------------------------------------------

    def get(self, path: str, default: Any = _MISSING) -> Any:
        v = _lookup(self._data, path)
        if v is _MISSING:
            if default is _MISSING:
                raise ConfigError(f"missing config key: {path}")
            return default
        return v

    def has(self, path: str) -> bool:
        """True if key exists and is non-null (Typesafe `hasPath` semantics)."""
        v = _lookup(self._data, path)
        return v is not _MISSING and v is not None

    # -- typed getters ------------------------------------------------------

    def get_string(self, path: str) -> str:
        v = self.get(path)
        if v is None or isinstance(v, (dict, list)):
            raise ConfigError(f"{path} is not a string: {v!r}")
        return _render_scalar(v)

    def get_int(self, path: str) -> int:
        v = self.get(path)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ConfigError(f"{path} is not a number: {v!r}")
        return int(v)

    def get_float(self, path: str) -> float:
        v = self.get(path)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ConfigError(f"{path} is not a number: {v!r}")
        return float(v)

    def get_bool(self, path: str) -> bool:
        v = self.get(path)
        if not isinstance(v, bool):
            raise ConfigError(f"{path} is not a boolean: {v!r}")
        return v

    def get_list(self, path: str) -> list:
        v = self.get(path)
        if not isinstance(v, list):
            raise ConfigError(f"{path} is not a list: {v!r}")
        return v

    def get_strings(self, path: str) -> list[str]:
        return [str(x) for x in self.get_list(path)]

    def get_config(self, path: str) -> "Config":
        v = self.get(path)
        if not isinstance(v, dict):
            raise ConfigError(f"{path} is not an object: {v!r}")
        return Config(v)

    # -- optional getters (null or missing -> None); mirrors
    # ConfigUtils.getOptionalString/getOptionalStringList/getOptionalDouble
    # (reference ConfigUtils.java:49-89) -----------------------------------

    def get_optional_string(self, path: str) -> str | None:
        v = _lookup(self._data, path)
        if v is _MISSING or v is None:
            return None
        if isinstance(v, (dict, list)):
            raise ConfigError(f"{path} is not a string: {v!r}")
        return _render_scalar(v)

    def get_optional_strings(self, path: str) -> list[str] | None:
        v = _lookup(self._data, path)
        if v is _MISSING or v is None:
            return None
        if isinstance(v, list):
            return [_render_scalar(x) for x in v]
        if isinstance(v, dict):
            raise ConfigError(f"{path} is not a string list: {v!r}")
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def get_optional_float(self, path: str) -> float | None:
        v = _lookup(self._data, path)
        if v is _MISSING or v is None:
            return None
        return float(v)

    def get_optional_int(self, path: str) -> int | None:
        v = _lookup(self._data, path)
        if v is _MISSING or v is None:
            return None
        return int(v)

    def get_optional_bool(self, path: str) -> bool | None:
        v = _lookup(self._data, path)
        if v is _MISSING or v is None:
            return None
        if not isinstance(v, bool):
            raise ConfigError(f"{path} is not a boolean: {v!r}")
        return v

    # -- layering -----------------------------------------------------------

    def with_overlay(self, overlay: "Config | dict | str | None") -> "Config":
        """Return a new Config = self with `overlay` taking precedence.

        Mirrors ConfigUtils.overlayOn (reference ConfigUtils.java:69-80).
        """
        if overlay is None:
            return self
        if isinstance(overlay, str):
            # parse unresolved so ${...} in the overlay can reference base keys
            overlay = parse_hocon(overlay, resolve=False)
        elif isinstance(overlay, Config):
            overlay = overlay._data
        merged = copy.deepcopy(self._data)
        _deep_merge(merged, overlay)
        _resolve(merged)
        return Config(merged)

    def as_dict(self) -> dict:
        return copy.deepcopy(self._data)

    # -- serialization ------------------------------------------------------

    def serialize(self) -> str:
        """Render to a string that parse_hocon can read back.

        Mirrors ConfigUtils.serialize (reference ConfigUtils.java:90-101):
        used to ship config across process boundaries as one string.
        """
        return json.dumps(self._data, ensure_ascii=False)

    def pretty(self) -> str:
        return json.dumps(self._data, indent=2, sort_keys=True, ensure_ascii=False)

    def to_properties(self, prefix: str = "") -> dict[str, str]:
        """Flatten to dotted key -> string value (ConfigToProperties analogue)."""
        out: dict[str, str] = {}

        def walk(node: Any, path: str) -> None:
            if isinstance(node, dict):
                for k, v in node.items():
                    walk(v, f"{path}.{k}" if path else k)
            elif node is None:
                pass
            elif isinstance(node, list):
                out[path] = json.dumps(node)
            elif isinstance(node, bool):
                out[path] = "true" if node else "false"
            else:
                out[path] = str(node)

        walk(self._data, prefix)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Config({json.dumps(self._data)[:200]})"


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

_DEFAULT_RESOURCES = [
    os.path.join(os.path.dirname(__file__), "resources", "reference.conf"),
    os.path.join(os.path.dirname(__file__), "..", "app", "resources", "reference.conf"),
]

_default_overlay: dict | None = None


def from_string(text: str) -> Config:
    return Config(parse_hocon(text))


def from_file(path: str) -> Config:
    with open(path, "r", encoding="utf-8") as f:
        return from_string(f.read())


def serialize(config: Config) -> str:
    return config.serialize()


def deserialize(text: str) -> Config:
    return from_string(text)


def set_default_overlay(overlay: dict | None) -> None:
    """Install a process-global overlay used by get_default() (test hook)."""
    global _default_overlay
    _default_overlay = overlay


def get_default() -> Config:
    """Layered default config: packaged reference.conf files, then the file
    named by $ORYX_CONF (the analogue of -Dconfig.file, oryx-run.sh:146-147),
    then any programmatic overlay installed by set_default_overlay()."""
    merged: dict = {}
    for res in _DEFAULT_RESOURCES:
        res = os.path.abspath(res)
        if os.path.exists(res):
            with open(res, "r", encoding="utf-8") as f:
                _deep_merge(merged, parse_hocon(f.read(), resolve=False))
    user = os.environ.get("ORYX_CONF")
    if user:
        with open(user, "r", encoding="utf-8") as f:
            _deep_merge(merged, parse_hocon(f.read(), resolve=False))
    if _default_overlay:
        _deep_merge(merged, copy.deepcopy(_default_overlay))
    _resolve(merged)
    return Config(merged)


def overlay_on(overlay: Config | dict | str | None, base: Config) -> Config:
    return base.with_overlay(overlay)


def key_value_to_properties(*pairs: Any) -> dict[str, str]:
    """keyValueToProperties analogue (ConfigUtils.java:103-118)."""
    if len(pairs) % 2 != 0:
        raise ValueError("odd number of key/value elements")
    it: Iterator[Any] = iter(pairs)
    return {str(k): str(v) for k, v in zip(it, it)}
