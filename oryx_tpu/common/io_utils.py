"""Filesystem and network helpers.

Rebuild of the reference's IOUtils (framework/oryx-common/src/main/java/com/
cloudera/oryx/common/io/IOUtils.java): free-port selection, recursive
delete, glob listing — mostly test and layer-runtime scaffolding.
"""

from __future__ import annotations

import contextlib
import fnmatch
import os
import shutil
import socket
from pathlib import Path

__all__ = ["choose_free_port", "delete_recursively", "list_files", "mkdirs"]


def choose_free_port() -> int:
    with contextlib.closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def delete_recursively(path: str | Path) -> None:
    p = Path(path)
    if p.is_dir():
        shutil.rmtree(p, ignore_errors=True)
    elif p.exists():
        p.unlink(missing_ok=True)


def list_files(dir_path: str | Path, glob: str = "*") -> list[Path]:
    """Sorted non-recursive glob listing (IOUtils.listFiles analogue)."""
    d = Path(dir_path)
    if not d.is_dir():
        return []
    return sorted(p for p in d.iterdir() if fnmatch.fnmatch(p.name, glob))


def mkdirs(path: str | Path) -> Path:
    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    return p
