"""Columnar record collections for the batch data path.

The reference's batch layer hands Spark RDDs of (key, message) pairs to
the update (BatchUpdateFunction.java:103-130): distributed, lazy, and
re-iterable. The TPU-native equivalent is a :class:`Records` collection —
re-iterable as ``KeyMessage`` objects for generic apps, and exposing
``blocks()`` of numpy byte-string columns so numeric apps (ALS) can parse
and aggregate whole micro-batches with vectorized numpy instead of a
Python loop per line. Nothing is materialized as one giant Python list:
``FileRecords`` streams one stored micro-batch file at a time, which is
what keeps a 100M-rating train within host RAM.

Messages travel as numpy ``S``-dtype (UTF-8 bytes) arrays: fixed-width,
contiguous, and directly consumable by the vectorized CSV parser in
app/als/data.py.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Sequence

import numpy as np

from oryx_tpu.bus.core import KeyMessage


class RecordBlock:
    """One columnar chunk: parallel key/message byte-string arrays.

    S arrays cannot hold None, so None keys travel as an explicit boolean
    mask — ``key=""`` and ``key=None`` survive a storage round-trip as
    distinct values, like the reference's nullable Text keys. (One
    S-dtype caveat: numpy strips *trailing* NUL bytes, so keys/messages
    ending in "\\x00" are not representable columnar.)
    """

    __slots__ = ("keys", "messages", "none_keys")

    def __init__(
        self,
        keys: np.ndarray | None,
        messages: np.ndarray,
        none_keys: np.ndarray | None = None,
    ) -> None:
        self.keys = keys  # S-dtype array, or None when every key is None
        self.messages = messages  # S-dtype array
        self.none_keys = none_keys  # bool array (True = key is None), or None

    def __len__(self) -> int:
        return len(self.messages)

    def iter_key_messages(self) -> Iterator[KeyMessage]:
        msgs = self.messages.tolist()  # list[bytes], C-level
        if self.keys is None:
            for m in msgs:
                yield KeyMessage(None, m.decode("utf-8", "replace"))
        else:
            nones = (
                self.none_keys.tolist()
                if self.none_keys is not None
                else [False] * len(msgs)
            )
            for k, m, is_none in zip(self.keys.tolist(), msgs, nones):
                yield KeyMessage(
                    None if is_none else k.decode("utf-8", "replace"),
                    m.decode("utf-8", "replace"),
                )

    @staticmethod
    def from_key_messages(records: Sequence[KeyMessage]) -> "RecordBlock":
        msgs = np.array([r.message.encode("utf-8") for r in records], dtype="S")
        if any(r.key is not None for r in records):
            keys = np.array(
                [(r.key or "").encode("utf-8") for r in records], dtype="S"
            )
            none_keys = np.array([r.key is None for r in records], dtype=bool)
            return RecordBlock(keys, msgs, none_keys if none_keys.any() else None)
        return RecordBlock(None, msgs)


class Records:
    """Re-iterable collection of records; base contract for the batch
    update's ``new_data``/``past_data`` arguments."""

    def blocks(self) -> Iterator[RecordBlock]:
        raise NotImplementedError

    def is_empty(self) -> bool:
        return next(iter(self.blocks()), None) is None

    def __iter__(self) -> Iterator[KeyMessage]:
        for block in self.blocks():
            yield from block.iter_key_messages()


class ListRecords(Records):
    """An in-memory list of KeyMessages (the drained input micro-batch)."""

    def __init__(self, records: list[KeyMessage]) -> None:
        self._records = records

    def blocks(self) -> Iterator[RecordBlock]:
        if self._records:
            yield RecordBlock.from_key_messages(self._records)

    def is_empty(self) -> bool:
        return not self._records

    def __iter__(self) -> Iterator[KeyMessage]:
        return iter(self._records)

    def __len__(self) -> int:
        return len(self._records)


class BlockRecords(Records):
    """A materialized list of columnar blocks (e.g. drained poll_block
    batches)."""

    def __init__(self, blocks: Sequence[RecordBlock]) -> None:
        self._blocks = list(blocks)

    def blocks(self) -> Iterator[RecordBlock]:
        return iter(self._blocks)

    def is_empty(self) -> bool:
        return not any(len(b) for b in self._blocks)

    def __len__(self) -> int:
        return sum(len(b) for b in self._blocks)


class ChainRecords(Records):
    """Concatenation of collections, kept lazy (past + new train data)."""

    def __init__(self, parts: Sequence[Records]) -> None:
        self._parts = list(parts)

    def blocks(self) -> Iterator[RecordBlock]:
        for part in self._parts:
            yield from part.blocks()

    def is_empty(self) -> bool:
        return all(p.is_empty() for p in self._parts)


def as_records(data: Iterable[KeyMessage]) -> Records:
    if isinstance(data, Records):
        return data
    return ListRecords(list(data))
