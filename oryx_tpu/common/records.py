"""Columnar record collections for the batch data path.

The reference's batch layer hands Spark RDDs of (key, message) pairs to
the update (BatchUpdateFunction.java:103-130): distributed, lazy, and
re-iterable. The TPU-native equivalent is a :class:`Records` collection —
re-iterable as ``KeyMessage`` objects for generic apps, and exposing
``blocks()`` of numpy byte-string columns so numeric apps (ALS) can parse
and aggregate whole micro-batches with vectorized numpy instead of a
Python loop per line. Nothing is materialized as one giant Python list:
``FileRecords`` streams one stored micro-batch file at a time, which is
what keeps a 100M-rating train within host RAM.

Messages travel as numpy ``S``-dtype (UTF-8 bytes) arrays: fixed-width,
contiguous, and directly consumable by the vectorized CSV parser in
app/als/data.py.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Sequence

import numpy as np

from oryx_tpu.bus.core import KeyMessage


class RecordBlock:
    """One columnar chunk: parallel key/message byte-string arrays.

    S arrays cannot hold None, so None keys travel as an explicit boolean
    mask — ``key=""`` and ``key=None`` survive a storage round-trip as
    distinct values, like the reference's nullable Text keys. (One
    S-dtype caveat: numpy strips *trailing* NUL bytes, so keys/messages
    ending in "\\x00" are not representable columnar.)
    """

    __slots__ = ("keys", "messages", "none_keys", "trace")

    def __init__(
        self,
        keys: np.ndarray | None,
        messages: np.ndarray,
        none_keys: np.ndarray | None = None,
    ) -> None:
        self.keys = keys  # S-dtype array, or None when every key is None
        self.messages = messages  # S-dtype array
        self.none_keys = none_keys  # bool array (True = key is None), or None
        # raw "@trc" control-record message (str) stripped by the
        # transport, or None; parse with common.tracing.parse_header
        self.trace = None

    def __len__(self) -> int:
        return len(self.messages)

    def iter_key_messages(self) -> Iterator[KeyMessage]:
        msgs = self.messages.tolist()  # list[bytes], C-level
        if self.keys is None:
            for m in msgs:
                yield KeyMessage(None, m.decode("utf-8", "replace"))
        else:
            nones = (
                self.none_keys.tolist()
                if self.none_keys is not None
                else [False] * len(msgs)
            )
            for k, m, is_none in zip(self.keys.tolist(), msgs, nones):
                yield KeyMessage(
                    None if is_none else k.decode("utf-8", "replace"),
                    m.decode("utf-8", "replace"),
                )

    @staticmethod
    def from_key_messages(records: Sequence[KeyMessage]) -> "RecordBlock":
        msgs = np.array([r.message.encode("utf-8") for r in records], dtype="S")
        if any(r.key is not None for r in records):
            keys = np.array(
                [(r.key or "").encode("utf-8") for r in records], dtype="S"
            )
            none_keys = np.array([r.key is None for r in records], dtype=bool)
            return RecordBlock(keys, msgs, none_keys if none_keys.any() else None)
        return RecordBlock(None, msgs)


class InteractionBlock:
    """A typed columnar chunk of rating events: int32 id codes + f32
    values, straight off a binary bus frame (bus/blockcodec.py kind=2).

    Quacks like a None-keyed :class:`RecordBlock` (``keys``/``messages``/
    ``none_keys``/``len``/``iter_key_messages``) so generic consumers and
    the dead-letter path keep working, but parse-aware consumers (the ALS
    speed manager) read ``users``/``items``/``values`` directly — the
    decode stage becomes array views instead of text splitting. The
    arrays may be zero-copy views over transport memory: they are valid
    until the consumer's next poll (or release()), the same lifetime
    contract GuardedBlockFeed already enforces for update blocks.
    """

    __slots__ = ("users", "items", "values", "timestamps",
                 "user_prefix", "item_prefix", "_messages", "trace")

    keys = None  # input events are None-keyed, like the text path
    none_keys = None

    def __init__(
        self,
        users: np.ndarray,
        items: np.ndarray,
        values: np.ndarray,
        timestamps: np.ndarray | None = None,
        user_prefix: bytes = b"u",
        item_prefix: bytes = b"i",
    ) -> None:
        self.users = users  # int32 id codes
        self.items = items  # int32 id codes
        self.values = values  # float32
        self.timestamps = timestamps  # int64 ms, or None
        self.user_prefix = user_prefix
        self.item_prefix = item_prefix
        self._messages = None
        self.trace = None  # raw "@trc" message carried by the transport

    def __len__(self) -> int:
        return len(self.values)

    def materialize(self) -> "InteractionBlock":
        """Copy the columns out of transport memory (for holders that
        outlive the poll window, e.g. a chaos-dup stash)."""
        out = InteractionBlock(
            np.array(self.users), np.array(self.items), np.array(self.values),
            None if self.timestamps is None else np.array(self.timestamps),
            self.user_prefix, self.item_prefix,
        )
        out.trace = self.trace
        return out

    @property
    def messages(self) -> np.ndarray:
        """Text rendering ``<up><user>,<ip><item>,<value>[,<ts>]`` as an
        S-array — the compatibility path (generic managers, dead-letter
        replay); parse-aware consumers never touch it. ``%.9g`` prints
        enough digits to round-trip any float32 exactly."""
        if self._messages is None:
            up = self.user_prefix.decode("ascii", "replace")
            ip = self.item_prefix.decode("ascii", "replace")
            us, its = self.users.tolist(), self.items.tolist()
            vs = self.values.tolist()
            if self.timestamps is not None:
                ts = self.timestamps.tolist()
                lines = [
                    f"{up}{u},{ip}{i},{v:.9g},{t}".encode()
                    for u, i, v, t in zip(us, its, vs, ts)
                ]
            else:
                lines = [
                    f"{up}{u},{ip}{i},{v:.9g}".encode()
                    for u, i, v in zip(us, its, vs)
                ]
            self._messages = np.array(lines, dtype="S") if lines else np.empty(0, "S1")
        return self._messages

    def iter_key_messages(self) -> Iterator[KeyMessage]:
        for m in self.messages.tolist():
            yield KeyMessage(None, m.decode("utf-8", "replace"))


class Records:
    """Re-iterable collection of records; base contract for the batch
    update's ``new_data``/``past_data`` arguments."""

    def blocks(self) -> Iterator[RecordBlock]:
        raise NotImplementedError

    def is_empty(self) -> bool:
        return next(iter(self.blocks()), None) is None

    def __iter__(self) -> Iterator[KeyMessage]:
        for block in self.blocks():
            yield from block.iter_key_messages()


class ListRecords(Records):
    """An in-memory list of KeyMessages (the drained input micro-batch)."""

    def __init__(self, records: list[KeyMessage]) -> None:
        self._records = records

    def blocks(self) -> Iterator[RecordBlock]:
        if self._records:
            yield RecordBlock.from_key_messages(self._records)

    def is_empty(self) -> bool:
        return not self._records

    def __iter__(self) -> Iterator[KeyMessage]:
        return iter(self._records)

    def __len__(self) -> int:
        return len(self._records)


class BlockRecords(Records):
    """A materialized list of columnar blocks (e.g. drained poll_block
    batches)."""

    def __init__(self, blocks: Sequence[RecordBlock]) -> None:
        self._blocks = list(blocks)

    def blocks(self) -> Iterator[RecordBlock]:
        return iter(self._blocks)

    def is_empty(self) -> bool:
        return not any(len(b) for b in self._blocks)

    def __len__(self) -> int:
        return sum(len(b) for b in self._blocks)


class ChainRecords(Records):
    """Concatenation of collections, kept lazy (past + new train data)."""

    def __init__(self, parts: Sequence[Records]) -> None:
        self._parts = list(parts)

    def blocks(self) -> Iterator[RecordBlock]:
        for part in self._parts:
            yield from part.blocks()

    def is_empty(self) -> bool:
        return all(p.is_empty() for p in self._parts)


def as_records(data: Iterable[KeyMessage]) -> Records:
    if isinstance(data, Records):
        return data
    return ListRecords(list(data))
