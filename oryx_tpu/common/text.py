"""Wire-format text utilities: CSV and JSON line parse/join.

Rebuild of the reference's TextUtils (framework/oryx-common/src/main/java/
com/cloudera/oryx/common/text/TextUtils.java:38-190) and the parse function
in MLFunctions.PARSE_FN (app/oryx-app-common/.../common/fn/MLFunctions.java:
30-54): an input line is JSON if it starts with '[' or '{', otherwise CSV.
`join_json`/`parse_json` is the wire format for ALS feature-vector "UP"
updates.
"""

from __future__ import annotations

import csv
import io
import json
import math
from typing import Any, Sequence

__all__ = [
    "parse_json_array",
    "parse_delimited",
    "parse_csv",
    "parse_line",
    "join_delimited",
    "join_csv",
    "join_json",
    "read_json",
]


def parse_json_array(line: str) -> list:
    """Parse a JSON array line into a flat list of strings/values.

    Mirrors TextUtils.parseJSONArray: primitives become their string form,
    nested arrays/objects stay JSON-encoded strings.
    """
    arr = json.loads(line)
    if not isinstance(arr, list):
        raise ValueError(f"not a JSON array: {line!r}")
    out: list[str] = []
    for v in arr:
        if isinstance(v, (list, dict)):
            out.append(json.dumps(v))
        elif isinstance(v, bool):
            out.append("true" if v else "false")
        elif v is None:
            out.append("")
        else:
            out.append(str(v))
    return out


def parse_delimited(line: str, delimiter: str = ",") -> list[str]:
    if not line:
        return []
    if '"' not in line:  # fast path: no quoting, plain split (hot ingest path)
        return line.split(delimiter)
    reader = csv.reader(io.StringIO(line), delimiter=delimiter)
    for row in reader:
        return row
    return []


def parse_csv(line: str) -> list[str]:
    return parse_delimited(line, ",")


def parse_line(line: str) -> list[str]:
    """CSV-or-JSON auto-detect (MLFunctions.PARSE_FN semantics)."""
    stripped = line.strip()
    if stripped.startswith("[") or stripped.startswith("{"):
        return parse_json_array(stripped)
    return parse_csv(stripped)


def _fmt(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    return str(v)


def join_delimited(items: Sequence[Any], delimiter: str = ",") -> str:
    buf = io.StringIO()
    writer = csv.writer(buf, delimiter=delimiter, lineterminator="")
    writer.writerow([_fmt(x) for x in items])
    return buf.getvalue()


def join_csv(items: Sequence[Any]) -> str:
    return join_delimited(items, ",")


class _CompactEncoder(json.JSONEncoder):
    def default(self, o: Any):
        try:
            import numpy as np

            if isinstance(o, np.ndarray):
                return o.tolist()
            if isinstance(o, np.generic):
                return o.item()
        except ImportError:  # pragma: no cover
            pass
        return super().default(o)


def join_json(items: Sequence[Any]) -> str:
    """Serialize a list as a compact JSON array (the 'UP' message format)."""
    return json.dumps(list(items), cls=_CompactEncoder, separators=(",", ":"), allow_nan=True)


def read_json(text: str) -> Any:
    return json.loads(text)


_PLAIN = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-.:@ "
)


def json_str(s: str) -> str:
    """JSON string literal; quoting fast path for typical IDs."""
    if all(c in _PLAIN for c in s):
        return f'"{s}"'
    return json.dumps(s)
