"""Profiling hooks: JAX profiler traces on demand.

The reference delegates job observability to the Spark web UI
(src/site/markdown/docs/performance.md:36-41); SURVEY.md §5 asks the
rebuild to exceed that with real profiler integration. When a profile
directory is configured (``oryx.batch.compute.profile-dir`` /
``oryx.speed.compute.profile-dir``) each traced span produces an xprof
trace under ``<dir>/<name>-<timestamp>/`` viewable with TensorBoard's
profile plugin or xprof; without one the context manager is a no-op
(zero overhead on the hot path).

Step-time breakdowns are separate: layers wrap their phases in
``metrics.timed`` histograms, exported at /metrics.
"""

from __future__ import annotations

import contextlib
import logging
import time

log = logging.getLogger(__name__)


@contextlib.contextmanager
def maybe_trace(profile_dir: str | None, name: str):
    """jax.profiler trace of the enclosed block when profile_dir is set."""
    if not profile_dir:
        yield
        return
    import jax

    target = f"{profile_dir.rstrip('/')}/{name}-{int(time.time() * 1000)}"
    log.info("profiling %s -> %s", name, target)
    # tracing must never take down a layer: profiler start/stop failures
    # are logged and swallowed; the body's own exceptions propagate
    started = False
    try:
        jax.profiler.start_trace(target)
        started = True
    except Exception:
        log.exception("could not start profiler trace %s", target)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                log.exception("could not stop profiler trace %s", target)


def profile_dir_from_config(config, layer: str) -> str | None:
    """Configured trace directory for a layer, or None (off)."""
    return config.get(f"oryx.{layer}.compute.profile-dir", None)


def capture(profile_dir: str, name: str, seconds: float) -> str:
    """On-demand wall-clock profiler capture (the serving layer's
    ``POST /debug/profile``): trace whatever the process's devices do for
    ``seconds``, write under ``profile_dir``, return the trace path.
    Raises RuntimeError when the profiler cannot start (caller maps it to
    an HTTP error)."""
    import jax

    target = f"{profile_dir.rstrip('/')}/{name}-{int(time.time() * 1000)}"
    try:
        jax.profiler.start_trace(target)
    except Exception as e:
        raise RuntimeError(f"could not start profiler trace: {e}") from e
    try:
        time.sleep(max(0.0, seconds))
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            log.exception("could not stop profiler trace %s", target)
    return target
