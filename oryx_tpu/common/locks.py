"""TSan-lite runtime lock instrumentation: OrderedLock + watchdog.

The static lock-order pass (oryx_tpu/analysis/lockorder.py) proves the
*declared* nesting graph acyclic; this module checks the *executed* one.
``instrument()`` swaps ``threading.Lock`` / ``threading.RLock`` for thin
wrappers that maintain a process-wide lock-acquisition order graph keyed
by construction site (every ``self._lock = threading.Lock()`` in a class
maps to one node, however many instances exist). On each blocking
acquire the wrapper records held-lock -> acquired-lock edges and refuses
edge insertions that would close a cycle — the AB/BA deadlock is
reported as a raised :class:`LockOrderViolation` in the acquiring
thread *before* it blocks, so tests detect the bug without hanging.

Two watchdogs ride along:

- acquire-timeout: an indefinite blocking acquire is sliced into timed
  acquires; exceeding the budget raises :class:`LockWatchdogTimeout`
  (turning a silent deadlock/hang into a test failure with a message);
- held-too-long: release() checks wall time since acquire and records a
  violation when a lock was held longer than the configured budget.

Design constraints, in order: (1) the wrappers must be perfect drop-ins
— once ``threading.Lock`` is patched, stdlib ``queue.Queue`` and
``threading.Condition`` construct them too, so the full Lock protocol
(including the ``_is_owned``/``_release_save``/``_acquire_restore``
hooks Condition probes for) is provided; (2) near-zero overhead — the
fast path is one threading.local lookup and a dict membership test per
acquire (bench.py enforces the <=2% envelope); (3) zero imports from
the rest of oryx_tpu — metrics/tracing themselves allocate locks, and
instrumenting the instrumenter must not recurse.

Locks created *before* ``instrument()`` (module singletons bound at
import) keep their raw type and stay untracked; coverage targets the
per-test object graph, which is where the lambda layers' concurrency
lives. ``deinstrument()`` restores the factories; surviving wrappers
degrade to plain delegation once inactive.
"""

from __future__ import annotations

import os
import sys
import threading
import time

# the real C factories, captured before any patching
_real_lock = threading.Lock
_real_rlock = threading.RLock

_SLICE_S = 0.1  # granularity of the sliced indefinite acquire


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here would close a lock-order cycle."""


class LockWatchdogTimeout(RuntimeError):
    """A blocking acquire exceeded the watchdog budget."""


class _Config:
    __slots__ = ("strict", "acquire_timeout", "hold_warn")

    def __init__(self, strict, acquire_timeout, hold_warn):
        self.strict = strict
        self.acquire_timeout = acquire_timeout
        self.hold_warn = hold_warn


_cfg: _Config | None = None
_graph_mu = _real_lock()
_edges: dict[str, set[str]] = {}
_violations: list[str] = []
_tls = threading.local()


def _active() -> bool:
    return _cfg is not None


def _site_key() -> str:
    """Identify a lock by its construction site (file:line), so all
    instances of a class share one graph node."""
    frame = sys._getframe(1)
    here = __name__
    while frame is not None and frame.f_globals.get("__name__") == here:
        frame = frame.f_back
    if frame is None:  # pragma: no cover - interpreter internals
        return "<unknown>"
    fn = frame.f_code.co_filename
    parts = fn.replace(os.sep, "/").rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) >= 2 else fn
    return f"{short}:{frame.f_lineno}"


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _find_path(src: str, dst: str) -> list[str] | None:
    """Path src -> ... -> dst in the order graph, or None. Caller holds
    _graph_mu."""
    seen = {src}
    trail = {src: None}
    work = [src]
    while work:
        cur = work.pop()
        if cur == dst:
            path = []
            while cur is not None:
                path.append(cur)
                cur = trail[cur]
            return path[::-1]
        for nxt in _edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                trail[nxt] = cur
                work.append(nxt)
    return None


def _note_acquire(key: str) -> None:
    """Record held -> key edges; detect (and in strict mode refuse) a
    cycle-closing edge before the caller blocks on the lock."""
    st = _stack()
    if not st:
        return
    boom = None
    for held_key, _t0 in st:
        if held_key == key or key in _edges.get(held_key, ()):
            continue
        with _graph_mu:
            bucket = _edges.setdefault(held_key, set())
            if key in bucket:
                continue
            path = _find_path(key, held_key)
            bucket.add(key)
            if path is not None:
                msg = (
                    f"lock-order cycle: acquiring {key} while holding "
                    f"{held_key}, but the reverse order "
                    f"{' -> '.join(path)} was already observed"
                )
                _violations.append(msg)
                boom = msg
    if boom is not None and _cfg is not None and _cfg.strict:
        raise LockOrderViolation(boom)


def _push(key: str) -> None:
    cfg = _cfg
    # the timestamp only feeds held-too-long; skip the clock read (the
    # costliest part of an uncontended acquire) when that check is off
    t0 = time.monotonic() if cfg is not None and cfg.hold_warn is not None else 0.0
    _stack().append((key, t0))


def _pop(key: str) -> None:
    st = getattr(_tls, "stack", None)
    if not st:
        return
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == key:
            _, t0 = st.pop(i)
            cfg = _cfg
            if cfg is not None and cfg.hold_warn is not None:
                held = time.monotonic() - t0
                if held > cfg.hold_warn:
                    _violations.append(
                        f"held-too-long: {key} held {held:.3f}s "
                        f"(budget {cfg.hold_warn}s)"
                    )
            return


def _acquire_sliced(raw, key: str, timeout_budget: float) -> bool:
    """Indefinite blocking acquire as timed slices so a deadlock turns
    into a diagnosable failure instead of a hung suite."""
    deadline = time.monotonic() + timeout_budget
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            msg = (
                f"acquire-timeout: {key} not acquired within "
                f"{timeout_budget}s (likely deadlock or a lock leak)"
            )
            _violations.append(msg)
            raise LockWatchdogTimeout(msg)
        if raw.acquire(True, min(_SLICE_S, remaining)):
            return True


class OrderedLock:
    """Drop-in ``threading.Lock`` tracked by the order graph."""

    __slots__ = ("_lk", "_key")

    def __init__(self, name: str | None = None):
        self._lk = _real_lock()
        self._key = name or _site_key()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _active():
            return self._lk.acquire(blocking, timeout)
        if not blocking:
            # a non-blocking attempt cannot deadlock: no edges recorded
            ok = self._lk.acquire(False)
        else:
            _note_acquire(self._key)
            cfg = _cfg
            if timeout is not None and timeout >= 0:
                ok = self._lk.acquire(True, timeout)
            elif cfg is not None and cfg.acquire_timeout is not None:
                # uncontended fast path: a try-lock avoids the sliced
                # acquire's deadline arithmetic entirely
                ok = self._lk.acquire(False) or _acquire_sliced(
                    self._lk, self._key, cfg.acquire_timeout
                )
            else:
                ok = self._lk.acquire(True)
        if ok:
            _push(self._key)
        return ok

    def release(self) -> None:
        if _active():
            _pop(self._key)
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition() probes for this; answering from the raw lock keeps the
    # probe out of the order graph (it is non-blocking by construction).
    def _is_owned(self) -> bool:
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True

    # stdlib modules register module-level locks with os.register_at_fork
    # (e.g. concurrent.futures.thread); without this they fail to import
    # while the watchdog is installed
    def _at_fork_reinit(self) -> None:
        self._lk = _real_lock()

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<OrderedLock {self._key} locked={self._lk.locked()}>"


class OrderedRLock:
    """Drop-in ``threading.RLock`` tracked by the order graph.

    Ownership/recursion are tracked wrapper-side so only the outermost
    acquire/release touch the graph, and so ``Condition.wait`` can fully
    release a reentrantly-held lock via ``_release_save``.
    """

    __slots__ = ("_lk", "_key", "_owner", "_count")

    def __init__(self, name: str | None = None):
        self._lk = _real_rlock()
        self._key = name or _site_key()
        self._owner = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:  # reentrant: no edges, no stack traffic
            ok = self._lk.acquire(blocking, timeout)
            if ok:
                self._count += 1
            return ok
        if not _active():
            ok = self._lk.acquire(blocking, timeout)
        elif not blocking:
            ok = self._lk.acquire(False)
        else:
            _note_acquire(self._key)
            cfg = _cfg
            if timeout is not None and timeout >= 0:
                ok = self._lk.acquire(True, timeout)
            elif cfg is not None and cfg.acquire_timeout is not None:
                ok = self._lk.acquire(False) or _acquire_sliced(
                    self._lk, self._key, cfg.acquire_timeout
                )
            else:
                ok = self._lk.acquire(True)
        if ok:
            self._owner = me
            self._count = 1
            if _active():
                _push(self._key)
        return ok

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            if _active():
                _pop(self._key)
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # --- Condition integration -------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        count, owner = self._count, self._owner
        self._count = 0
        self._owner = None
        if _active():
            _pop(self._key)
        for _ in range(count):
            self._lk.release()
        return (count, owner)

    def _acquire_restore(self, state) -> None:
        count, owner = state
        if _active():
            _note_acquire(self._key)
        for _ in range(count):
            self._lk.acquire()
        self._count = count
        self._owner = owner
        if _active():
            _push(self._key)

    def _at_fork_reinit(self) -> None:
        self._lk = _real_rlock()
        self._owner = None
        self._count = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<OrderedRLock {self._key} count={self._count}>"


def instrument(
    strict: bool = True,
    acquire_timeout: float | None = 30.0,
    hold_warn: float | None = None,
) -> None:
    """Activate the watchdog: new ``threading.Lock()``/``RLock()`` calls
    return tracked wrappers. ``strict`` raises on cycle-closing edges;
    otherwise they are only recorded (see :func:`violations`)."""
    global _cfg
    _cfg = _Config(strict, acquire_timeout, hold_warn)
    threading.Lock = OrderedLock
    threading.RLock = OrderedRLock


def deinstrument() -> None:
    """Restore the real factories. Surviving wrappers become passthrough
    (``_active()`` gates every bookkeeping path)."""
    global _cfg
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _cfg = None


def reset() -> None:
    """Drop the accumulated order graph and violation log."""
    with _graph_mu:
        _edges.clear()
        _violations.clear()


def violations() -> list[str]:
    """Violations recorded since the last reset (cycles, held-too-long,
    acquire-timeouts) — strict-mode raises are also recorded here."""
    with _graph_mu:
        return list(_violations)


def order_edges() -> dict[str, set[str]]:
    """Snapshot of the observed acquisition-order graph (for tests)."""
    with _graph_mu:
        return {k: set(v) for k, v in _edges.items()}


def instrumented() -> bool:
    return _active()
