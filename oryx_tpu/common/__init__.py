"""Common substrate: config, RNG, text wire formats, IO, concurrency, PMML.

Rebuild of the reference's framework/oryx-common module (SURVEY.md §2.1).
"""
