"""Seeded, env-activated kill-point instrumentation (crash-only testing).

The lambda architecture's fault-tolerance story (PAPER.md) is usually
tested at the message level — the fault bus drops/delays/duplicates
deliveries — but the failures that actually corrupt state are process
deaths *between* the steps of a commit sequence: the model directory is
promoted but the manifest isn't written, the update message is published
but the input offsets aren't committed, the CHAMPION temp file is
renamed but never fsynced. This module marks those instants explicitly.

Every state-mutating commit sequence in the repo calls
``crashpoint("<site>")`` at each step boundary. In production the call
is a no-op costing one attribute load and one comparison. Under test,
setting

    ORYX_CRASHPOINT=<site>:<nth>

in a worker's environment kills the process with SIGKILL the <nth> time
(1-based) execution reaches that site — no atexit hooks, no ``finally``
blocks, no stream flushing: the closest stand-in for ``kill -9`` a
process can inflict on itself. The sweep harness (tools/crash_sweep.py)
iterates every site in ``CATALOG``, kills a worker at each one, restarts
it, and asserts the at-least-once invariants (no acknowledged-input
loss, no duplicate generations, monotone CHAMPION lineage) survived.

For in-process unit tests ``arm(site, nth, action="raise")`` raises
``CrashPointReached`` instead of killing the interpreter, so a single
test can simulate the death of one commit step and then drive recovery
in the same process.
"""

from __future__ import annotations

import os
import signal
import threading

__all__ = [
    "CATALOG",
    "CrashPointReached",
    "arm",
    "arm_from_env",
    "armed_site",
    "crashpoint",
    "hits",
    "reset",
    "sites",
]

# Exit status a killed worker reports to its parent: SIGKILL's 128+9.
KILL_EXIT_CODE = 137

# The authoritative kill-point registry: site -> (layer, what dies here).
# Docs (docs/durability.md) and the sweep harness both read this table;
# an instrumented call site MUST be declared here or the sweep will
# never exercise it. Sites are named <subsystem>.<sequence>.<step>.
CATALOG: dict[str, tuple[str, str]] = {
    # -- bus: file-backed partition logs + offset ledger --------------------
    "bus.file.append.pre": (
        "bus", "before record lines land in the active segment (send not acked)"),
    "bus.file.append.post": (
        "bus", "records appended + flushed, before send() returns the ack"),
    "bus.file.roll.mid": (
        "bus", "segment archived to its rolled name, before the .base sidecar commit"),
    "bus.file.offsets.pre": (
        "bus", "records consumed, before the offset-ledger atomic replace"),
    "bus.file.offsets.post": (
        "bus", "offset ledger replaced, before commit() returns"),
    # -- bus: shared-memory ring --------------------------------------------
    "bus.shm.publish.pre": (
        "bus", "frame bytes + CRC written into the ring, head not yet published"),
    "bus.shm.publish.post": (
        "bus", "head published past the new frame, before send() returns"),
    # -- storage: the atomic temp+rename commit helper ----------------------
    "storage.commit.pre": (
        "storage", "temp file written + fsynced, before the atomic rename"),
    "storage.commit.post": (
        "storage", "renamed over the target, before the parent-directory fsync"),
    # -- registry ------------------------------------------------------------
    "registry.champion.pre": (
        "registry", "before the CHAMPION pointer write begins"),
    "registry.publish.pre": (
        "registry", "generation durable in the registry, before the update-topic send"),
    "registry.publish.post": (
        "registry", "update-topic send acked, before publish_generation returns"),
    # -- batch layer: MLUpdate commit sequence ------------------------------
    "ml.promote.mid": (
        "batch", "candidate promoted into the model dir, manifest not yet written"),
    "ml.champion.pre": (
        "batch", "manifest written, CHAMPION pointer not yet moved"),
    "ml.publish.pre": (
        "batch", "CHAMPION moved, model not yet published on the update topic"),
    "ml.publish.post": (
        "batch", "model published on the update topic, before GC / return"),
    # -- batch layer: micro-batch persistence + input commit ----------------
    "batch.save.pre": (
        "batch", "generation complete, micro-batch not yet saved to the data dir"),
    "batch.commit.pre": (
        "batch", "micro-batch saved, input offsets not yet committed"),
    # -- speed layer ----------------------------------------------------------
    "speed.commit.pre": (
        "speed", "UP deltas published, input offsets not yet committed"),
    "speed.commit.post": (
        "speed", "input offsets committed, before batch bookkeeping"),
    # -- serving: MODEL-REF restage ------------------------------------------
    "serving.restage.mid": (
        "serving", "some artifact files copied into the staging temp dir"),
    "serving.restage.pre-commit": (
        "serving", "all artifacts staged, before the atomic rename into the cache"),
}

ENV_VAR = "ORYX_CRASHPOINT"


class CrashPointReached(BaseException):
    """Raised (instead of killing the process) when a site is armed with
    action="raise" — BaseException so no ``except Exception`` recovery
    path can accidentally swallow the simulated death."""

    def __init__(self, site: str) -> None:
        super().__init__(f"crashpoint {site} reached")
        self.site = site


_lock = threading.Lock()
_hits: dict[str, int] = {}
_armed_site: str | None = None
_armed_nth: int = 1
_armed_action: str = "kill"


def _parse_spec(spec: str) -> tuple[str, int]:
    site, sep, nth = spec.partition(":")
    if not site:
        raise ValueError(f"bad {ENV_VAR} spec {spec!r} (want <site>:<nth>)")
    return site, int(nth) if sep and nth else 1


def arm(site: str, nth: int = 1, action: str = "kill") -> None:
    """Arm one site in-process: the nth visit dies (action="kill") or
    raises CrashPointReached (action="raise", for unit tests)."""
    global _armed_site, _armed_nth, _armed_action
    if action not in ("kill", "raise"):
        raise ValueError(f"unknown crashpoint action {action!r}")
    with _lock:
        _armed_site, _armed_nth, _armed_action = site, max(1, int(nth)), action


def arm_from_env(environ=os.environ) -> str | None:
    """Arm from $ORYX_CRASHPOINT (no-op when unset). Returns the site."""
    spec = environ.get(ENV_VAR)
    if not spec:
        return None
    site, nth = _parse_spec(spec)
    arm(site, nth, action="kill")
    return site


def reset() -> None:
    """Disarm and forget hit counts (test isolation)."""
    global _armed_site
    with _lock:
        _armed_site = None
        _hits.clear()


def armed_site() -> str | None:
    return _armed_site


def hits(site: str) -> int:
    with _lock:
        return _hits.get(site, 0)


def sites(layer: str | None = None) -> list[str]:
    """Registered kill-point names, optionally filtered by layer."""
    return sorted(s for s, (lyr, _) in CATALOG.items() if layer is None or lyr == layer)


def _die() -> None:  # pragma: no cover - by design nothing after it runs
    try:
        os.kill(os.getpid(), signal.SIGKILL)
    finally:
        # SIGKILL cannot be handled, but cover exotic platforms anyway
        os._exit(KILL_EXIT_CODE)


def crashpoint(site: str) -> None:
    """Mark one step boundary of a commit sequence. No-op unless armed."""
    if _armed_site is None:  # fast path: production cost is this check
        return
    if site != _armed_site:
        return
    with _lock:
        if _armed_site != site:  # re-check under the lock (disarm race)
            return
        n = _hits.get(site, 0) + 1
        _hits[site] = n
        if n != _armed_nth:
            return
        action = _armed_action
    if action == "raise":
        raise CrashPointReached(site)
    _die()


# a worker spawned with ORYX_CRASHPOINT set is armed from birth
arm_from_env()
