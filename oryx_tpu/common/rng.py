"""Deterministic randomness manager.

Rebuild of the reference's RandomManager (framework/oryx-common/src/main/
java/com/cloudera/oryx/common/random/RandomManager.java:29-100): normal mode
hands out OS-entropy generators; test mode (`use_test_seed()`) makes every
generator in the process deterministic so all tests are reproducible. The
test seed can be overridden with $ORYX_TEST_SEED (reference: system property
`oryx.test.seed`, RandomManager.java:41).

TPU-side randomness uses `jax.random` keys derived from the same seed
stream, so host- and device-side draws are both deterministic under test.
"""

from __future__ import annotations

import os
import secrets
import threading

import numpy as np

_TEST_SEED_ENV = "ORYX_TEST_SEED"
_DEFAULT_TEST_SEED = 1234

_lock = threading.Lock()
_test_seed: int | None = None
_counter = 0


def use_test_seed() -> None:
    """Switch to deterministic seeding for ALL subsequent generators."""
    global _test_seed, _counter
    with _lock:
        _test_seed = int(os.environ.get(_TEST_SEED_ENV, _DEFAULT_TEST_SEED))
        _counter = 0


def clear_test_seed() -> None:
    global _test_seed
    with _lock:
        _test_seed = None


def in_test_mode() -> bool:
    return _test_seed is not None


def next_seed() -> int:
    """Next raw seed: deterministic sequence in test mode, OS entropy else."""
    global _counter
    with _lock:
        if _test_seed is not None:
            _counter += 1
            return _test_seed + _counter - 1
        return secrets.randbits(63)


def get_random(seed: int | None = None) -> np.random.Generator:
    """A host-side generator (NumPy PCG64)."""
    return np.random.default_rng(next_seed() if seed is None else seed)


def get_key(seed: int | None = None):
    """A fresh `jax.random` PRNG key (imported lazily to keep host-only
    callers free of a jax dependency)."""
    import jax

    return jax.random.key(next_seed() if seed is None else seed)
