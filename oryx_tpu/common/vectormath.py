"""Small host-side linear algebra: dot, norms, V^T V, and k x k solvers.

Rebuild of the reference's VectorMath (framework/oryx-common/src/main/java/
com/cloudera/oryx/common/math/VectorMath.java:27-110) and
LinearSystemSolver/Solver (.../math/LinearSystemSolver.java:28-70,
Solver.java:25-50): a pseudo-inverse solver over V^T V with a singularity
threshold of 1e-5, used on the ALS fold-in hot path in the speed and
serving layers. Device-side (batched, sharded) versions of these ops live
in oryx_tpu.ops; these NumPy forms serve host-side per-request math where
a device round-trip would cost more than the flop count.
"""

from __future__ import annotations

import numpy as np

SINGULARITY_THRESHOLD = 1.0e-5

__all__ = [
    "dot",
    "norm",
    "cosine_similarity",
    "transpose_times_self",
    "parse_vector",
    "random_vector_f",
    "Solver",
    "SingularMatrixSolverException",
    "get_solver",
]


class SingularMatrixSolverException(Exception):
    """Raised when V^T V is effectively singular (apparent rank deficiency).

    Mirrors SingularMatrixSolverException: carries the apparent rank so
    callers can log how degenerate the system is.
    """

    def __init__(self, apparent_rank: int, message: str = "") -> None:
        super().__init__(message or f"apparent rank {apparent_rank}")
        self.apparent_rank = apparent_rank


def dot(x: np.ndarray, y: np.ndarray) -> float:
    return float(np.dot(np.asarray(x, dtype=np.float64), np.asarray(y, dtype=np.float64)))


def norm(x: np.ndarray) -> float:
    return float(np.linalg.norm(np.asarray(x, dtype=np.float64)))


def cosine_similarity(x: np.ndarray, y: np.ndarray, norm_y: float | None = None) -> float:
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    ny = norm(y) if norm_y is None else norm_y
    nx = norm(x)
    if nx == 0.0 or ny == 0.0:
        return 0.0
    return float(np.dot(x, y) / (nx * ny))


def transpose_times_self(vectors) -> np.ndarray | None:
    """V^T V over an iterable (or dict id->vector) of float vectors.

    Mirrors VectorMath.transposeTimesSelf (VectorMath.java:84-103): returns
    None for an empty collection.
    """
    if hasattr(vectors, "values"):
        vectors = vectors.values()
    vt = None
    count = 0
    rows = []
    for v in vectors:
        rows.append(np.asarray(v, dtype=np.float64))
        count += 1
    if count == 0:
        return None
    m = np.stack(rows)
    vt = m.T @ m
    return vt


def parse_vector(tokens) -> np.ndarray:
    return np.asarray([float(t) for t in tokens], dtype=np.float64)


def random_vector_f(features: int, rng: np.random.Generator) -> np.ndarray:
    """Random unit-normal float32 vector (VectorMath.randomVectorF)."""
    return rng.standard_normal(features).astype(np.float32)


class Solver:
    """Solves Ax=b for a fixed symmetric A = V^T V via pinv-style QR.

    Mirrors Solver (math/Solver.java): the decomposition is done once and
    reused across many right-hand sides (the fold-in hot path,
    ALSSpeedModel.getXTXSolver / ALSServingModel caching).
    """

    def __init__(self, a: np.ndarray) -> None:
        a = np.asarray(a, dtype=np.float64)
        # QR-based rank check with the reference's singularity threshold
        # (LinearSystemSolver.java:31,35-52).
        _, r = np.linalg.qr(a)
        diag = np.abs(np.diag(r))
        max_diag = diag.max() if diag.size else 0.0
        if max_diag == 0.0:
            raise SingularMatrixSolverException(0, "all-zero matrix")
        apparent_rank = int(np.sum(diag > SINGULARITY_THRESHOLD * max_diag))
        if apparent_rank < a.shape[0]:
            raise SingularMatrixSolverException(
                apparent_rank,
                f"apparent rank {apparent_rank} < dimension {a.shape[0]}",
            )
        self._a = a
        # Cholesky is valid since A is SPD once rank-checked; fall back to
        # lstsq on numerical failure.
        try:
            self._chol = np.linalg.cholesky(a)
        except np.linalg.LinAlgError:
            self._chol = None

    @property
    def matrix(self) -> np.ndarray:
        """The decomposed A = V^T V (for batched solves elsewhere)."""
        return self._a

    def solve_d_to_d(self, b: np.ndarray) -> np.ndarray:
        b = np.asarray(b, dtype=np.float64)
        if self._chol is not None:
            y = np.linalg.solve(self._chol, b)
            return np.linalg.solve(self._chol.T, y)
        return np.linalg.lstsq(self._a, b, rcond=None)[0]

    def solve_f_to_f(self, b: np.ndarray) -> np.ndarray:
        return self.solve_d_to_d(np.asarray(b, dtype=np.float64)).astype(np.float32)


def get_solver(a: np.ndarray | None) -> Solver | None:
    """LinearSystemSolver.getSolver: None in, None out."""
    if a is None:
        return None
    return Solver(a)
