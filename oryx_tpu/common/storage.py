"""Pluggable storage: local filesystem or object store behind one URI API.

The reference persists historical data and models to HDFS and resolves
MODEL-REF messages from it (BatchUpdateFunction.java:103-130,
AppPMMLUtils.java:256); a multi-host TPU deployment needs the same —
a shared store all layers can reach. Paths without a scheme (or with
``file://``) use the local filesystem directly (fast path, atomic
temp+rename writes); any other scheme (``gs://``, ``s3://``,
``memory://`` for tests) routes through fsspec, whose per-blob writes
are atomic on object stores.

All functions take URI strings. Directory semantics are emulated on
object stores the usual way (prefixes); ``mkdirs`` is a no-op there.
"""

from __future__ import annotations

import contextlib
import gzip
import os
import shutil
import threading
from pathlib import Path
from typing import IO, Iterator

from oryx_tpu.common.crashpoints import crashpoint

__all__ = [
    "is_remote", "local_path", "open_read", "open_write", "open_gzip_read",
    "open_gzip_write", "exists", "list_names", "delete",
    "mkdirs", "size", "read_text", "write_text", "join",
    "upload_dir", "commit_bytes", "commit_text", "fsync_dir", "sweep_tmp",
]


def is_remote(uri: str | os.PathLike) -> bool:
    s = str(uri)
    return "://" in s and not s.startswith("file://")


def _local(uri: str | os.PathLike) -> Path:
    s = str(uri)
    return Path(s[len("file://"):] if s.startswith("file://") else s)


def local_path(uri: str | os.PathLike) -> Path:
    """Local filesystem Path for a non-remote URI (strips any file://
    scheme). Callers doing direct Path work (rename-based promotion)
    must use this instead of Path(uri), or a file:// prefix turns into
    a literal relative directory."""
    if is_remote(str(uri)):
        raise ValueError(f"not a local URI: {uri}")
    return _local(uri)


def _fs(uri: str):
    import fsspec

    fs, path = fsspec.core.url_to_fs(uri)
    return fs, path


def join(uri: str | os.PathLike, *parts: str) -> str:
    s = str(uri).rstrip("/")
    return "/".join([s, *[p.strip("/") for p in parts]])


@contextlib.contextmanager
def open_read(uri: str | os.PathLike, mode: str = "rb") -> Iterator[IO]:
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        with fs.open(path, mode) as f:
            yield f
    else:
        with open(_local(uri), mode, encoding="utf-8" if "b" not in mode else None) as f:
            yield f


TMP_MARKER = ".tmp-"


def _tmp_sibling(p: Path) -> Path:
    # tmp name must be unique PER WRITER: concurrent writers of the
    # same target sharing one tmp path race each other's atomic
    # replace (writer A's replace unlinks the tmp writer B is about
    # to replace -> FileNotFoundError; surfaced by concurrent
    # /model/rollback requests moving the CHAMPION pointer). A sibling
    # (never /tmp or tempfile.mkstemp) guarantees same-filesystem
    # rename: cross-device "renames" degrade to copy+unlink, which is
    # not atomic and can tear (ORX602).
    return p.parent / f".{p.name}{TMP_MARKER}{os.getpid()}-{threading.get_ident()}"


def fsync_dir(path: str | os.PathLike) -> None:
    """fsync a directory so a completed rename inside it is durable —
    without this the *entry* can vanish on power loss even though the
    file's bytes survived. Platforms that refuse O_RDONLY fsync on
    directories (some network filesystems) are skipped, not failed."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs without dir fsync support
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def open_write(uri: str | os.PathLike, mode: str = "wb") -> Iterator[IO]:
    """Atomic AND durable everywhere: local writes go through temp +
    fsync + rename + parent-dir fsync (a rename can survive a crash
    while its contents don't — fsync the temp file first — and a rename
    itself isn't durable until the directory entry is synced); remote
    writes go to a temp key that is moved into place only on success —
    fsspec finalizes a blob on close() even when the with-body raised,
    so writing the final key directly would commit truncated data."""
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        tmp = f"{path}{TMP_MARKER}{os.getpid()}"
        try:
            with fs.open(tmp, mode) as f:
                yield f
        except BaseException:
            with contextlib.suppress(Exception):
                fs.rm(tmp)
            raise
        crashpoint("storage.commit.pre")
        fs.mv(tmp, path)
        crashpoint("storage.commit.post")
    else:
        p = _local(uri)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = _tmp_sibling(p)
        try:
            with open(tmp, mode, encoding="utf-8" if "b" not in mode else None) as f:
                yield f
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            with contextlib.suppress(Exception):
                tmp.unlink()
            raise
        crashpoint("storage.commit.pre")
        tmp.replace(p)
        crashpoint("storage.commit.post")
        fsync_dir(p.parent)


def commit_bytes(path: str | os.PathLike, data: bytes) -> None:
    """THE recognized local commit helper (ORX601/ORX603): write a small
    state file — CHAMPION pointer, offset ledger, segment-base sidecar,
    topic meta — atomically and durably: sibling temp + fsync + rename +
    parent-dir fsync, with crashpoints at each step boundary. Callers
    that already hold a Path (filebus sidecars) use this instead of the
    URI-level write_text."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = _tmp_sibling(p)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    except BaseException:
        with contextlib.suppress(Exception):
            tmp.unlink()
        raise
    crashpoint("storage.commit.pre")
    tmp.replace(p)
    crashpoint("storage.commit.post")
    fsync_dir(p.parent)


def commit_text(path: str | os.PathLike, text: str) -> None:
    commit_bytes(path, text.encode("utf-8"))


def sweep_tmp(dir_uri: str | os.PathLike) -> int:
    """Remove stale writer temp litter (crashed mid-commit) directly
    under a directory: any ``.<name>.tmp-<pid>-...`` sibling left by
    open_write/commit_bytes. A temp file is only ever garbage once its
    writer is gone — renames happen in the writer's own lifetime — so
    sweeping at repair/open time is safe for files whose writer pid is
    dead (or foreign). Returns the number removed."""
    if is_remote(str(dir_uri)):
        return 0
    d = _local(dir_uri)
    if not d.is_dir():
        return 0
    removed = 0
    for p in d.iterdir():
        if not p.is_file() or TMP_MARKER not in p.name or not p.name.startswith("."):
            continue
        pid_part = p.name.split(TMP_MARKER, 1)[1].split("-", 1)[0]
        try:
            pid = int(pid_part)
        except ValueError:
            continue
        if pid != os.getpid() and not _pid_alive(pid):
            with contextlib.suppress(OSError):
                p.unlink()
                removed += 1
    return removed


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - pid exists, other user
        return True
    return True


@contextlib.contextmanager
def open_gzip_read(uri: str | os.PathLike) -> Iterator[IO]:
    with open_read(uri, "rb") as raw, gzip.open(raw, "rt", encoding="utf-8") as g:
        yield g


@contextlib.contextmanager
def open_gzip_write(uri: str | os.PathLike) -> Iterator[IO]:
    with open_write(uri, "wb") as raw, gzip.open(raw, "wt", encoding="utf-8") as g:
        yield g


def exists(uri: str | os.PathLike) -> bool:
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        return fs.exists(path)
    return _local(uri).exists()


def list_names(uri: str | os.PathLike) -> list[str]:
    """Entry names (final path components) directly under a directory /
    prefix; empty when it doesn't exist."""
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        if not fs.exists(path):
            return []
        return sorted({p.rstrip("/").rsplit("/", 1)[-1] for p in fs.ls(path, detail=False)})
    d = _local(uri)
    if not d.is_dir():
        return []
    return sorted(p.name for p in d.iterdir())


def delete(uri: str | os.PathLike, recursive: bool = False) -> None:
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        if fs.exists(path):
            fs.rm(path, recursive=recursive)
        return
    p = _local(uri)
    if p.is_dir():
        if recursive:
            shutil.rmtree(p, ignore_errors=True)
        else:
            p.rmdir()
    else:
        p.unlink(missing_ok=True)


def mkdirs(uri: str | os.PathLike) -> None:
    if is_remote(str(uri)):
        return  # object stores have no directories
    _local(uri).mkdir(parents=True, exist_ok=True)


def size(uri: str | os.PathLike) -> int:
    if is_remote(str(uri)):
        fs, path = _fs(str(uri))
        return fs.size(path)
    return _local(uri).stat().st_size


def read_text(uri: str | os.PathLike) -> str:
    with open_read(uri, "rb") as f:
        return f.read().decode("utf-8")


def write_text(uri: str | os.PathLike, text: str) -> None:
    with open_write(uri, "wb") as f:
        f.write(text.encode("utf-8"))


def upload_dir(local_dir: str | Path, dst_uri: str) -> None:
    """Recursively copy a local directory tree to a destination URI
    (model-candidate promotion to an object store). The PMML file
    (model.pmml) is uploaded LAST so a consumer that sees it can rely on
    the sibling artifacts being complete."""
    root = Path(local_dir)
    files = [p for p in root.rglob("*") if p.is_file()]
    files.sort(key=lambda p: (p.name == "model.pmml", str(p)))
    for p in files:
        rel = p.relative_to(root)
        target = join(dst_uri, *rel.parts)
        with open(p, "rb") as f, open_write(target, "wb") as out:
            shutil.copyfileobj(f, out)
