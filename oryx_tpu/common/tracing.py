"""Lightweight always-on sampled distributed tracer (Dapper-style).

Answers the two questions the flat `/metrics` registry cannot: "where did
this request's 9 ms go?" and "how stale is what serving returns?". The
design follows Dapper / W3C Trace Context:

- A ``TraceContext`` is (trace id, span id, sampled flag), serialized as
  the W3C ``traceparent`` string ``00-<32hex>-<16hex>-<2hex>``. HTTP
  clients send it as a ``traceparent`` header; bus publishers carry it in
  a reserved control record (key ``@trc``) prepended to the batch, so the
  same context flows through every transport (inproc / file / net / shm
  text frames) without any transport-specific framing. The shm columnar
  path uses a dedicated zero-count trace frame (blockcodec KIND_TRACE).
- Sampling is parent-based: an incoming sampled context is always
  honored; new roots sample at ``oryx.tracing.sample-rate``. Unsampled
  work records nothing and emits no bus header — the hot columnar paths
  stay byte-identical to the untraced build.
- Completed spans land in a bounded in-process ring buffer (oldest
  evicted first) with parent links, exported as Chrome-trace JSON
  (``GET /trace`` on the serving layer, ``cli trace``) or as a raw span
  list for tests.

The control-record message is ``<traceparent or "-">[;ts=<ms>]`` where ``ts``
is the origin ingest timestamp (epoch ms): speed publishes stamp the
micro-batch's earliest event-ingest time, model publishes stamp publish
time — consumers derive the freshness histogram (event-ingest to
servable-visibility) and the per-generation propagation skew from it.

Config: ``oryx.tracing.enabled`` / ``oryx.tracing.sample-rate`` /
``oryx.tracing.ring-capacity``; env overrides ``ORYX_TRACING`` (0/1) and
``ORYX_TRACING_SAMPLE_RATE`` let the bench toggle tracing in
subprocesses without threading config through every tool.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass

# Reserved bus record key for the trace control record. Consumers strip
# it from delivered blocks and surface it as ``block.trace``.
TRACE_KEY = "@trc"

_DEFAULT_SAMPLE_RATE = 0.01
_DEFAULT_RING_CAPACITY = 4096


def _env_enabled(default: bool) -> bool:
    raw = os.environ.get("ORYX_TRACING")
    if raw is None:
        return default
    return raw.strip() not in ("0", "false", "no", "off", "")


def _env_sample_rate(default: float) -> float:
    raw = os.environ.get("ORYX_TRACING_SAMPLE_RATE")
    if raw is None:
        return default
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return default


_lock = threading.Lock()
_enabled: bool = _env_enabled(True)
_sample_rate: float = _env_sample_rate(_DEFAULT_SAMPLE_RATE)
_ring: deque = deque(maxlen=_DEFAULT_RING_CAPACITY)
_recorded: int = 0
# private RNG: sampling must not consume draws from the global `random`
# sequence tests seed deterministically
_rng = random.Random()
_local = threading.local()


@dataclass(frozen=True)
class TraceContext:
    """W3C-style trace context: ids are lowercase hex strings."""

    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    def child(self) -> "TraceContext":
        """Same trace, fresh span id (a redelivered duplicate gets the
        same trace id but a new span per delivery)."""
        return TraceContext(self.trace_id, _new_span_id(), self.sampled)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(value: str | None) -> TraceContext | None:
    """Parse a ``traceparent`` header; None when malformed (malformed
    context never poisons the request — it just starts untraced)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 or len(flags) != 2:
        return None
    try:
        int(version, 16)
        int(flags, 16)
        bad = int(trace_id, 16) == 0 or int(span_id, 16) == 0
    except ValueError:
        return None
    if version == "ff" or bad:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), bool(int(flags, 16) & 1))


# -- configuration -----------------------------------------------------------


def configure(
    enabled: bool | None = None,
    sample_rate: float | None = None,
    ring_capacity: int | None = None,
) -> None:
    global _enabled, _sample_rate, _ring
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if sample_rate is not None:
            _sample_rate = min(1.0, max(0.0, float(sample_rate)))
        if ring_capacity is not None and ring_capacity != _ring.maxlen:
            _ring = deque(_ring, maxlen=max(1, int(ring_capacity)))


def configure_from(config) -> None:
    """Apply ``oryx.tracing.*``; env vars win (bench subprocess toggle)."""
    enabled = config.get("oryx.tracing.enabled", True)
    rate = config.get("oryx.tracing.sample-rate", _DEFAULT_SAMPLE_RATE)
    cap = config.get("oryx.tracing.ring-capacity", _DEFAULT_RING_CAPACITY)
    configure(
        enabled=_env_enabled(bool(enabled)),
        sample_rate=_env_sample_rate(float(rate)),
        ring_capacity=int(cap),
    )


def enabled() -> bool:
    return _enabled


def sample_rate() -> float:
    return _sample_rate


def reset() -> None:
    """Test hook: clear the ring and ambient context, restore defaults."""
    global _enabled, _sample_rate, _ring, _recorded
    with _lock:
        _enabled = _env_enabled(True)
        _sample_rate = _env_sample_rate(_DEFAULT_SAMPLE_RATE)
        _ring = deque(maxlen=_DEFAULT_RING_CAPACITY)
        _recorded = 0
    _local.ctx = None


# -- ambient context ---------------------------------------------------------


def current() -> TraceContext | None:
    return getattr(_local, "ctx", None)


@contextmanager
def use(ctx: TraceContext | None):
    """Set the thread's ambient context for the body."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev


def sample_root() -> TraceContext | None:
    """Roll the sampling dice for a new root; None when unsampled (the
    caller then records nothing and emits no headers)."""
    if not _enabled or _sample_rate <= 0.0:
        return None
    if _sample_rate < 1.0 and _rng.random() >= _sample_rate:
        return None
    return TraceContext(_new_trace_id(), _new_span_id(), True)


def continue_from(ctx_or_traceparent) -> TraceContext | None:
    """Child context continuing an incoming trace (parent-based sampling:
    a sampled parent is always honored). Accepts a TraceContext or a raw
    traceparent string; None when absent/unsampled/disabled."""
    if not _enabled:
        return None
    ctx = ctx_or_traceparent
    if isinstance(ctx, str):
        ctx = parse_traceparent(ctx)
    if ctx is None or not ctx.sampled:
        return None
    return ctx.child()


# -- span recording ----------------------------------------------------------


class Span:
    """Handle yielded by ``span()``; ``set()`` attaches attributes."""

    __slots__ = ("name", "ctx", "parent_id", "attrs", "_t0", "_wall0")

    def __init__(self, name: str, ctx: TraceContext, parent_id: str | None, attrs):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self._t0 = time.perf_counter()
        self._wall0 = time.time()

    def set(self, key: str, value) -> None:
        self.attrs[key] = value


class _NullSpan:
    __slots__ = ()
    ctx = None

    def set(self, key: str, value) -> None:
        pass


_NULL_SPAN = _NullSpan()


def record_span(
    name: str,
    ctx: TraceContext,
    parent_id: str | None,
    wall_start: float,
    duration: float,
    attrs: dict | None = None,
) -> None:
    """Append one completed span to the ring (explicit-timestamp form,
    for call sites that measured the interval themselves, e.g. the
    batcher's queue-wait)."""
    global _recorded
    if not _enabled or not ctx.sampled:
        return
    entry = {
        "name": name,
        "trace": ctx.trace_id,
        "span": ctx.span_id,
        "parent": parent_id,
        "ts": wall_start,
        "dur": max(0.0, duration),
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "attrs": dict(attrs) if attrs else {},
    }
    with _lock:
        _ring.append(entry)
        _recorded += 1


@contextmanager
def span(
    name: str,
    ctx: TraceContext | None = None,
    attrs: dict | None = None,
    root: bool = False,
):
    """Record a span around the body. ``ctx`` (or the ambient context)
    is the PARENT; the body runs with a fresh child context ambient so
    nested spans and bus headers link to this span. With ``root=True``
    and no traced parent, the sampling dice are rolled and (if sampled)
    the span becomes a trace root with no parent link. No-op (null span)
    when untraced."""
    parent = ctx if ctx is not None else current()
    if not _enabled or parent is None or not parent.sampled:
        if root:
            rc = sample_root()
            if rc is not None:
                sp = Span(name, rc, None, attrs)
                prev = getattr(_local, "ctx", None)
                _local.ctx = rc
                try:
                    yield sp
                finally:
                    _local.ctx = prev
                    record_span(
                        name, rc, None, sp._wall0,
                        time.perf_counter() - sp._t0, sp.attrs,
                    )
                return
        yield _NULL_SPAN
        return
    child = parent.child()
    sp = Span(name, child, parent.span_id, attrs)
    prev = getattr(_local, "ctx", None)
    _local.ctx = child
    try:
        yield sp
    finally:
        _local.ctx = prev
        record_span(
            name, child, parent.span_id, sp._wall0, time.perf_counter() - sp._t0, sp.attrs
        )


def spans(trace_id: str | None = None) -> list[dict]:
    """Snapshot of recorded spans (optionally one trace), oldest first."""
    with _lock:
        out = list(_ring)
    if trace_id is not None:
        out = [s for s in out if s["trace"] == trace_id]
    return out


def stats() -> dict:
    with _lock:
        return {
            "enabled": _enabled,
            "sample_rate": _sample_rate,
            "ring_capacity": _ring.maxlen,
            "buffered": len(_ring),
            "recorded": _recorded,
        }


def export_chrome(trace_id: str | None = None) -> dict:
    """Chrome-trace/Perfetto JSON (load via chrome://tracing or
    ui.perfetto.dev). Durations are complete events (ph "X")."""
    events = []
    for s in spans(trace_id):
        events.append(
            {
                "name": s["name"],
                "ph": "X",
                "ts": s["ts"] * 1e6,
                "dur": s["dur"] * 1e6,
                "pid": s["pid"],
                "tid": s["tid"],
                "args": {
                    "trace": s["trace"],
                    "span": s["span"],
                    "parent": s["parent"],
                    **s["attrs"],
                },
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms", **stats()}


# -- bus control-record carriage ---------------------------------------------


def header_record(
    ctx: TraceContext | None = None, ingest_ms: int | None = None
) -> tuple[str, str] | None:
    """The ``@trc`` control record to prepend to a bus batch, or None
    when there is nothing to carry (untraced and no origin timestamp) —
    the default-off case that keeps hot paths header-free."""
    if not _enabled:
        return None
    if ctx is None:
        ctx = current()
    traced = ctx is not None and ctx.sampled
    if not traced and ingest_ms is None:
        return None
    msg = ctx.traceparent() if traced else "-"
    if ingest_ms is not None:
        msg += f";ts={int(ingest_ms)}"
    return (TRACE_KEY, msg)


def with_header(records, ctx: TraceContext | None = None, ingest_ms: int | None = None):
    """(records-with-optional-header, extra) — ``extra`` is how many
    control records were prepended (0 or 1) so publishers can report
    caller-visible counts: ``sent = producer.send_many(recs) - extra``."""
    header = header_record(ctx, ingest_ms)
    out = records if isinstance(records, list) else list(records)
    if header is None:
        return out, 0
    return [header, *out], 1


@dataclass(frozen=True)
class BlockTrace:
    """Parsed ``@trc`` message as surfaced on ``block.trace``."""

    ctx: TraceContext | None
    ingest_ms: int | None


def parse_header(message: str | bytes | None) -> BlockTrace | None:
    """Parse a ``@trc`` control-record message; None when absent."""
    if message is None:
        return None
    if isinstance(message, bytes):
        message = message.decode("utf-8", "replace")
    head, _, rest = message.partition(";")
    ctx = None if head in ("", "-") else parse_traceparent(head)
    ingest = None
    for part in rest.split(";"):
        if part.startswith("ts="):
            try:
                ingest = int(part[3:])
            except ValueError:
                ingest = None
    return BlockTrace(ctx, ingest)
