"""Torn-write corruption injector for recovery drills.

The crashpoint catalog (``common/crashpoints``) kills processes at
chosen commit points; this module fabricates the on-disk aftermath
directly — a torn final record, a truncated segment, a CRC-garbled shm
frame, stale commit-temp litter, an unreadable CHAMPION pointer — so
chaos tests and the fleet crash campaign can drive every repair path
(``FileBroker.repair``, ``ShmBroker.repair``, ``RegistryStore.fsck``)
without having to catch a real writer at exactly the wrong instant.

Primitives operate on raw paths; the ``*_filebus`` / ``*_shm`` /
``*_registry`` helpers locate the right file from broker/store layout.
Every injector returns a short description of the damage it did, so a
drill's report can say what was broken as well as what was repaired.

Test/ops-only: nothing in the serving or pipeline path imports this.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

__all__ = [
    "tear_tail",
    "truncate_to",
    "append_garbage",
    "flip_byte",
    "litter_tmp",
    "tear_filebus_partition",
    "garble_filebus_ledger",
    "garble_shm_frame",
    "garble_shm_header",
    "garble_champion",
    "amputate_generation",
    "litter_promote",
    "point_champion_at",
]


# -- raw-path primitives -----------------------------------------------------


def tear_tail(path: str | Path, cut: int = 3) -> str:
    """Cut ``cut`` bytes off the end of a file — the classic torn append:
    the final record loses its newline and part of its payload."""
    p = Path(path)
    size = p.stat().st_size
    keep = max(0, size - cut)
    with open(p, "rb+") as f:
        f.truncate(keep)
    return f"tore {size - keep} byte(s) off {p.name} (now {keep}B)"


def truncate_to(path: str | Path, nbytes: int) -> str:
    """Truncate a file to an absolute byte length (mid-record when the
    caller picks an offset inside one)."""
    p = Path(path)
    with open(p, "rb+") as f:
        f.truncate(nbytes)
    return f"truncated {p.name} to {nbytes}B"


def append_garbage(path: str | Path, data: bytes = b"\x00\xffgarbage") -> str:
    """Append junk with no record framing — a torn write that made it to
    disk but never completed."""
    p = Path(path)
    with open(p, "ab") as f:
        f.write(data)
    return f"appended {len(data)}B of garbage to {p.name}"


def flip_byte(path: str | Path, offset: int, count: int = 1) -> str:
    """XOR ``count`` byte(s) at ``offset`` — bit rot / a torn sector."""
    p = Path(path)
    with open(p, "rb+") as f:
        f.seek(offset)
        original = f.read(count)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in original))
    return f"flipped {count} byte(s) at offset {offset} in {p.name}"


def litter_tmp(directory: str | Path, name: str = "STATE", pid: int = 999_999_999) -> str:
    """Drop a stale commit-side temp file (``.{name}.tmp-<pid>-0``) as a
    dead writer would — repair must sweep it, readers must never see it."""
    d = Path(directory)
    p = d / f".{name}.tmp-{pid}-0"
    p.write_bytes(b"half-written state from a dead writer")
    return f"littered {p.name} in {d}"


# -- filebus -----------------------------------------------------------------


def tear_filebus_partition(root: str | Path, topic: str, partition: int = 0, cut: int = 7) -> str:
    """Tear the active segment's tail for a filebus topic partition."""
    log_path = Path(root) / topic / f"partition-{partition}.log"
    return "filebus: " + tear_tail(log_path, cut=cut)


def garble_filebus_ledger(root: str | Path, group: str) -> str:
    """Overwrite a consumer group's offset ledger with non-JSON junk —
    repair must quarantine it so the group replays from earliest."""
    from oryx_tpu.bus import filebus

    ledger = Path(root) / filebus._OFFSETS_DIR / f"{group}.json"
    ledger.parent.mkdir(parents=True, exist_ok=True)
    ledger.write_bytes(b"{torn mid-writ")
    return f"filebus: garbled offset ledger {ledger.name}"


# -- shm ring ----------------------------------------------------------------


def garble_shm_frame(ring_path: str | Path) -> str:
    """Flip a payload byte inside the newest unconsumed data frame so its
    CRC no longer matches — fsck must roll the head back to the last
    intact frontier. Raises ValueError when the ring holds no data frame.

    Walks the frame chain exactly as fsck does (the CRC covers the
    payload, not the 8-byte alignment padding, so a blind poke at the
    frame tail could land on padding and change nothing)."""
    from oryx_tpu.bus import blockcodec, shmbus

    p = Path(ring_path)
    with open(p, "rb") as f:
        data = f.read()
    head = shmbus._U64.unpack_from(data, shmbus._OFF_HEAD)[0]
    pos = shmbus._U64.unpack_from(data, shmbus._OFF_TAIL)[0]
    ring_bytes = shmbus._U64.unpack_from(data, shmbus._OFF_RING_BYTES)[0]
    target = None
    while pos < head:
        rem = ring_bytes - pos % ring_bytes
        if rem < blockcodec.HEADER_BYTES:
            pos += rem
            continue
        off = shmbus._HEADER_PAGE + pos % ring_bytes
        magic, kind, _flags, _seq, _count, length, _crc = blockcodec.HEADER.unpack_from(
            data, off
        )
        wire = blockcodec.HEADER_BYTES + blockcodec.pad8(length)
        if magic != blockcodec.MAGIC or wire > rem or pos + wire > head:
            break
        if kind != blockcodec.KIND_PAD and length > 0:
            target = off + blockcodec.HEADER_BYTES  # first payload byte
        pos += wire
    if target is None:
        raise ValueError(f"shm ring {p.name} holds no data frame; nothing to garble")
    return "shm: " + flip_byte(p, target)


def garble_shm_header(ring_path: str | Path) -> str:
    """Write an impossible head/tail geometry (tail > head) into the ring
    header — fsck must refuse to trust it and reset the ring empty."""
    from oryx_tpu.bus import shmbus

    p = Path(ring_path)
    with open(p, "rb+") as f:
        f.seek(shmbus._OFF_HEAD)
        f.write(shmbus._U64.pack(1))
        f.seek(shmbus._OFF_TAIL)
        f.write(shmbus._U64.pack(2))
    return f"shm: wrote insane head/tail geometry into {p.name}"


# -- registry ----------------------------------------------------------------


def garble_champion(model_dir: str | Path) -> str:
    """Overwrite the CHAMPION pointer with truncated JSON — fsck must
    quarantine it and fall back to the newest intact generation."""
    p = Path(model_dir) / "CHAMPION"
    p.write_text('{"generation_id": "12')
    return "registry: garbled CHAMPION pointer"


def amputate_generation(model_dir: str | Path, generation_id: str) -> str:
    """Delete a generation's model.pmml, leaving the half-written dir a
    promote that died mid-copy would — fsck must quarantine it."""
    p = Path(model_dir) / str(generation_id) / "model.pmml"
    os.unlink(p)
    return f"registry: amputated model.pmml from generation {generation_id}"


def litter_promote(model_dir: str | Path, generation_id: str = "99999", pid: int = 999_999_999) -> str:
    """Strand a dead promoter's ``.promote-<gen>-<pid>`` staging dir."""
    d = Path(model_dir) / f".promote-{generation_id}-{pid}"
    d.mkdir(parents=True, exist_ok=True)
    (d / "model.pmml").write_text("<torn")
    return f"registry: stranded promote litter {d.name}"


def point_champion_at(model_dir: str | Path, generation_id: str) -> str:
    """Point CHAMPION at an arbitrary (possibly nonexistent) generation —
    fsck must reset it to the newest intact one."""
    p = Path(model_dir) / "CHAMPION"
    p.write_text(json.dumps({"generation_id": str(generation_id), "updated_at_ms": 0}))
    return f"registry: pointed CHAMPION at {generation_id}"
