"""Step/request metrics: counters, gauges, latency histograms.

The reference has no metrics registry at all — observability is the Spark
web UI plus log lines every 10k updates (SURVEY.md §5: "Rebuild should
exceed this (step metrics, eval metrics, serving QPS/latency
histograms)"). This module is that exceedance: a small thread-safe
registry the layers report into, exposed by the serving layer at
/metrics as JSON.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "registry", "timed"]


class Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self._value}


class Gauge:
    def __init__(self) -> None:
        self._value: float | None = None

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float | None:
        return self._value

    def snapshot(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Log-bucketed histogram for latencies/durations in seconds.

    Buckets are powers of `base` from `start` (default: 1 µs up through
    ~2 min); quantiles are estimated from bucket boundaries — plenty for
    QPS/latency dashboards and assertions in tests.
    """

    def __init__(self, start: float = 1e-6, base: float = 2.0, count: int = 28) -> None:
        self._lock = threading.Lock()
        self._bounds = [start * base**i for i in range(count)]
        self._buckets = [0] * (count + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = 0.0

    def observe(self, value: float) -> None:
        idx = 0
        while idx < len(self._bounds) and value > self._bounds[idx]:
            idx += 1
        with self._lock:
            self._buckets[idx] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation."""
        with self._lock:
            if not self._count:
                return 0.0
            target = q * self._count
            seen = 0
            for i, c in enumerate(self._buckets):
                seen += c
                if seen >= target:
                    return self._bounds[i] if i < len(self._bounds) else self._max
            return self._max

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            if not self._count:
                return {"type": "histogram", "count": 0}
        return {
            "type": "histogram",
            "count": self._count,
            "mean": self.mean,
            "min": self._min,
            "max": self._max,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} is {type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


registry = MetricsRegistry()
"""Process-global default registry (each layer is its own process)."""


class timed:
    """Context manager observing elapsed seconds into a histogram:

    with timed(registry.histogram("serving.request.seconds")): ...
    """

    def __init__(self, histogram: Histogram) -> None:
        self._h = histogram

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False
