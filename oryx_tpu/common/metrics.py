"""Step/request metrics: counters, gauges, latency histograms.

The reference has no metrics registry at all — observability is the Spark
web UI plus log lines every 10k updates (SURVEY.md §5: "Rebuild should
exceed this (step metrics, eval metrics, serving QPS/latency
histograms)"). This module is that exceedance: a small thread-safe
registry the layers report into, exposed by the serving layer at
/metrics as JSON.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PROMETHEUS_CONTENT_TYPE",
    "SLOWindow",
    "registry",
    "render_prometheus",
    "timed",
]


class Counter:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self._value}


class Gauge:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value: float | None = None

    def set(self, value: float) -> None:
        # a bare float store is atomic in CPython, but `set` must stay
        # safe if a gauge ever grows read-modify-write semantics; the
        # uncontended lock costs ~100ns on a path that is never hot
        with self._lock:
            self._value = value

    @property
    def value(self) -> float | None:
        with self._lock:
            return self._value

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {"type": "gauge", "value": self._value}


class Histogram:
    """Log-bucketed histogram for latencies/durations in seconds.

    Buckets are powers of `base` from `start` (default: 1 µs up through
    ~2 min); quantiles are estimated from bucket boundaries — plenty for
    QPS/latency dashboards and assertions in tests.
    """

    def __init__(self, start: float = 1e-6, base: float = 2.0, count: int = 28) -> None:
        self._lock = threading.Lock()
        self._bounds = [start * base**i for i in range(count)]
        self._buckets = [0] * (count + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = 0.0

    def observe(self, value: float) -> None:
        idx = 0
        while idx < len(self._bounds) and value > self._bounds[idx]:
            idx += 1
        with self._lock:
            self._buckets[idx] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    def merge_buckets(self, buckets, total_sum: float) -> None:
        """Fold pre-bucketed observations in (native-front stats drain).

        ``buckets`` must use THIS histogram's bucketing (the C++ side
        mirrors the 1e-6·2^i bounds and the same upper-bound-inclusive
        index rule); ``total_sum`` is the sum of the raw values in
        seconds. min/max are approximated by the populated bucket
        bounds — exact raw values never crossed the drain."""
        n = sum(buckets)
        if n == 0:
            return
        with self._lock:
            for i, c in enumerate(buckets):
                if i < len(self._buckets):
                    self._buckets[i] += c
                else:
                    self._buckets[-1] += c
            self._sum += total_sum
            self._count += n
            lo = next(i for i, c in enumerate(buckets) if c)
            hi = max(i for i, c in enumerate(buckets) if c)
            self._min = min(self._min, self._bounds[lo] if lo < len(self._bounds) else self._bounds[-1])
            self._max = max(self._max, self._bounds[hi] if hi < len(self._bounds) else self._bounds[-1])

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def _quantile_locked(self, q: float) -> float:
        target = q * self._count
        seen = 0
        for i, c in enumerate(self._buckets):
            seen += c
            if seen >= target:
                return self._bounds[i] if i < len(self._bounds) else self._max
        return self._max

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation."""
        with self._lock:
            if not self._count:
                return 0.0
            return self._quantile_locked(q)

    def snapshot(self) -> dict[str, Any]:
        # the whole snapshot is taken under ONE lock acquisition so a
        # concurrent observe() can never yield a torn view (e.g. a count
        # that doesn't match the bucket sum, or a min/max from a later
        # observation than the count reflects)
        with self._lock:
            if not self._count:
                return {"type": "histogram", "count": 0}
            cumulative = []
            seen = 0
            for i, c in enumerate(self._buckets):
                seen += c
                le = self._bounds[i] if i < len(self._bounds) else math.inf
                cumulative.append((le, seen))
            return {
                "type": "histogram",
                "count": self._count,
                "mean": self._sum / self._count,
                "min": self._min,
                "max": self._max,
                "p50": self._quantile_locked(0.50),
                "p90": self._quantile_locked(0.90),
                "p99": self._quantile_locked(0.99),
                "sum": self._sum,
                # cumulative (le, count) pairs, Prometheus-style, ending
                # with the +Inf bucket == count
                "buckets": cumulative,
            }


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} is {type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


class SLOWindow:
    """Sliding-window SLO accounting with burn rates (Google SRE workbook
    multi-window style): record per-request (ok, latency) events, then ask
    for the error rate, a latency quantile, or a *burn rate* — the ratio
    of the observed bad fraction to the fraction the SLO budgets — over
    any trailing window up to `horizon_s`.

    A burn rate of 1.0 consumes the error budget exactly as fast as the
    SLO allows; sustained > 1.0 means the budget exhausts early (14.4x
    over 1h burns a 30-day 99.9% budget in ~2 days — the classic paging
    threshold). The open-loop traffic harness asserts burn rates over
    short windows as first-class test outcomes (docs/traffic-harness.md).

    Thread-safe; `clock` is injectable for deterministic tests.
    """

    def __init__(self, horizon_s: float = 600.0, clock=time.monotonic) -> None:
        self._horizon = horizon_s
        self._clock = clock
        self._lock = threading.Lock()
        # (t, ok, latency_s) — appended monotonically, pruned from the left
        self._events: deque[tuple[float, bool, float]] = deque()

    def record(self, ok: bool, latency_s: float, now: float | None = None) -> None:
        t = self._clock() if now is None else now
        with self._lock:
            self._events.append((t, bool(ok), float(latency_s)))
            cutoff = t - self._horizon
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()

    def _window(self, window_s: float, now: float | None) -> list[tuple[float, bool, float]]:
        t = self._clock() if now is None else now
        cutoff = t - window_s
        with self._lock:
            return [e for e in self._events if e[0] >= cutoff]

    def count(self, window_s: float, now: float | None = None) -> int:
        return len(self._window(window_s, now))

    def error_rate(self, window_s: float, now: float | None = None) -> float:
        """Fraction of requests in the window that failed (0.0 when empty)."""
        ev = self._window(window_s, now)
        if not ev:
            return 0.0
        return sum(1 for _, ok, _ in ev if not ok) / len(ev)

    def error_burn_rate(
        self, window_s: float, slo_error_rate: float, now: float | None = None
    ) -> float:
        """observed error fraction / budgeted error fraction over the window."""
        if slo_error_rate <= 0.0:
            # a zero-error SLO: any failure is an infinite burn
            return math.inf if self.error_rate(window_s, now) > 0.0 else 0.0
        return self.error_rate(window_s, now) / slo_error_rate

    def latency_quantile(self, q: float, window_s: float, now: float | None = None) -> float:
        """Latency quantile over the window's requests (0.0 when empty)."""
        lats = sorted(lat for _, _, lat in self._window(window_s, now))
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(q * len(lats)))]

    def latency_burn_rate(
        self,
        window_s: float,
        threshold_s: float,
        slo_violation_rate: float,
        now: float | None = None,
    ) -> float:
        """Burn rate of a latency SLO of the form "no more than
        `slo_violation_rate` of requests slower than `threshold_s`"
        (e.g. p99 <= 50 ms is threshold_s=0.05, slo_violation_rate=0.01)."""
        ev = self._window(window_s, now)
        if not ev:
            return 0.0
        slow = sum(1 for _, _, lat in ev if lat > threshold_s) / len(ev)
        if slo_violation_rate <= 0.0:
            return math.inf if slow > 0.0 else 0.0
        return slow / slo_violation_rate


registry = MetricsRegistry()
"""Process-global default registry (each layer is its own process)."""


PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PROM_BAD_CHARS = None  # compiled lazily; most processes never render


def _prom_name(name: str) -> str:
    global _PROM_BAD_CHARS
    if _PROM_BAD_CHARS is None:
        import re

        _PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
    out = _PROM_BAD_CHARS.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_num(v) -> str:
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def render_prometheus(snapshot: dict[str, dict[str, Any]]) -> str:
    """A registry snapshot as Prometheus text exposition format 0.0.4,
    for standard scrapers (`/metrics` content-negotiates this alongside
    the JSON form). Dotted names map to underscored ones; histograms emit
    cumulative `_bucket{le=...}` series plus `_sum` / `_count`; unset
    gauges are omitted. Unknown entry shapes are skipped, so callers can
    merge extra JSON-only context into the dict without breaking
    scrapers."""
    lines: list[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        if not isinstance(entry, dict):
            continue
        kind = entry.get("type")
        pname = _prom_name(name)
        if kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_prom_num(entry.get('value') or 0.0)}")
        elif kind == "gauge":
            value = entry.get("value")
            if value is None:
                continue
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_prom_num(value)}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            buckets = entry.get("buckets") or []
            for le, cum in buckets:
                le_s = "+Inf" if math.isinf(float(le)) else _prom_num(le)
                lines.append(f'{pname}_bucket{{le="{le_s}"}} {cum}')
            if not buckets:  # empty histogram still needs its +Inf bucket
                lines.append(f'{pname}_bucket{{le="+Inf"}} 0')
            lines.append(f"{pname}_sum {_prom_num(entry.get('sum') or 0.0)}")
            lines.append(f"{pname}_count {entry.get('count') or 0}")
    return "\n".join(lines) + "\n"


class timed:
    """Context manager observing elapsed seconds into a histogram:

    with timed(registry.histogram("serving.request.seconds")): ...
    """

    def __init__(self, histogram: Histogram) -> None:
        self._h = histogram

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False
