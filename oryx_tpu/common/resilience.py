"""Resilience primitives: retry/backoff, deadlines, circuit breaking,
and supervised threads.

The reference leans on Kafka/Spark for its recovery story (replay-from-zero
on the update topic, SpeedLayer.java:107-121, and Spark task retry). The
rebuild owns its transport and layer runtimes, so it owns the failure
handling too. This module is the one place that policy lives:

- :class:`RetryPolicy` — bounded exponential backoff with deterministic
  jitter (seeded through :mod:`oryx_tpu.common.rng`, so chaos tests
  replay exactly), loadable from ``oryx.*.retry.*`` config blocks.
- :class:`Deadline` — a monotonic time budget shared across retries.
- :class:`CircuitBreaker` — closed/open/half-open, for dependencies that
  fail fast rather than fail slow.
- :class:`SupervisedThread` — a restart-with-backoff wrapper for the
  long-lived consume/batch threads in the lambda layers: restart on
  failure, give up after the policy is exhausted, and report health.

Everything emits into :mod:`oryx_tpu.common.metrics` so operators can see
retries, breaker state, and supervisor restarts at /metrics.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Iterator

from oryx_tpu.common import metrics, rng

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "Deadline",
    "DeadlineExceeded",
    "RetryError",
    "RetryPolicy",
    "SupervisedThread",
]

log = logging.getLogger(__name__)


class RetryError(Exception):
    """A retried call exhausted its policy; __cause__ is the last failure."""


class DeadlineExceeded(Exception):
    """A Deadline expired before the work completed."""


class CircuitOpenError(Exception):
    """A call was refused because the circuit breaker is open."""


class Deadline:
    """A monotonic time budget. Cheap to pass down call chains so one
    top-level budget bounds every retry loop underneath it."""

    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._expires = clock() + seconds

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(seconds)

    def remaining(self) -> float:
        return max(0.0, self._expires - self._clock())

    def expired(self) -> bool:
        return self._clock() >= self._expires

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExceeded(f"{what} exceeded its deadline")

    def clamp(self, delay: float) -> float:
        """A sleep no longer than what's left of the budget."""
        return min(delay, self.remaining())


class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``max_attempts`` counts total tries including the first; backoff before
    retry ``n`` (1-based) is ``initial_backoff * multiplier**(n-1)`` capped
    at ``max_backoff``, then jittered by ``±jitter`` fraction. Jitter draws
    come from :func:`oryx_tpu.common.rng.get_random`, so under
    ``use_test_seed()`` (or an explicit ``seed``) the delay sequence is
    reproducible — the property the chaos suite depends on.
    """

    def __init__(
        self,
        max_attempts: int = 5,
        initial_backoff: float = 0.1,
        max_backoff: float = 5.0,
        multiplier: float = 2.0,
        jitter: float = 0.1,
        seed: int | None = None,
    ) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1.0")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = max_attempts
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = rng.get_random(seed)
        self._rng_lock = threading.Lock()

    @classmethod
    def from_config(cls, config, prefix: str, **defaults: Any) -> "RetryPolicy":
        """Build from an ``oryx.*.retry`` block, e.g.
        ``RetryPolicy.from_config(cfg, "oryx.speed.retry")``. Missing keys
        fall back to ``defaults`` then to the constructor defaults."""

        def opt(key: str, kind: str):
            getter = config.get_optional_int if kind == "int" else config.get_optional_float
            return getter(f"{prefix}.{key}")

        kw: dict[str, Any] = dict(defaults)
        v = opt("max-attempts", "int")
        if v is not None:
            kw["max_attempts"] = v
        v = opt("initial-backoff-ms", "float")
        if v is not None:
            kw["initial_backoff"] = v / 1000.0
        v = opt("max-backoff-ms", "float")
        if v is not None:
            kw["max_backoff"] = v / 1000.0
        v = opt("multiplier", "float")
        if v is not None:
            kw["multiplier"] = v
        v = opt("jitter", "float")
        if v is not None:
            kw["jitter"] = v
        return cls(**kw)

    def backoff(self, attempt: int) -> float:
        """Jittered delay before retry `attempt` (1-based)."""
        base = min(self.max_backoff, self.initial_backoff * self.multiplier ** (attempt - 1))
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        with self._rng_lock:
            u = float(self._rng.random())
        return base * (1.0 - self.jitter + 2.0 * self.jitter * u)

    def backoff_or_none(self, attempt: int) -> float | None:
        """backoff(), or None once the policy is exhausted (attempt counts
        failures so far; the policy allows max_attempts - 1 retries)."""
        if attempt >= self.max_attempts:
            return None
        return self.backoff(attempt)

    def delays(self) -> Iterator[float]:
        """The max_attempts - 1 retry delays, in order."""
        for attempt in range(1, self.max_attempts):
            yield self.backoff(attempt)

    def call(
        self,
        fn: Callable[[], Any],
        *,
        retry_on: tuple[type[BaseException], ...] = (Exception,),
        deadline: Deadline | None = None,
        metrics_prefix: str | None = None,
        stop_event: threading.Event | None = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> Any:
        """Run fn(), retrying on `retry_on` with this policy's backoff.

        Raises :class:`RetryError` (cause = last failure) once attempts are
        exhausted, :class:`DeadlineExceeded` when the deadline runs out
        first. With `metrics_prefix`, emits `<prefix>.retry.retries` and
        `<prefix>.retry.failures` counters. A set `stop_event` aborts the
        backoff wait and re-raises the last failure immediately.
        """
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except retry_on as e:
                if isinstance(e, (CircuitOpenError, DeadlineExceeded)):
                    raise  # refusals are not transient faults
                delay = self.backoff_or_none(attempt)
                if delay is None:
                    if metrics_prefix:
                        metrics.registry.counter(f"{metrics_prefix}.retry.failures").inc()
                    raise RetryError(f"gave up after {attempt} attempts: {e}") from e
                if deadline is not None:
                    if deadline.expired():
                        raise DeadlineExceeded("deadline expired during retries") from e
                    delay = deadline.clamp(delay)
                if metrics_prefix:
                    metrics.registry.counter(f"{metrics_prefix}.retry.retries").inc()
                log.debug("retry %d/%d after %.3fs: %s", attempt, self.max_attempts, delay, e)
                if stop_event is not None:
                    if stop_event.wait(delay):
                        raise
                else:
                    sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover


class CircuitBreaker:
    """Closed/open/half-open circuit breaker.

    CLOSED: calls flow; `failure_threshold` consecutive failures trip it
    OPEN. OPEN: calls are refused with :class:`CircuitOpenError` until
    `reset_timeout` elapses, then one probe is let through (HALF_OPEN).
    HALF_OPEN: probe success closes the circuit, probe failure re-opens it
    and restarts the timeout. State is exported as the gauge
    `<name>.circuit.state` (0=closed, 1=open, 2=half-open).
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
    _STATE_VALUE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        reset_timeout: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._set_gauge()

    def _set_gauge(self) -> None:
        metrics.registry.gauge(f"{self.name}.circuit.state").set(
            self._STATE_VALUE[self._state]
        )

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        """Caller holds the lock."""
        if self._state == self.OPEN and self._clock() - self._opened_at >= self.reset_timeout:
            self._state = self.HALF_OPEN
            self._set_gauge()

    def allow(self) -> bool:
        """True if a call may proceed now (an allowed call in half-open is
        the probe: its record_success/record_failure decides the state)."""
        with self._lock:
            self._maybe_half_open()
            return self._state != self.OPEN

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != self.CLOSED:
                self._state = self.CLOSED
                self._set_gauge()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or (
                self._state == self.CLOSED and self._failures >= self.failure_threshold
            ):
                self._state = self.OPEN
                self._opened_at = self._clock()
                metrics.registry.counter(f"{self.name}.circuit.opens").inc()
                self._set_gauge()

    def call(self, fn: Callable[[], Any]) -> Any:
        """Guarded call: refuses with CircuitOpenError while open, records
        the outcome otherwise."""
        if not self.allow():
            metrics.registry.counter(f"{self.name}.circuit.refused").inc()
            raise CircuitOpenError(f"circuit {self.name} is open")
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result


class SupervisedThread:
    """A daemon thread whose target is restarted with backoff on failure.

    Two shapes of target:

    - ``loop=False`` (default): `target` is long-running (e.g. a blocking
      consume loop). Normal return ends the thread. An exception restarts
      it after the policy's backoff; a run that survived `min_uptime_sec`
      resets the failure count, so only *rapid* consecutive crashes walk
      toward give-up.
    - ``loop=True``: `target` is ONE iteration (e.g. one micro-batch
      interval). It is invoked repeatedly until the stop event is set;
      each normal return resets the failure count.

    Once the policy is exhausted the thread gives up: `healthy` flips
    False, `<metrics_prefix>.giveups` increments, and the owning layer
    reports unhealthy. `on_failure(exc)` (if given) runs after each
    failure, before the backoff — the hook the speed layer uses to
    dead-letter poison blocks.
    """

    def __init__(
        self,
        name: str,
        target: Callable[[], None],
        policy: RetryPolicy,
        stop_event: threading.Event,
        *,
        loop: bool = False,
        metrics_prefix: str | None = None,
        on_failure: Callable[[BaseException], None] | None = None,
        min_uptime_sec: float = 5.0,
    ) -> None:
        self.name = name
        self._target = target
        self._policy = policy
        self._stop_event = stop_event
        self._loop = loop
        self._metrics_prefix = metrics_prefix or f"supervised.{name}"
        self._on_failure = on_failure
        self._min_uptime_sec = min_uptime_sec
        # guards _gave_up/restarts: written on the supervisor thread,
        # read by health probes on request threads (oryxlint ORX102)
        self._state_lock = threading.Lock()
        self._gave_up = False
        self.restarts = 0
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        metrics.registry.gauge(f"{self._metrics_prefix}.healthy").set(1)

    # -- thread surface ------------------------------------------------------

    def start(self) -> None:
        from oryx_tpu.common import ledger

        self._thread.start()
        # registered at start (not construction) so an unstarted thread
        # never counts as a live resource; live while the OS thread runs
        ledger.register("thread", self, live=SupervisedThread.is_alive)

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    @property
    def healthy(self) -> bool:
        with self._state_lock:
            return not self._gave_up

    @property
    def gave_up(self) -> bool:
        with self._state_lock:
            return self._gave_up

    # -- supervisor loop -----------------------------------------------------

    def _run(self) -> None:
        failures = 0
        while not self._stop_event.is_set():
            started = time.monotonic()
            try:
                self._target()
                if not self._loop:
                    return
                failures = 0
                continue
            except Exception as e:  # noqa: BLE001 - that's the job
                if self._stop_event.is_set():
                    return
                log.exception("supervised thread %s failed", self.name)
                metrics.registry.counter(f"{self._metrics_prefix}.restarts").inc()
                if self._on_failure is not None:
                    try:
                        self._on_failure(e)
                    except Exception:  # noqa: BLE001
                        log.exception("on_failure hook for %s failed", self.name)
                if not self._loop and time.monotonic() - started >= self._min_uptime_sec:
                    failures = 0
                failures += 1
                with self._state_lock:
                    self.restarts += 1
                delay = self._policy.backoff_or_none(failures)
                if delay is None:
                    with self._state_lock:
                        self._gave_up = True
                    metrics.registry.counter(f"{self._metrics_prefix}.giveups").inc()
                    metrics.registry.gauge(f"{self._metrics_prefix}.healthy").set(0)
                    log.error(
                        "supervised thread %s giving up after %d consecutive failures",
                        self.name,
                        failures,
                    )
                    return
                self._stop_event.wait(delay)
