"""ctypes wrapper for the C++ concurrent feature-vector store.

API-compatible with the pure-Python FeatureVectors
(oryx_tpu.app.als.common) — same method surface, same rotation semantics
(FeatureVectors.java:36-161). The native store fixes the vector dimension
on first write; ctypes releases the GIL for every call, so concurrent
readers/writers on different shards genuinely run in parallel.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Callable, Iterable

import numpy as np

from oryx_tpu.native import get_library


def _decode_ids(buf: bytes) -> list[str]:
    """Parse the length-prefixed id stream ([u32 len][bytes]...)."""
    ids = []
    pos = 0
    end = len(buf)
    while pos + 4 <= end:
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        ids.append(buf[pos : pos + n].decode("utf-8"))
        pos += n
    return ids


def _offsets_payload(ids: list[str]) -> tuple[np.ndarray, bytes]:
    """Ids for the native ABI as (offsets[n+1] int64, concatenated utf-8
    payload): id i is payload[offsets[i]:offsets[i+1]]. Builds in a few
    vectorized passes — the length-prefix interleaving this replaces cost
    a Python loop with a struct.pack per id, which dominated the speed
    layer's serialization profile at 100k-event micro-batches."""
    n = len(ids)
    offs = np.zeros(n + 1, dtype=np.int64)
    if not n:
        return offs, b""
    # ascii fast path: one join + one encode for the whole batch; byte
    # lengths equal char lengths exactly when the encode didn't grow, so
    # a single length check validates the assumption (non-ascii ids fall
    # back to the per-id encode)
    np.cumsum(np.fromiter(map(len, ids), np.int64, count=n), out=offs[1:])
    payload = "".join(ids).encode("utf-8")
    if len(payload) == offs[n]:
        return offs, payload
    bs = [s.encode("utf-8") for s in ids]
    np.cumsum(np.fromiter(map(len, bs), np.int64, count=n), out=offs[1:])
    return offs, b"".join(bs)


def _offsets_ptr(offs: np.ndarray):
    return offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class NativeFeatureVectors:
    """Drop-in FeatureVectors backed by the C++ store."""

    def __init__(self, num_shards: int = 16) -> None:
        self._lib = get_library()
        if self._lib is None:  # pragma: no cover - build always works in CI
            raise RuntimeError("native library unavailable")
        self._num_shards = num_shards
        self._ptr = None
        self._dim: int | None = None
        self._init_lock = threading.Lock()

    def __del__(self):  # pragma: no cover - interpreter teardown
        ptr, self._ptr = self._ptr, None
        if ptr and self._lib is not None:
            self._lib.fs_destroy(ptr)

    def _ensure(self, dim: int):
        with self._init_lock:
            if self._ptr is None:
                self._ptr = self._lib.fs_create(dim, self._num_shards)
                self._dim = dim
            elif dim != self._dim:
                raise ValueError(f"vector dim {dim} != store dim {self._dim}")
        return self._ptr

    # -- FeatureVectors API --------------------------------------------------

    def size(self) -> int:
        if self._ptr is None:
            return 0
        return int(self._lib.fs_size(self._ptr))

    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        vec = np.ascontiguousarray(vector, dtype=np.float32)
        ptr = self._ensure(vec.shape[0])
        key = id_.encode("utf-8")
        self._lib.fs_set(
            ptr, key, len(key), vec.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )

    def set_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        """Insert/update many vectors in one native call (fs_set_batch):
        the self-consume hot path at 100K+ deltas/s."""
        n = len(ids)
        if n == 0:
            return
        mat = np.ascontiguousarray(vectors, dtype=np.float32)
        ptr = self._ensure(mat.shape[1])
        offs, payload = _offsets_payload(ids)
        self._lib.fs_set_batch(
            ptr,
            _offsets_ptr(offs),
            payload,
            n,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )

    def get_vector(self, id_: str) -> np.ndarray | None:
        if self._ptr is None:
            return None
        out = np.empty(self._dim, dtype=np.float32)
        key = id_.encode("utf-8")
        found = self._lib.fs_get(
            self._ptr, key, len(key), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        return out if found else None

    def get_batch(
        self, ids: list[str], dim: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectors for many ids in one native call:
        ([n, dim] float32 with zero rows for misses, [n] bool valid).
        ``dim`` keeps the shape well-formed when the store is empty."""
        n = len(ids)
        if self._ptr is None or n == 0:
            return np.zeros((n, self._dim or dim or 0), dtype=np.float32), np.zeros(n, dtype=bool)
        offs, payload = _offsets_payload(ids)
        mat = np.zeros((n, self._dim), dtype=np.float32)
        valid = np.zeros(n, dtype=np.uint8)
        self._lib.fs_get_batch(
            self._ptr,
            _offsets_ptr(offs),
            payload,
            n,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return mat, valid.astype(bool)

    def remove_vector(self, id_: str) -> None:
        if self._ptr is not None:
            key = id_.encode("utf-8")
            self._lib.fs_remove(self._ptr, key, len(key))

    def _pack(self, recent_only: bool = False) -> tuple[list[str], np.ndarray]:
        if self._ptr is None:
            return [], np.zeros((0, 0), dtype=np.float32)
        mat_cap = max(1, self.size() + 64) * self._dim
        ids_cap = max(1024, (self.size() + 64) * 64)
        while True:
            mat = np.empty(mat_cap, dtype=np.float32)
            ids_buf = ctypes.create_string_buffer(ids_cap)
            mat_needed = ctypes.c_int64()
            ids_needed = ctypes.c_int64()
            n = self._lib.fs_pack(
                self._ptr,
                mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                mat_cap,
                ids_buf,
                ids_cap,
                ctypes.byref(mat_needed),
                ctypes.byref(ids_needed),
                1 if recent_only else 0,
            )
            if n >= 0:
                ids = _decode_ids(ids_buf.raw[: ids_needed.value])
                return ids, mat[: n * self._dim].reshape(n, self._dim).copy()
            mat_cap = max(mat_needed.value, self._dim)
            ids_cap = max(ids_needed.value, 1024)

    def _pack_ids(self, recent_only: bool = False) -> list[str]:
        """IDs without copying vector data (fs_ids)."""
        if self._ptr is None:
            return []
        ids_cap = max(4096, (self.size() + 64) * 64)
        while True:
            ids_buf = ctypes.create_string_buffer(ids_cap)
            ids_needed = ctypes.c_int64()
            n = self._lib.fs_ids(
                self._ptr, ids_buf, ids_cap, ctypes.byref(ids_needed),
                1 if recent_only else 0,
            )
            if n >= 0:
                return _decode_ids(ids_buf.raw[: ids_needed.value])
            ids_cap = max(ids_needed.value, 4096)

    def to_matrix(self) -> tuple[list[str], np.ndarray]:
        return self._pack()

    def ids(self) -> list[str]:
        return self._pack_ids()

    def items(self) -> list[tuple[str, np.ndarray]]:
        ids, mat = self._pack()
        return [(i, mat[r]) for r, i in enumerate(ids)]

    def for_each(self, fn: Callable[[str, np.ndarray], None]) -> None:
        for id_, v in self.items():
            fn(id_, v)

    def add_all_ids_to(self, out: set[str]) -> None:
        out.update(self._pack_ids())

    def add_all_recent_to(self, out: set[str]) -> None:
        out.update(self._pack_ids(recent_only=True))

    def retain_recent_and_ids(self, new_model_ids: Iterable[str]) -> None:
        if self._ptr is None:
            return
        offs, payload = _offsets_payload(list(new_model_ids))
        self._lib.fs_retain(self._ptr, _offsets_ptr(offs), payload, len(offs) - 1)

    def get_vtv(self) -> np.ndarray | None:
        if self._ptr is None or self.size() == 0:
            return None
        out = np.zeros((self._dim, self._dim), dtype=np.float64)
        self._lib.fs_vtv(self._ptr, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out


def format_vectors_json(mat: np.ndarray) -> list[str]:
    """Each row of [n, k] float32 as a JSON number-array string. Native
    %.9g formatting (round-trips float32) when the library is available;
    json.dumps fallback otherwise."""
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    lib = get_library()
    if lib is None or n == 0:
        import json

        # match the native formatter: non-finite components become 0 so the
        # wire format stays valid JSON regardless of which path serialized
        return [json.dumps(np.nan_to_num(row, nan=0.0, posinf=0.0, neginf=0.0).tolist()) for row in mat]
    cap = n * (2 + k * 18)
    out = np.empty(cap, dtype=np.uint8)  # no zero-fill: the C side writes
    offsets = np.empty(n + 1, dtype=np.int64)
    needed = ctypes.c_int64()
    total = lib.json_format_vectors(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        k,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(needed),
    )
    if total < 0:  # pragma: no cover - cap is the function's own worst case
        raise RuntimeError("json_format_vectors buffer underestimate")
    # one decode of the packed output, then O(row) str slices (ascii, so
    # byte offsets == char offsets)
    s = out[:total].tobytes().decode("ascii")
    off = offsets.tolist()
    return [s[off[i] : off[i + 1]] for i in range(n)]


# cap on one native-formatter call's output buffer (n rows x uniform
# worst-case stride); larger requests are sliced into bounded calls
_MULTI_BUFFER_BUDGET = 256 * 1024 * 1024


def _format_rows(
    n: int,
    stride: int,
    all_ascii: bool,
    num_threads: int | None,
    invoke,
) -> list[str] | None:
    """Shared tail of the update formatters: allocate the stride-spaced
    output + row-offset buffers, run the native call, slice rows out of
    the compacted byte run (one ascii decode when every payload is ascii,
    per-row utf-8 otherwise)."""
    out = np.empty(n * stride, dtype=np.uint8)
    starts = np.empty(n, dtype=np.int64)
    ends = np.empty(n, dtype=np.int64)
    threads = num_threads or min(8, os.cpu_count() or 1)
    total = invoke(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        threads,
    )
    if total < 0:  # pragma: no cover - strides are computed right here
        return None
    st, en = starts.tolist(), ends.tolist()
    if all_ascii:
        s = str(memoryview(out)[:total], "ascii")
        return [s[st[i] : en[i]] for i in range(n)]
    buf = memoryview(out)[:total]
    return [str(buf[st[i] : en[i]], "utf-8") for i in range(n)]


def format_update_messages(
    mat: np.ndarray,
    ids: list[str],
    other_ids: list[str],
    tag: str,
    include_known: bool = True,
    num_threads: int | None = None,
) -> list[str] | None:
    """Complete speed-layer update messages ["X"|"Y", id, [v..], [other]]
    for n rows in one thread-parallel native call, or None when the
    native library is unavailable (caller assembles in Python)."""
    lib = get_library()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    if n == 0:
        return []
    if len(ids) != n or (include_known and len(other_ids) != n):
        return None  # malformed pairing; the native side trusts the lengths
    id_offs, id_payload = _offsets_payload(ids)
    other_offs, other_payload = _offsets_payload(other_ids if include_known else [""] * n)
    # ascii payloads mean byte offsets == char offsets when slicing output
    all_ascii = len(id_payload) == sum(map(len, ids)) and (
        not include_known or len(other_payload) == sum(map(len, other_ids))
    )
    max_id_len = max(
        1,
        int(np.diff(id_offs).max()) if n else 1,
        int(np.diff(other_offs).max()) if n else 1,
    )
    stride = int(lib.als_update_row_cap(k, max_id_len))
    return _format_rows(
        n, stride, all_ascii, num_threads,
        lambda out, starts, ends, threads: lib.als_format_updates(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, k,
            _offsets_ptr(id_offs), id_payload,
            _offsets_ptr(other_offs), other_payload,
            tag.encode("ascii"),
            1 if include_known else 0,
            max_id_len, out, starts, ends, threads,
        ),
    )


def format_update_messages_multi(
    mat: np.ndarray,
    ids: list[str],
    known_lists: list[list[str]],
    tag: str,
    num_threads: int | None = None,
) -> list[str] | None:
    """Update messages ["X"|"Y", id, [v..], [k1, k2, ...]] where each row
    carries its own known-id LIST — the shape the speed layer needs after
    coalescing a micro-batch's per-event updates into one message per id
    (the known items of dropped duplicates merge into the survivor).
    Returns None when the native library is unavailable."""
    lib = get_library()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    if n == 0:
        return []
    if len(ids) != n or len(known_lists) != n:
        return None
    id_offs, id_payload = _offsets_payload(ids)
    flat_known: list[str] = []
    row_offs = np.empty(n + 1, dtype=np.int64)
    row_offs[0] = 0
    for i, kl in enumerate(known_lists):
        flat_known.extend(kl)
        row_offs[i + 1] = len(flat_known)
    known_offs, known_payload = _offsets_payload(flat_known)
    all_ascii = len(id_payload) == sum(map(len, ids)) and len(known_payload) == sum(
        map(len, flat_known)
    )
    max_id_len = max(1, int(np.diff(id_offs).max()) if n else 1)
    # widest known list's worst-case bytes: 6x escape + quotes + comma each
    if len(flat_known):
        per_known = np.diff(known_offs) * 6 + 3
        cs = np.concatenate([[0], np.cumsum(per_known)])
        row_extra = cs[row_offs[1:]] - cs[row_offs[:-1]]
        max_known_extra = int(row_extra.max())
    else:
        row_extra = np.zeros(n, dtype=np.int64)
        max_known_extra = 0
    base_cap = int(lib.als_update_row_cap(k, max_id_len))
    stride = base_cap + max_known_extra
    if n > 1 and n * stride > _MULTI_BUFFER_BUDGET:
        # the stride is uniform (each thread region is stride-spaced), so
        # one id with a huge known union would inflate the buffer for
        # every row; slice rows so each call's n * stride stays bounded
        # (a pathological row lands in a small slice of its own)
        out_all: list[str] = []
        lo = 0
        while lo < n:
            hi, worst = lo + 1, int(row_extra[lo])
            while hi < n:
                w = max(worst, int(row_extra[hi]))
                if (hi - lo + 1) * (base_cap + w) > _MULTI_BUFFER_BUDGET:
                    break
                worst, hi = w, hi + 1
            part = format_update_messages_multi(
                mat[lo:hi], ids[lo:hi], known_lists[lo:hi], tag, num_threads
            )
            if part is None:  # pragma: no cover - lib vanished mid-call
                return None
            out_all.extend(part)
            lo = hi
        return out_all
    return _format_rows(
        n, stride, all_ascii, num_threads,
        lambda out, starts, ends, threads: lib.als_format_updates_multi(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, k,
            _offsets_ptr(id_offs), id_payload,
            _offsets_ptr(row_offs),
            _offsets_ptr(known_offs), known_payload,
            tag.encode("ascii"),
            stride, out, starts, ends, threads,
        ),
    )


def parse_float_csv(payload: bytes, expected: int) -> np.ndarray | None:
    """Parse a comma-separated float run natively; None when the library
    is unavailable, the token count mismatches, or a token is malformed
    (caller falls back to numpy astype / per-record parsing)."""
    lib = get_library()
    if lib is None:
        return None
    out = np.empty(expected, dtype=np.float32)
    n = lib.parse_float_csv(
        payload, len(payload), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), expected
    )
    if n != expected:
        return None
    return out


def make_feature_vectors(num_shards: int = 16):
    """Native store when available, else the pure-Python FeatureVectors."""
    if get_library() is not None:
        return NativeFeatureVectors(num_shards)
    from oryx_tpu.app.als.common import FeatureVectors

    return FeatureVectors()
