"""ctypes wrapper for the C++ concurrent feature-vector store.

API-compatible with the pure-Python FeatureVectors
(oryx_tpu.app.als.common) — same method surface, same rotation semantics
(FeatureVectors.java:36-161). The native store fixes the vector dimension
on first write; ctypes releases the GIL for every call, so concurrent
readers/writers on different shards genuinely run in parallel.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Callable, Iterable

import numpy as np

from oryx_tpu.common import metrics
from oryx_tpu.native import get_library


def _decode_ids(buf: bytes) -> list[str]:
    """Parse the length-prefixed id stream ([u32 len][bytes]...)."""
    ids = []
    pos = 0
    end = len(buf)
    while pos + 4 <= end:
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        ids.append(buf[pos : pos + n].decode("utf-8"))
        pos += n
    return ids


def _offsets_payload(ids: list[str]) -> tuple[np.ndarray, bytes]:
    """Ids for the native ABI as (offsets[n+1] int64, concatenated utf-8
    payload): id i is payload[offsets[i]:offsets[i+1]]. Builds in a few
    vectorized passes — the length-prefix interleaving this replaces cost
    a Python loop with a struct.pack per id, which dominated the speed
    layer's serialization profile at 100k-event micro-batches."""
    n = len(ids)
    offs = np.zeros(n + 1, dtype=np.int64)
    if not n:
        return offs, b""
    # ascii fast path: one join + one encode for the whole batch; byte
    # lengths equal char lengths exactly when the encode didn't grow, so
    # a single length check validates the assumption (non-ascii ids fall
    # back to the per-id encode)
    np.cumsum(np.fromiter(map(len, ids), np.int64, count=n), out=offs[1:])
    payload = "".join(ids).encode("utf-8")
    if len(payload) == offs[n]:
        return offs, payload
    bs = [s.encode("utf-8") for s in ids]
    np.cumsum(np.fromiter(map(len, bs), np.int64, count=n), out=offs[1:])
    return offs, b"".join(bs)


def _offsets_ptr(offs: np.ndarray):
    return offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class NativeFeatureVectors:
    """Drop-in FeatureVectors backed by the C++ store."""

    def __init__(self, num_shards: int = 16) -> None:
        self._lib = get_library()
        if self._lib is None:  # pragma: no cover - build always works in CI
            raise RuntimeError("native library unavailable")
        self._num_shards = num_shards
        self._ptr = None
        self._dim: int | None = None
        self._init_lock = threading.Lock()

    def __del__(self):  # pragma: no cover - interpreter teardown
        ptr, self._ptr = self._ptr, None
        if ptr and self._lib is not None:
            self._lib.fs_destroy(ptr)

    def _ensure(self, dim: int):
        with self._init_lock:
            if self._ptr is None:
                self._ptr = self._lib.fs_create(dim, self._num_shards)
                self._dim = dim
            elif dim != self._dim:
                raise ValueError(f"vector dim {dim} != store dim {self._dim}")
        return self._ptr

    # -- FeatureVectors API --------------------------------------------------

    def size(self) -> int:
        if self._ptr is None:
            return 0
        return int(self._lib.fs_size(self._ptr))

    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        vec = np.ascontiguousarray(vector, dtype=np.float32)
        ptr = self._ensure(vec.shape[0])
        key = id_.encode("utf-8")
        self._lib.fs_set(
            ptr, key, len(key), vec.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )

    def set_batch(self, ids: list[str], vectors: np.ndarray) -> None:
        """Insert/update many vectors in one native call (fs_set_batch):
        the self-consume hot path at 100K+ deltas/s."""
        n = len(ids)
        if n == 0:
            return
        mat = np.ascontiguousarray(vectors, dtype=np.float32)
        ptr = self._ensure(mat.shape[1])
        offs, payload = _offsets_payload(ids)
        self._lib.fs_set_batch(
            ptr,
            _offsets_ptr(offs),
            payload,
            n,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )

    def get_vector(self, id_: str) -> np.ndarray | None:
        if self._ptr is None:
            return None
        out = np.empty(self._dim, dtype=np.float32)
        key = id_.encode("utf-8")
        found = self._lib.fs_get(
            self._ptr, key, len(key), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        return out if found else None

    def get_batch(
        self, ids: list[str], dim: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectors for many ids in one native call:
        ([n, dim] float32 with zero rows for misses, [n] bool valid).
        ``dim`` keeps the shape well-formed when the store is empty."""
        n = len(ids)
        if self._ptr is None or n == 0:
            return np.zeros((n, self._dim or dim or 0), dtype=np.float32), np.zeros(n, dtype=bool)
        offs, payload = _offsets_payload(ids)
        mat = np.zeros((n, self._dim), dtype=np.float32)
        valid = np.zeros(n, dtype=np.uint8)
        self._lib.fs_get_batch(
            self._ptr,
            _offsets_ptr(offs),
            payload,
            n,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return mat, valid.astype(bool)

    def remove_vector(self, id_: str) -> None:
        if self._ptr is not None:
            key = id_.encode("utf-8")
            self._lib.fs_remove(self._ptr, key, len(key))

    def _pack(self, recent_only: bool = False) -> tuple[list[str], np.ndarray]:
        if self._ptr is None:
            return [], np.zeros((0, 0), dtype=np.float32)
        mat_cap = max(1, self.size() + 64) * self._dim
        ids_cap = max(1024, (self.size() + 64) * 64)
        while True:
            mat = np.empty(mat_cap, dtype=np.float32)
            ids_buf = ctypes.create_string_buffer(ids_cap)
            mat_needed = ctypes.c_int64()
            ids_needed = ctypes.c_int64()
            n = self._lib.fs_pack(
                self._ptr,
                mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                mat_cap,
                ids_buf,
                ids_cap,
                ctypes.byref(mat_needed),
                ctypes.byref(ids_needed),
                1 if recent_only else 0,
            )
            if n >= 0:
                ids = _decode_ids(ids_buf.raw[: ids_needed.value])
                return ids, mat[: n * self._dim].reshape(n, self._dim).copy()
            mat_cap = max(mat_needed.value, self._dim)
            ids_cap = max(ids_needed.value, 1024)

    def _pack_ids(self, recent_only: bool = False) -> list[str]:
        """IDs without copying vector data (fs_ids)."""
        if self._ptr is None:
            return []
        ids_cap = max(4096, (self.size() + 64) * 64)
        while True:
            ids_buf = ctypes.create_string_buffer(ids_cap)
            ids_needed = ctypes.c_int64()
            n = self._lib.fs_ids(
                self._ptr, ids_buf, ids_cap, ctypes.byref(ids_needed),
                1 if recent_only else 0,
            )
            if n >= 0:
                return _decode_ids(ids_buf.raw[: ids_needed.value])
            ids_cap = max(ids_needed.value, 4096)

    def to_matrix(self) -> tuple[list[str], np.ndarray]:
        return self._pack()

    def ids(self) -> list[str]:
        return self._pack_ids()

    def items(self) -> list[tuple[str, np.ndarray]]:
        ids, mat = self._pack()
        return [(i, mat[r]) for r, i in enumerate(ids)]

    def for_each(self, fn: Callable[[str, np.ndarray], None]) -> None:
        for id_, v in self.items():
            fn(id_, v)

    def add_all_ids_to(self, out: set[str]) -> None:
        out.update(self._pack_ids())

    def add_all_recent_to(self, out: set[str]) -> None:
        out.update(self._pack_ids(recent_only=True))

    def retain_recent_and_ids(self, new_model_ids: Iterable[str]) -> None:
        if self._ptr is None:
            return
        offs, payload = _offsets_payload(list(new_model_ids))
        self._lib.fs_retain(self._ptr, _offsets_ptr(offs), payload, len(offs) - 1)

    def get_vtv(self) -> np.ndarray | None:
        if self._ptr is None or self.size() == 0:
            return None
        out = np.zeros((self._dim, self._dim), dtype=np.float64)
        self._lib.fs_vtv(self._ptr, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out


def format_vectors_json(mat: np.ndarray) -> list[str]:
    """Each row of [n, k] float32 as a JSON number-array string. Native
    %.9g formatting (round-trips float32) when the library is available;
    json.dumps fallback otherwise."""
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    lib = get_library()
    if lib is None or n == 0:
        import json

        # match the native formatter: non-finite components become 0 so the
        # wire format stays valid JSON regardless of which path serialized
        return [json.dumps(np.nan_to_num(row, nan=0.0, posinf=0.0, neginf=0.0).tolist()) for row in mat]
    cap = n * (2 + k * 18)
    out = np.empty(cap, dtype=np.uint8)  # no zero-fill: the C side writes
    offsets = np.empty(n + 1, dtype=np.int64)
    needed = ctypes.c_int64()
    total = lib.json_format_vectors(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        k,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        cap,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(needed),
    )
    if total < 0:  # pragma: no cover - cap is the function's own worst case
        raise RuntimeError("json_format_vectors buffer underestimate")
    # one decode of the packed output, then O(row) str slices (ascii, so
    # byte offsets == char offsets)
    s = out[:total].tobytes().decode("ascii")
    off = offsets.tolist()
    return [s[off[i] : off[i + 1]] for i in range(n)]


# cap on one native-formatter call's output buffer (n rows x uniform
# worst-case stride); larger requests are sliced into bounded calls
_MULTI_BUFFER_BUDGET = 256 * 1024 * 1024


def _format_rows(
    n: int,
    stride: int,
    all_ascii: bool,
    num_threads: int | None,
    invoke,
) -> list[str] | None:
    """Shared tail of the update formatters: allocate the stride-spaced
    output + row-offset buffers, run the native call, slice rows out of
    the compacted byte run (one ascii decode when every payload is ascii,
    per-row utf-8 otherwise)."""
    out = np.empty(n * stride, dtype=np.uint8)
    starts = np.empty(n, dtype=np.int64)
    ends = np.empty(n, dtype=np.int64)
    threads = num_threads or min(8, os.cpu_count() or 1)
    total = invoke(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        threads,
    )
    if total < 0:  # pragma: no cover - strides are computed right here
        return None
    st, en = starts.tolist(), ends.tolist()
    if all_ascii:
        s = str(memoryview(out)[:total], "ascii")
        return [s[st[i] : en[i]] for i in range(n)]
    buf = memoryview(out)[:total]
    return [str(buf[st[i] : en[i]], "utf-8") for i in range(n)]


def format_update_messages(
    mat: np.ndarray,
    ids: list[str],
    other_ids: list[str],
    tag: str,
    include_known: bool = True,
    num_threads: int | None = None,
) -> list[str] | None:
    """Complete speed-layer update messages ["X"|"Y", id, [v..], [other]]
    for n rows in one thread-parallel native call, or None when the
    native library is unavailable (caller assembles in Python)."""
    lib = get_library()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    if n == 0:
        return []
    if len(ids) != n or (include_known and len(other_ids) != n):
        return None  # malformed pairing; the native side trusts the lengths
    id_offs, id_payload = _offsets_payload(ids)
    other_offs, other_payload = _offsets_payload(other_ids if include_known else [""] * n)
    # ascii payloads mean byte offsets == char offsets when slicing output
    all_ascii = len(id_payload) == sum(map(len, ids)) and (
        not include_known or len(other_payload) == sum(map(len, other_ids))
    )
    max_id_len = max(
        1,
        int(np.diff(id_offs).max()) if n else 1,
        int(np.diff(other_offs).max()) if n else 1,
    )
    stride = int(lib.als_update_row_cap(k, max_id_len))
    return _format_rows(
        n, stride, all_ascii, num_threads,
        lambda out, starts, ends, threads: lib.als_format_updates(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, k,
            _offsets_ptr(id_offs), id_payload,
            _offsets_ptr(other_offs), other_payload,
            tag.encode("ascii"),
            1 if include_known else 0,
            max_id_len, out, starts, ends, threads,
        ),
    )


def format_update_messages_multi(
    mat: np.ndarray,
    ids: list[str],
    known_lists: list[list[str]],
    tag: str,
    num_threads: int | None = None,
) -> list[str] | None:
    """Update messages ["X"|"Y", id, [v..], [k1, k2, ...]] where each row
    carries its own known-id LIST — the shape the speed layer needs after
    coalescing a micro-batch's per-event updates into one message per id
    (the known items of dropped duplicates merge into the survivor).
    Returns None when the native library is unavailable."""
    lib = get_library()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    n, k = mat.shape
    if n == 0:
        return []
    if len(ids) != n or len(known_lists) != n:
        return None
    id_offs, id_payload = _offsets_payload(ids)
    flat_known: list[str] = []
    row_offs = np.empty(n + 1, dtype=np.int64)
    row_offs[0] = 0
    for i, kl in enumerate(known_lists):
        flat_known.extend(kl)
        row_offs[i + 1] = len(flat_known)
    known_offs, known_payload = _offsets_payload(flat_known)
    all_ascii = len(id_payload) == sum(map(len, ids)) and len(known_payload) == sum(
        map(len, flat_known)
    )
    max_id_len = max(1, int(np.diff(id_offs).max()) if n else 1)
    # widest known list's worst-case bytes: 6x escape + quotes + comma each
    if len(flat_known):
        per_known = np.diff(known_offs) * 6 + 3
        cs = np.concatenate([[0], np.cumsum(per_known)])
        row_extra = cs[row_offs[1:]] - cs[row_offs[:-1]]
        max_known_extra = int(row_extra.max())
    else:
        row_extra = np.zeros(n, dtype=np.int64)
        max_known_extra = 0
    base_cap = int(lib.als_update_row_cap(k, max_id_len))
    stride = base_cap + max_known_extra
    if n > 1 and n * stride > _MULTI_BUFFER_BUDGET:
        # the stride is uniform (each thread region is stride-spaced), so
        # one id with a huge known union would inflate the buffer for
        # every row; slice rows so each call's n * stride stays bounded
        # (a pathological row lands in a small slice of its own)
        out_all: list[str] = []
        lo = 0
        while lo < n:
            hi, worst = lo + 1, int(row_extra[lo])
            while hi < n:
                w = max(worst, int(row_extra[hi]))
                if (hi - lo + 1) * (base_cap + w) > _MULTI_BUFFER_BUDGET:
                    break
                worst, hi = w, hi + 1
            part = format_update_messages_multi(
                mat[lo:hi], ids[lo:hi], known_lists[lo:hi], tag, num_threads
            )
            if part is None:  # pragma: no cover - lib vanished mid-call
                return None
            out_all.extend(part)
            lo = hi
        return out_all
    return _format_rows(
        n, stride, all_ascii, num_threads,
        lambda out, starts, ends, threads: lib.als_format_updates_multi(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, k,
            _offsets_ptr(id_offs), id_payload,
            _offsets_ptr(row_offs),
            _offsets_ptr(known_offs), known_payload,
            tag.encode("ascii"),
            stride, out, starts, ends, threads,
        ),
    )


def parse_float_csv(payload: bytes, expected: int) -> np.ndarray | None:
    """Parse a comma-separated float run natively; None when the library
    is unavailable, the token count mismatches, or a token is malformed
    (caller falls back to numpy astype / per-record parsing)."""
    lib = get_library()
    if lib is None:
        return None
    out = np.empty(expected, dtype=np.float32)
    n = lib.parse_float_csv(
        payload, len(payload), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), expected
    )
    if n != expected:
        return None
    return out


def make_feature_vectors(num_shards: int = 16):
    """Native store when available, else the pure-Python FeatureVectors."""
    if get_library() is not None:
        return NativeFeatureVectors(num_shards)
    from oryx_tpu.app.als.common import FeatureVectors

    return FeatureVectors()


# -- tiered HBM->RAM->disk cell plane -----------------------------------------
#
# Large-catalog mode for the IVF host plane: instead of one flat
# [n_slots, kf] float32 array that must fit RAM, cells live in a
# three-tier store — a small LRU of decoded ndarrays (the device/HBM
# working set; on the CPU stage-1 path this is the set of cells handed
# straight to BLAS), a byte-budgeted warm tier of pinned host-RAM
# copies, and an mmap'd append-only disk file holding every cell. The
# scan gathers probed tiles through ``TieredHostPlane.gather_tiles``;
# the batcher calls ``IVFIndex.prefetch_for_queries`` while a group
# assembles so disk->RAM promotion overlaps batching instead of
# stalling the matmul. Backed by the GIL-free ts_* C++ store when the
# native library is available, with a semantics-identical pure-Python
# fallback (PyTieredCellStore) otherwise.

# residency codes (ts_residency / PyTieredCellStore.residency)
TIER_ABSENT = 0
TIER_DISK = 1
TIER_RAM = 2

_TIER_LOCK = threading.Lock()
_TIER_CONFIG = {
    "enabled": False,
    "hot_cells": 32,  # decoded-ndarray LRU entries (the "HBM" tier)
    "ram_bytes": 256 << 20,  # warm-tier byte budget
    "spill_dir": None,  # cold-tier directory; None -> per-plane tempdir
}


def configure_tier(
    enabled: bool | None = None,
    hot_cells: int | None = None,
    ram_bytes: int | None = None,
    spill_dir: str | None = None,
) -> dict:
    """Set the tiered-store knobs (oryx.serving.store.tier.* in
    reference.conf); None leaves a knob unchanged. Returns the resulting
    config. Applies to planes built afterwards — live planes keep the
    budgets they were created with."""
    with _TIER_LOCK:
        if enabled is not None:
            _TIER_CONFIG["enabled"] = bool(enabled)
        if hot_cells is not None:
            _TIER_CONFIG["hot_cells"] = max(1, int(hot_cells))
        if ram_bytes is not None:
            _TIER_CONFIG["ram_bytes"] = max(0, int(ram_bytes))
        if spill_dir is not None:
            _TIER_CONFIG["spill_dir"] = str(spill_dir) or None
        return dict(_TIER_CONFIG)


def tier_config() -> dict:
    with _TIER_LOCK:
        return dict(_TIER_CONFIG)


def tier_active() -> bool:
    """Should newly built IVF host planes move into the tiered store?"""
    with _TIER_LOCK:
        return bool(_TIER_CONFIG["enabled"])


class NativeTieredCellStore:
    """ctypes wrapper for the ts_* two-tier (RAM + disk) cell store."""

    def __init__(self, n_cells: int, ram_budget_bytes: int, directory: str):
        self._lib = get_library()
        if self._lib is None:  # pragma: no cover - caller checks first
            raise RuntimeError("native library unavailable")
        self._n_cells = int(n_cells)
        d = directory.encode("utf-8")
        self._ptr = self._lib.ts_create(
            d, len(d), self._n_cells, int(ram_budget_bytes)
        )
        if not self._ptr:
            raise RuntimeError(f"ts_create failed for {directory}")

    def __del__(self):  # pragma: no cover - interpreter teardown
        self.close()

    def close(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and self._lib is not None:
            self._lib.ts_destroy(ptr)

    def put_cell(self, cell: int, data: np.ndarray) -> None:
        buf = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
        rc = self._lib.ts_put_cell(
            self._ptr,
            int(cell),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.nbytes,
        )
        if rc < 0:
            raise ValueError(f"ts_put_cell({cell}) failed")

    def cell_bytes(self, cell: int) -> int:
        return int(self._lib.ts_cell_bytes(self._ptr, int(cell)))

    def read_cell(self, cell: int) -> np.ndarray | None:
        """Cell payload as a fresh uint8 array (RAM hit or disk read +
        warm-tier promotion), or None when the cell was never written."""
        nbytes = self.cell_bytes(cell)
        if nbytes < 0:
            return None
        out = np.empty(nbytes, dtype=np.uint8)
        got = self._lib.ts_read_cell(
            self._ptr,
            int(cell),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            nbytes,
        )
        return out if got == nbytes else None

    def prefetch(self, cells: np.ndarray) -> int:
        arr = np.ascontiguousarray(cells, dtype=np.int64)
        if not len(arr):
            return 0
        return int(
            self._lib.ts_prefetch(
                self._ptr,
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(arr),
            )
        )

    def residency(self) -> np.ndarray:
        out = np.zeros(self._n_cells, dtype=np.int64)
        self._lib.ts_residency(
            self._ptr,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._n_cells,
        )
        return out

    def stats(self) -> dict:
        out = np.zeros(8, dtype=np.int64)
        self._lib.ts_stats(
            self._ptr, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        )
        keys = (
            "ram_cells", "disk_cells", "hits", "misses",
            "promotions", "demotions", "ram_bytes", "queue_len",
        )
        return dict(zip(keys, out.tolist()))

    def drop_ram(self, cell: int) -> None:
        self._lib.ts_drop_ram(self._ptr, int(cell))


class PyTieredCellStore:
    """Pure-Python fallback with the ts_* semantics: append-only disk
    file + byte-budgeted LRU warm tier + background prefetch thread.
    Same counters, same residency codes — the tier tests run both."""

    def __init__(self, n_cells: int, ram_budget_bytes: int, directory: str):
        self._path = os.path.join(directory, "cells.bin")
        self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        self._n_cells = int(n_cells)
        self._off: list[tuple[int, int]] = [(-1, 0)] * self._n_cells
        self._file_bytes = 0
        self._budget = int(ram_budget_bytes)
        self._mu = threading.Lock()  # offsets + warm tier + counters
        self._ram: dict[int, bytes] = {}  # insertion order == LRU order
        self._ram_bytes = 0
        self._hits = self._misses = 0
        self._promotions = self._demotions = 0
        self._q: list[int] = []
        self._cv = threading.Condition()
        self._stopped = False
        self._worker = threading.Thread(
            target=self._run, name="py-tier-prefetch", daemon=True
        )
        self._worker.start()

    def __del__(self):  # pragma: no cover - interpreter teardown
        self.close()

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
        self._worker.join(timeout=5)
        with self._mu:
            fd, self._fd = self._fd, -1
        if fd >= 0:
            os.close(fd)
            try:
                os.unlink(self._path)
            except OSError:  # pragma: no cover - already swept
                pass

    # -- warm-tier internals (caller holds self._mu) --------------------------

    def _promote_locked(self, cell: int, data: bytes) -> None:
        if cell in self._ram:
            self._ram[cell] = self._ram.pop(cell)  # LRU touch
            return
        self._ram[cell] = data
        self._ram_bytes += len(data)
        self._promotions += 1
        while self._ram_bytes > self._budget and len(self._ram) > 1:
            old, buf = next(iter(self._ram.items()))
            del self._ram[old]
            self._ram_bytes -= len(buf)
            self._demotions += 1

    def _pread(self, cell: int) -> bytes | None:
        off, nbytes = self._off[cell]
        if off < 0 or self._fd < 0:
            return None
        return os.pread(self._fd, nbytes, off)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                cell = self._q.pop(0)
            with self._mu:
                if cell in self._ram:
                    continue
                data = self._pread(cell)
                if data is not None:
                    self._promote_locked(cell, data)

    # -- ts_* surface ---------------------------------------------------------

    def put_cell(self, cell: int, data: np.ndarray) -> None:
        buf = np.ascontiguousarray(data).view(np.uint8).reshape(-1).tobytes()
        with self._mu:
            if not 0 <= cell < self._n_cells:
                raise ValueError(f"cell {cell} out of range")
            os.pwrite(self._fd, buf, self._file_bytes)
            self._off[cell] = (self._file_bytes, len(buf))
            self._file_bytes += len(buf)
            stale = self._ram.pop(cell, None)  # rewritten: drop stale copy
            if stale is not None:
                self._ram_bytes -= len(stale)

    def cell_bytes(self, cell: int) -> int:
        with self._mu:
            if not 0 <= cell < self._n_cells:
                return -1
            off, nbytes = self._off[cell]
            return nbytes if off >= 0 else -1

    def read_cell(self, cell: int) -> np.ndarray | None:
        with self._mu:
            data = self._ram.get(cell)
            if data is not None:
                self._hits += 1
                self._ram[cell] = self._ram.pop(cell)  # LRU touch
            else:
                data = self._pread(cell)
                if data is None:
                    return None
                self._misses += 1
                self._promote_locked(cell, data)
        return np.frombuffer(data, dtype=np.uint8).copy()

    def prefetch(self, cells: np.ndarray) -> int:
        queued = 0
        with self._mu:
            want = [int(c) for c in np.asarray(cells).tolist() if c not in self._ram]
        if not want:
            return 0
        with self._cv:
            for c in want:
                if c not in self._q:
                    self._q.append(c)
                    queued += 1
            self._cv.notify()
        return queued

    def residency(self) -> np.ndarray:
        out = np.zeros(self._n_cells, dtype=np.int64)
        with self._mu:
            for c in range(self._n_cells):
                if self._off[c][0] < 0:
                    out[c] = TIER_ABSENT
                else:
                    out[c] = TIER_RAM if c in self._ram else TIER_DISK
        return out

    def stats(self) -> dict:
        with self._mu:
            disk = sum(1 for off, _ in self._off if off >= 0)
            snap = {
                "ram_cells": len(self._ram),
                "disk_cells": disk,
                "hits": self._hits,
                "misses": self._misses,
                "promotions": self._promotions,
                "demotions": self._demotions,
                "ram_bytes": self._ram_bytes,
            }
        with self._cv:
            snap["queue_len"] = len(self._q)
        return snap

    def drop_ram(self, cell: int) -> None:
        with self._mu:
            buf = self._ram.pop(cell, None)
            if buf is not None:
                self._ram_bytes -= len(buf)
                self._demotions += 1


def make_tier_store(n_cells: int, ram_budget_bytes: int, directory: str):
    """Native ts_* store when the library is available, else the
    pure-Python fallback — same surface either way."""
    os.makedirs(directory, exist_ok=True)
    if get_library() is not None:
        return NativeTieredCellStore(n_cells, ram_budget_bytes, directory)
    return PyTieredCellStore(n_cells, ram_budget_bytes, directory)


class TieredHostPlane:
    """IVF host stage-1 plane served out of the tiered cell store.

    Holds the per-cell geometry (tile_start/tile_count in tile units),
    a decoded-ndarray LRU (the hot tier: cells handed straight to the
    BLAS gather, sized in cells), the routing arrays the batcher's
    prefetch hint needs, and the underlying cell store. ``gather_tiles``
    is the scan-path entry point — drop-in for the flat
    ``plane3[tl].reshape(-1, kf)`` block take in ``ivf._host_topk``.
    """

    def __init__(
        self,
        store,
        *,
        tile_start: np.ndarray,
        tile_count: np.ndarray,
        tile_slots: int,
        kf: int,
        centroids: np.ndarray,
        centroid_norms: np.ndarray,
        hot_cells: int,
        spill_dir: str,
        owns_dir: bool,
    ):
        self._store = store
        self._tile_start = np.asarray(tile_start, np.int64)
        self._tile_count = np.asarray(tile_count, np.int64)
        self._ts = int(tile_slots)
        self._kf = int(kf)
        self._cent = np.ascontiguousarray(centroids, np.float32)
        self._cnorms = np.asarray(centroid_norms, np.float32)
        self._hot_cap = max(1, int(hot_cells))
        self._hot: dict[int, np.ndarray] = {}  # insertion order == LRU
        self._mu = threading.Lock()
        self._spill_dir = spill_dir
        self._owns_dir = owns_dir
        n_tiles = int((self._tile_start + self._tile_count).max(initial=0))
        # tile -> owning cell (cells are tile-contiguous by construction)
        self._tile_cell = np.full(n_tiles, -1, np.int64)
        for c in range(len(self._tile_start)):
            s, n = int(self._tile_start[c]), int(self._tile_count[c])
            self._tile_cell[s : s + n] = c

    @classmethod
    def build(
        cls,
        host_plane: np.ndarray,
        *,
        tile_start: np.ndarray,
        tile_count: np.ndarray,
        tile_slots: int,
        centroids: np.ndarray,
        centroid_norms: np.ndarray,
        store=None,
        hot_cells: int | None = None,
        ram_bytes: int | None = None,
        spill_dir: str | None = None,
    ) -> "TieredHostPlane":
        """Spill a flat [n_slots, kf] host plane into the cell store,
        cell by cell, and return the serving handle. Config knobs
        default to ``configure_tier``'s current values; pass ``store``
        to adopt a prebuilt one (tests)."""
        cfg = tier_config()
        hot = cfg["hot_cells"] if hot_cells is None else int(hot_cells)
        budget = cfg["ram_bytes"] if ram_bytes is None else int(ram_bytes)
        base = cfg["spill_dir"] if spill_dir is None else spill_dir
        owns_dir = False
        if store is None:
            if base is None:
                import tempfile

                base = tempfile.mkdtemp(prefix="oryx-tier-")
                owns_dir = True
            else:
                os.makedirs(base, exist_ok=True)
            store = make_tier_store(len(tile_start), budget, base)
        plane = np.ascontiguousarray(host_plane, np.float32)
        kf = plane.shape[1]
        ts = int(tile_slots)
        starts = np.asarray(tile_start, np.int64)
        counts = np.asarray(tile_count, np.int64)
        for c in range(len(starts)):
            if counts[c] <= 0:
                continue
            lo = int(starts[c]) * ts
            hi = lo + int(counts[c]) * ts
            store.put_cell(c, plane[lo:hi])
        return cls(
            store,
            tile_start=starts,
            tile_count=counts,
            tile_slots=ts,
            kf=kf,
            centroids=centroids,
            centroid_norms=centroid_norms,
            hot_cells=hot,
            spill_dir=base or "",
            owns_dir=owns_dir,
        )

    # -- scan-path surface ----------------------------------------------------

    def routing_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(centroids [kf_pad, n_cells] f32, norms [n_cells]) for the
        batcher's host-side prefetch routing."""
        return self._cent, self._cnorms

    def _cell_array(self, cell: int) -> np.ndarray:
        """Decoded [count*ts, kf] f32 for one cell: hot-LRU hit, or a
        store read (RAM hit / disk promotion) + hot insert. Counts the
        prefetch hit/miss outcome: a gather that finds the cell already
        decoded or warm means the prefetch (or locality) won the race;
        a disk read on the scan path means it lost."""
        with self._mu:
            arr = self._hot.get(cell)
            if arr is not None:
                self._hot[cell] = self._hot.pop(cell)  # LRU touch
                metrics.registry.counter("serving.store.prefetch.hit").inc()
                return arr
        warm = self._store.residency()[cell] == TIER_RAM
        buf = self._store.read_cell(cell)
        if buf is None:  # pragma: no cover - geometry guarantees writes
            raise KeyError(f"tier cell {cell} missing")
        if warm:
            metrics.registry.counter("serving.store.prefetch.hit").inc()
        else:
            metrics.registry.counter("serving.store.prefetch.miss").inc()
        arr = buf.view(np.float32).reshape(-1, self._kf)
        with self._mu:
            self._hot[cell] = arr
            while len(self._hot) > self._hot_cap:
                del self._hot[next(iter(self._hot))]
        return arr

    def gather_tiles(self, tl) -> np.ndarray:
        """Probed tiles as one [len(tl)*ts, kf] f32 slab (tile order
        preserved — the caller's slot-id arrays line up row for row)."""
        tl = np.asarray(tl, np.int64)
        out = np.empty((len(tl) * self._ts, self._kf), np.float32)
        for j, t in enumerate(tl.tolist()):
            c = int(self._tile_cell[t])
            arr = self._cell_array(c)
            o = (t - int(self._tile_start[c])) * self._ts
            out[j * self._ts : (j + 1) * self._ts] = arr[o : o + self._ts]
        self._publish_gauges()
        return out

    def prefetch_cells(self, cells) -> int:
        """Advisory disk->RAM promotion hint for probed cells (async;
        the store's worker thread does the reads)."""
        arr = np.asarray(cells, np.int64)
        with self._mu:
            cold = arr[[int(c) not in self._hot for c in arr.tolist()]]
        n = self._store.prefetch(cold) if len(cold) else 0
        self._publish_gauges()
        return n

    def _publish_gauges(self) -> None:
        st = self._store.stats()
        with self._mu:
            hot = len(self._hot)
        metrics.registry.gauge("serving.store.tier.hbm.cells").set(hot)
        metrics.registry.gauge("serving.store.tier.ram.cells").set(st["ram_cells"])
        metrics.registry.gauge("serving.store.tier.disk.cells").set(st["disk_cells"])

    def stats(self) -> dict:
        st = self._store.stats()
        with self._mu:
            st["hot_cells"] = len(self._hot)
        return st

    def close(self) -> None:
        store, self._store = self._store, None
        if store is not None:
            store.close()
        if self._owns_dir and self._spill_dir:
            import shutil

            shutil.rmtree(self._spill_dir, ignore_errors=True)
