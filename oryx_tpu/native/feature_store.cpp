// Concurrent hash-partitioned ID -> float32-vector store.
//
// The native serving/speed-layer hot-path state: the C++ counterpart of the
// reference's FeatureVectors (app/oryx-app-common/.../als/FeatureVectors
// .java:36-161 — a ConcurrentHashMap guarded by an AutoReadWriteLock) and of
// the hash-partitioned vector store inside ALSServingModel.java:58-124.
// Per SURVEY.md: "any remaining CPU-side hot path that genuinely needs it
// (e.g. the serving layer's concurrent hash-partitioned vector store) gets a
// C++ implementation bound into Python". Vectors live in per-shard
// contiguous slabs so packing a snapshot for device upload is a straight
// memcpy sweep, and readers take per-shard shared locks so lookups/scans run
// in parallel with writes to other shards (ctypes releases the GIL around
// every call).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Shard {
  mutable std::shared_mutex mu;
  std::unordered_map<std::string, int64_t> index;  // id -> slot
  std::vector<std::string> slot_ids;               // slot -> id ("" = free)
  std::vector<float> slab;                         // slot-major vector data
  std::vector<int64_t> free_slots;
  std::unordered_set<std::string> recent;
};

struct Store {
  int64_t dim;
  int64_t num_shards;
  std::vector<Shard> shards;

  Shard& shard_for(const std::string& id) {
    return shards[std::hash<std::string>{}(id) % num_shards];
  }
};

}  // namespace

extern "C" {

void* fs_create(int64_t dim, int64_t num_shards) {
  if (dim <= 0 || num_shards <= 0) return nullptr;
  auto* s = new Store();
  s->dim = dim;
  s->num_shards = num_shards;
  s->shards = std::vector<Shard>(num_shards);
  return s;
}

void fs_destroy(void* p) { delete static_cast<Store*>(p); }

int64_t fs_dim(void* p) { return static_cast<Store*>(p)->dim; }

void fs_set(void* p, const char* id, int64_t id_len, const float* vec) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::unique_lock lock(sh.mu);
  auto it = sh.index.find(key);
  int64_t slot;
  if (it != sh.index.end()) {
    slot = it->second;
  } else if (!sh.free_slots.empty()) {
    slot = sh.free_slots.back();
    sh.free_slots.pop_back();
    sh.slot_ids[slot] = key;
    sh.index.emplace(key, slot);
  } else {
    slot = static_cast<int64_t>(sh.slot_ids.size());
    sh.slot_ids.push_back(key);
    sh.slab.resize(sh.slab.size() + s->dim);
    sh.index.emplace(key, slot);
  }
  std::memcpy(sh.slab.data() + slot * s->dim, vec, s->dim * sizeof(float));
  sh.recent.insert(key);
}

int fs_get(void* p, const char* id, int64_t id_len, float* out) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::shared_lock lock(sh.mu);
  auto it = sh.index.find(key);
  if (it == sh.index.end()) return 0;
  std::memcpy(out, sh.slab.data() + it->second * s->dim, s->dim * sizeof(float));
  return 1;
}

void fs_remove(void* p, const char* id, int64_t id_len) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::unique_lock lock(sh.mu);
  auto it = sh.index.find(key);
  if (it == sh.index.end()) {
    sh.recent.erase(key);
    return;
  }
  int64_t slot = it->second;
  sh.index.erase(it);
  sh.slot_ids[slot].clear();
  sh.free_slots.push_back(slot);
  sh.recent.erase(key);
}

int64_t fs_size(void* p) {
  auto* s = static_cast<Store*>(p);
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    n += static_cast<int64_t>(sh.index.size());
  }
  return n;
}

int64_t fs_recent_count(void* p) {
  auto* s = static_cast<Store*>(p);
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    n += static_cast<int64_t>(sh.recent.size());
  }
  return n;
}

// IDs cross the ABI as a length-prefixed stream: [u32 len][bytes]... — ids
// are arbitrary strings off the wire (JSON), so a newline/NUL-delimited
// protocol would corrupt the id<->row mapping for ids containing the
// delimiter.
static char* write_id(char* out, const std::string& id) {
  uint32_t len = static_cast<uint32_t>(id.size());
  std::memcpy(out, &len, sizeof(len));
  out += sizeof(len);
  std::memcpy(out, id.data(), id.size());
  return out + id.size();
}

static int64_t id_stream_size(const std::string& id) {
  return static_cast<int64_t>(sizeof(uint32_t) + id.size());
}

// Pack a consistent snapshot: all shard locks are held (shared) for the
// duration. Returns n on success, -1 when a buffer is too small (caller
// re-sizes from *mat_needed / *ids_needed and retries), with the needed
// capacities always reported.
int64_t fs_pack(void* p, float* mat_out, int64_t mat_cap, char* ids_out,
                int64_t ids_cap, int64_t* mat_needed, int64_t* ids_needed,
                int recent_only) {
  auto* s = static_cast<Store*>(p);
  std::vector<std::shared_lock<std::shared_mutex>> locks;
  locks.reserve(s->shards.size());
  for (auto& sh : s->shards) locks.emplace_back(sh.mu);

  int64_t n = 0, ids_len = 0;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) {
          n++;
          ids_len += id_stream_size(id);
        }
      }
    } else {
      n += static_cast<int64_t>(sh.index.size());
      for (const auto& kv : sh.index) ids_len += id_stream_size(kv.first);
    }
  }
  *mat_needed = n * s->dim;
  *ids_needed = ids_len;
  if (n * s->dim > mat_cap || ids_len > ids_cap) return -1;

  int64_t row = 0;
  char* idp = ids_out;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        auto it = sh.index.find(id);
        if (it == sh.index.end()) continue;
        std::memcpy(mat_out + row * s->dim, sh.slab.data() + it->second * s->dim,
                    s->dim * sizeof(float));
        idp = write_id(idp, id);
        row++;
      }
    } else {
      for (const auto& kv : sh.index) {
        std::memcpy(mat_out + row * s->dim, sh.slab.data() + kv.second * s->dim,
                    s->dim * sizeof(float));
        idp = write_id(idp, kv.first);
        row++;
      }
    }
  }
  return row;
}

// IDs only, without copying vector data (the /user/allIDs-style calls and
// rotation bookkeeping need just the key set).
int64_t fs_ids(void* p, char* ids_out, int64_t ids_cap, int64_t* ids_needed,
               int recent_only) {
  auto* s = static_cast<Store*>(p);
  std::vector<std::shared_lock<std::shared_mutex>> locks;
  locks.reserve(s->shards.size());
  for (auto& sh : s->shards) locks.emplace_back(sh.mu);

  int64_t n = 0, ids_len = 0;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) {
          n++;
          ids_len += id_stream_size(id);
        }
      }
    } else {
      n += static_cast<int64_t>(sh.index.size());
      for (const auto& kv : sh.index) ids_len += id_stream_size(kv.first);
    }
  }
  *ids_needed = ids_len;
  if (ids_len > ids_cap) return -1;

  char* idp = ids_out;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) idp = write_id(idp, id);
      }
    } else {
      for (const auto& kv : sh.index) idp = write_id(idp, kv.first);
    }
  }
  return n;
}

// V^T V over all vectors, accumulated in double (FeatureVectors.getVTV).
void fs_vtv(void* p, double* out) {
  auto* s = static_cast<Store*>(p);
  const int64_t k = s->dim;
  std::memset(out, 0, k * k * sizeof(double));
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    for (const auto& kv : sh.index) {
      const float* v = sh.slab.data() + kv.second * k;
      for (int64_t i = 0; i < k; i++) {
        const double vi = v[i];
        double* row = out + i * k;
        for (int64_t j = i; j < k; j++) row[j] += vi * v[j];
      }
    }
  }
  for (int64_t i = 0; i < k; i++)
    for (int64_t j = 0; j < i; j++) out[i * k + j] = out[j * k + i];
}

// Rotation reconciliation (FeatureVectors.retainRecentAndIDs:131-136): keep
// ids present in the new model (length-prefixed `keep` stream) OR written
// since the last rotation, then reset recency.
void fs_retain(void* p, const char* keep, int64_t keep_len) {
  auto* s = static_cast<Store*>(p);
  std::unordered_set<std::string> keep_set;
  const char* q = keep;
  const char* end = keep + keep_len;
  while (q + sizeof(uint32_t) <= end) {
    uint32_t len;
    std::memcpy(&len, q, sizeof(len));
    q += sizeof(len);
    if (q + len > end) break;  // truncated stream: ignore the tail
    keep_set.emplace(q, len);
    q += len;
  }
  for (auto& sh : s->shards) {
    std::unique_lock lock(sh.mu);
    std::vector<std::string> drop;
    for (const auto& kv : sh.index) {
      if (!keep_set.count(kv.first) && !sh.recent.count(kv.first)) {
        drop.push_back(kv.first);
      }
    }
    for (const auto& id : drop) {
      auto it = sh.index.find(id);
      sh.slot_ids[it->second].clear();
      sh.free_slots.push_back(it->second);
      sh.index.erase(it);
    }
    sh.recent.clear();
  }
}

}  // extern "C"
