// Concurrent hash-partitioned ID -> float32-vector store.
//
// The native serving/speed-layer hot-path state: the C++ counterpart of the
// reference's FeatureVectors (app/oryx-app-common/.../als/FeatureVectors
// .java:36-161 — a ConcurrentHashMap guarded by an AutoReadWriteLock) and of
// the hash-partitioned vector store inside ALSServingModel.java:58-124.
// Per SURVEY.md: "any remaining CPU-side hot path that genuinely needs it
// (e.g. the serving layer's concurrent hash-partitioned vector store) gets a
// C++ implementation bound into Python". Vectors live in per-shard
// contiguous slabs so packing a snapshot for device upload is a straight
// memcpy sweep, and readers take per-shard shared locks so lookups/scans run
// in parallel with writes to other shards (ctypes releases the GIL around
// every call).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <charconv>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <list>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Shard {
  mutable std::shared_mutex mu;
  std::unordered_map<std::string, int64_t> index;  // id -> slot
  std::vector<std::string> slot_ids;               // slot -> id ("" = free)
  std::vector<float> slab;                         // slot-major vector data
  std::vector<int64_t> free_slots;
  std::unordered_set<std::string> recent;
};

struct Store {
  int64_t dim;
  int64_t num_shards;
  std::vector<Shard> shards;

  Shard& shard_for(const std::string& id) {
    return shards[std::hash<std::string>{}(id) % num_shards];
  }
};

}  // namespace

extern "C" {

void* fs_create(int64_t dim, int64_t num_shards) {
  if (dim <= 0 || num_shards <= 0) return nullptr;
  auto* s = new Store();
  s->dim = dim;
  s->num_shards = num_shards;
  s->shards = std::vector<Shard>(num_shards);
  return s;
}

void fs_destroy(void* p) { delete static_cast<Store*>(p); }

int64_t fs_dim(void* p) { return static_cast<Store*>(p)->dim; }

void fs_set(void* p, const char* id, int64_t id_len, const float* vec) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::unique_lock lock(sh.mu);
  auto it = sh.index.find(key);
  int64_t slot;
  if (it != sh.index.end()) {
    slot = it->second;
  } else if (!sh.free_slots.empty()) {
    slot = sh.free_slots.back();
    sh.free_slots.pop_back();
    sh.slot_ids[slot] = key;
    sh.index.emplace(key, slot);
  } else {
    slot = static_cast<int64_t>(sh.slot_ids.size());
    sh.slot_ids.push_back(key);
    sh.slab.resize(sh.slab.size() + s->dim);
    sh.index.emplace(key, slot);
  }
  std::memcpy(sh.slab.data() + slot * s->dim, vec, s->dim * sizeof(float));
  sh.recent.insert(key);
}

int fs_get(void* p, const char* id, int64_t id_len, float* out) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::shared_lock lock(sh.mu);
  auto it = sh.index.find(key);
  if (it == sh.index.end()) return 0;
  std::memcpy(out, sh.slab.data() + it->second * s->dim, s->dim * sizeof(float));
  return 1;
}

void fs_remove(void* p, const char* id, int64_t id_len) {
  auto* s = static_cast<Store*>(p);
  std::string key(id, id_len);
  Shard& sh = s->shard_for(key);
  std::unique_lock lock(sh.mu);
  auto it = sh.index.find(key);
  if (it == sh.index.end()) {
    sh.recent.erase(key);
    return;
  }
  int64_t slot = it->second;
  sh.index.erase(it);
  sh.slot_ids[slot].clear();
  sh.free_slots.push_back(slot);
  sh.recent.erase(key);
}

int64_t fs_size(void* p) {
  auto* s = static_cast<Store*>(p);
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    n += static_cast<int64_t>(sh.index.size());
  }
  return n;
}

int64_t fs_recent_count(void* p) {
  auto* s = static_cast<Store*>(p);
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    n += static_cast<int64_t>(sh.recent.size());
  }
  return n;
}

// IDs cross the ABI as a length-prefixed stream: [u32 len][bytes]... — ids
// are arbitrary strings off the wire (JSON), so a newline/NUL-delimited
// protocol would corrupt the id<->row mapping for ids containing the
// delimiter.
static char* write_id(char* out, const std::string& id) {
  uint32_t len = static_cast<uint32_t>(id.size());
  std::memcpy(out, &len, sizeof(len));
  out += sizeof(len);
  std::memcpy(out, id.data(), id.size());
  return out + id.size();
}

static int64_t id_stream_size(const std::string& id) {
  return static_cast<int64_t>(sizeof(uint32_t) + id.size());
}

// Pack a consistent snapshot: all shard locks are held (shared) for the
// duration. Returns n on success, -1 when a buffer is too small (caller
// re-sizes from *mat_needed / *ids_needed and retries), with the needed
// capacities always reported.
int64_t fs_pack(void* p, float* mat_out, int64_t mat_cap, char* ids_out,
                int64_t ids_cap, int64_t* mat_needed, int64_t* ids_needed,
                int recent_only) {
  auto* s = static_cast<Store*>(p);
  std::vector<std::shared_lock<std::shared_mutex>> locks;
  locks.reserve(s->shards.size());
  for (auto& sh : s->shards) locks.emplace_back(sh.mu);

  int64_t n = 0, ids_len = 0;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) {
          n++;
          ids_len += id_stream_size(id);
        }
      }
    } else {
      n += static_cast<int64_t>(sh.index.size());
      for (const auto& kv : sh.index) ids_len += id_stream_size(kv.first);
    }
  }
  *mat_needed = n * s->dim;
  *ids_needed = ids_len;
  if (n * s->dim > mat_cap || ids_len > ids_cap) return -1;

  int64_t row = 0;
  char* idp = ids_out;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        auto it = sh.index.find(id);
        if (it == sh.index.end()) continue;
        std::memcpy(mat_out + row * s->dim, sh.slab.data() + it->second * s->dim,
                    s->dim * sizeof(float));
        idp = write_id(idp, id);
        row++;
      }
    } else {
      for (const auto& kv : sh.index) {
        std::memcpy(mat_out + row * s->dim, sh.slab.data() + kv.second * s->dim,
                    s->dim * sizeof(float));
        idp = write_id(idp, kv.first);
        row++;
      }
    }
  }
  return row;
}

// IDs only, without copying vector data (the /user/allIDs-style calls and
// rotation bookkeeping need just the key set).
int64_t fs_ids(void* p, char* ids_out, int64_t ids_cap, int64_t* ids_needed,
               int recent_only) {
  auto* s = static_cast<Store*>(p);
  std::vector<std::shared_lock<std::shared_mutex>> locks;
  locks.reserve(s->shards.size());
  for (auto& sh : s->shards) locks.emplace_back(sh.mu);

  int64_t n = 0, ids_len = 0;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) {
          n++;
          ids_len += id_stream_size(id);
        }
      }
    } else {
      n += static_cast<int64_t>(sh.index.size());
      for (const auto& kv : sh.index) ids_len += id_stream_size(kv.first);
    }
  }
  *ids_needed = ids_len;
  if (ids_len > ids_cap) return -1;

  char* idp = ids_out;
  for (auto& sh : s->shards) {
    if (recent_only) {
      for (const auto& id : sh.recent) {
        if (sh.index.count(id)) idp = write_id(idp, id);
      }
    } else {
      for (const auto& kv : sh.index) idp = write_id(idp, kv.first);
    }
  }
  return n;
}

// V^T V over all vectors, accumulated in double (FeatureVectors.getVTV).
void fs_vtv(void* p, double* out) {
  auto* s = static_cast<Store*>(p);
  const int64_t k = s->dim;
  std::memset(out, 0, k * k * sizeof(double));
  for (auto& sh : s->shards) {
    std::shared_lock lock(sh.mu);
    for (const auto& kv : sh.index) {
      const float* v = sh.slab.data() + kv.second * k;
      for (int64_t i = 0; i < k; i++) {
        const double vi = v[i];
        double* row = out + i * k;
        for (int64_t j = i; j < k; j++) row[j] += vi * v[j];
      }
    }
  }
  for (int64_t i = 0; i < k; i++)
    for (int64_t j = 0; j < i; j++) out[i * k + j] = out[j * k + i];
}

// Rotation reconciliation (FeatureVectors.retainRecentAndIDs:131-136): keep
// ids present in the new model OR written since the last rotation, then
// reset recency. Ids arrive as (offsets[n+1], payload): id i is
// payload[offsets[i]..offsets[i+1]) — offsets build vectorized in numpy,
// unlike the per-id length-prefix packing this replaces.
void fs_retain(void* p, const int64_t* offs, const char* payload, int64_t n) {
  auto* s = static_cast<Store*>(p);
  std::unordered_set<std::string> keep_set;
  keep_set.reserve(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    keep_set.emplace(payload + offs[i], static_cast<size_t>(offs[i + 1] - offs[i]));
  }
  for (auto& sh : s->shards) {
    std::unique_lock lock(sh.mu);
    std::vector<std::string> drop;
    for (const auto& kv : sh.index) {
      if (!keep_set.count(kv.first) && !sh.recent.count(kv.first)) {
        drop.push_back(kv.first);
      }
    }
    for (const auto& id : drop) {
      auto it = sh.index.find(id);
      sh.slot_ids[it->second].clear();
      sh.free_slots.push_back(it->second);
      sh.index.erase(it);
    }
    sh.recent.clear();
  }
}

// Batched insert/update: ids as (offsets, payload), vectors mat[n][dim].
// Same slot logic as fs_set, but the whole batch runs without returning
// to Python — the speed layer's self-consume thread applies 100K+
// deltas/s through here (one ctypes fs_set per delta cost ~60us on a
// 1-core host; the batch call amortizes it away).
void fs_set_batch(void* p, const int64_t* offs, const char* payload,
                  int64_t n, const float* mat) {
  auto* s = static_cast<Store*>(p);
  std::string key;
  for (int64_t i = 0; i < n; ++i) {
    key.assign(payload + offs[i], static_cast<size_t>(offs[i + 1] - offs[i]));
    Shard& sh = s->shard_for(key);
    std::unique_lock lock(sh.mu);
    auto it = sh.index.find(key);
    int64_t slot;
    if (it != sh.index.end()) {
      slot = it->second;
    } else if (!sh.free_slots.empty()) {
      slot = sh.free_slots.back();
      sh.free_slots.pop_back();
      sh.slot_ids[slot] = key;
      sh.index.emplace(key, slot);
    } else {
      slot = static_cast<int64_t>(sh.slot_ids.size());
      sh.slot_ids.push_back(key);
      sh.slab.resize(sh.slab.size() + s->dim);
      sh.index.emplace(key, slot);
    }
    std::memcpy(sh.slab.data() + slot * s->dim, mat + i * s->dim,
                s->dim * sizeof(float));
    sh.recent.insert(key);
  }
}

// Batched lookup: ids as (offsets, payload), vectors written to
// out_mat[n][dim] (rows for missing ids left untouched), out_valid[i]
// set 1/0. One lock acquisition per id, no Python between lookups —
// the speed layer fetches every event's user+item vector in one call.
int64_t fs_get_batch(void* p, const int64_t* offs, const char* payload,
                     int64_t n, float* out_mat, uint8_t* out_valid) {
  auto* s = static_cast<Store*>(p);
  std::string key;
  for (int64_t i = 0; i < n; ++i) {
    key.assign(payload + offs[i], static_cast<size_t>(offs[i + 1] - offs[i]));
    Shard& sh = s->shard_for(key);
    std::shared_lock lock(sh.mu);
    auto it = sh.index.find(key);
    if (it == sh.index.end()) {
      out_valid[i] = 0;
    } else {
      std::memcpy(out_mat + i * s->dim, sh.slab.data() + it->second * s->dim,
                  s->dim * sizeof(float));
      out_valid[i] = 1;
    }
  }
  return n;
}

// Format n rows of float32 [n][k] as JSON number arrays "[v,v,...]" with
// %.9g (shortest round-trip for float32 needs <= 9 significant digits).
// Rows are written back-to-back; offsets[i]..offsets[i+1] bounds row i.
// Returns total bytes, or -1 if cap is too small (needed reported).
// This is the speed layer's update-serialization hot path: Python's json
// encoder spends ~1us per float printing 17-digit float64 reprs.
int64_t json_format_vectors(const float* mat, int64_t n, int64_t k,
                            char* out, int64_t cap, int64_t* offsets,
                            int64_t* needed) {
  // worst case per float: sign + 9 digits + '.' + 'e+38' + ',' ~ 18 bytes
  int64_t worst = n * (2 + k * 18);
  *needed = worst;
  if (cap < worst) return -1;
  char* w = out;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = w - out;
    *w++ = '[';
    const float* row = mat + i * k;
    for (int64_t j = 0; j < k; ++j) {
      if (j) *w++ = ',';
      double v = static_cast<double>(row[j]);
      int len = snprintf(w, 32, "%.9g", v);
      // JSON has no Infinity/NaN literals; clamp to 0 like a poisoned
      // update would be dropped downstream anyway
      if (!std::isfinite(v)) {
        len = snprintf(w, 32, "0");
      }
      w += len;
    }
    *w++ = ']';
  }
  offsets[n] = w - out;
  return w - out;
}

// --- speed-layer update-message assembly -----------------------------------
//
// Emit complete update-topic messages ["X"|"Y", id, [v,...], [otherId]]
// (ALSSpeedModelManager.toUpdateJSON wire format) for n rows at once,
// formatted in parallel across threads. Rows are written into fixed-
// stride per-row regions of `out` (so threads never contend); true
// bounds come back via starts[i]/ends[i]. Gaps between rows are
// space-filled so the caller may decode the whole buffer as ASCII.

namespace {

inline char* json_escape_append(char* w, const char* s, uint32_t len) {
  *w++ = '"';
  for (uint32_t i = 0; i < len; ++i) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    if (c == '"' || c == '\\') {
      *w++ = '\\';
      *w++ = static_cast<char>(c);
    } else if (c < 0x20) {
      w += snprintf(w, 8, "\\u%04x", c);
    } else {
      *w++ = static_cast<char>(c);  // UTF-8 bytes pass through
    }
  }
  *w++ = '"';
  return w;
}

// 10^k for k in [-30, 53]: covers scaling any finite float32 (decimal
// exponent -45..38) into the nine-digit window [1e8, 1e9).
static const double kPow10[84] = {
    1e-30, 1e-29, 1e-28, 1e-27, 1e-26, 1e-25, 1e-24, 1e-23, 1e-22, 1e-21,
    1e-20, 1e-19, 1e-18, 1e-17, 1e-16, 1e-15, 1e-14, 1e-13, 1e-12, 1e-11,
    1e-10, 1e-9,  1e-8,  1e-7,  1e-6,  1e-5,  1e-4,  1e-3,  1e-2,  1e-1,
    1e0,   1e1,   1e2,   1e3,   1e4,   1e5,   1e6,   1e7,   1e8,   1e9,
    1e10,  1e11,  1e12,  1e13,  1e14,  1e15,  1e16,  1e17,  1e18,  1e19,
    1e20,  1e21,  1e22,  1e23,  1e24,  1e25,  1e26,  1e27,  1e28,  1e29,
    1e30,  1e31,  1e32,  1e33,  1e34,  1e35,  1e36,  1e37,  1e38,  1e39,
    1e40,  1e41,  1e42,  1e43,  1e44,  1e45,  1e46,  1e47,  1e48,  1e49,
    1e50,  1e51,  1e52,  1e53,
};
static inline double pow10tab(int k) { return kPow10[k + 30]; }

static char* float_append_9g(char* w, float f) {
  if (f == 0.0f) {
    if (std::signbit(f)) *w++ = '-';
    *w++ = '0';
    return w;
  }
  double d = static_cast<double>(f);
  if (d < 0.0) {
    *w++ = '-';
    d = -d;
  }
  // e10 = floor(log10(d)): estimate from the binary exponent (floor(e2 *
  // log10 2) is off by at most one, always low), confirm by comparison
  uint64_t bits;
  std::memcpy(&bits, &d, 8);
  int e2 = static_cast<int>((bits >> 52) & 0x7FF) - 1023;  // d is a normal double
  int e10 = static_cast<int>((e2 * 315653) >> 20);         // 315653/2^20 ~= log10(2)
  if (e10 < -45) e10 = -45;                                // clamp for table safety
  if (d >= pow10tab(e10 + 1)) ++e10;
  double scaled = d * pow10tab(8 - e10);
  // inexact power-of-ten boundaries can land one decade off; renormalize
  if (scaled >= 1e9) {
    ++e10;
    scaled = d * pow10tab(8 - e10);
  } else if (scaled < 1e8) {
    --e10;
    scaled = d * pow10tab(8 - e10);
  }
  uint64_t n = static_cast<uint64_t>(std::llround(scaled));
  if (n >= 1000000000ull) {  // 999999999.6 rounded up a decade
    n /= 10;
    ++e10;
  }
  int nd = 9;
  while (nd > 1 && n % 10 == 0) {  // %g strips trailing zeros
    n /= 10;
    --nd;
  }
  char digs[10];
  auto res = std::to_chars(digs, digs + sizeof digs, n);  // integral: always available
  int len = static_cast<int>(res.ptr - digs);
  if (e10 >= -4 && e10 < 9) {  // %g fixed notation band for precision 9
    if (e10 >= len - 1) {
      std::memcpy(w, digs, static_cast<size_t>(len));
      w += len;
      for (int i = len - 1; i < e10; ++i) *w++ = '0';
    } else if (e10 >= 0) {
      std::memcpy(w, digs, static_cast<size_t>(e10 + 1));
      w += e10 + 1;
      *w++ = '.';
      std::memcpy(w, digs + e10 + 1, static_cast<size_t>(len - e10 - 1));
      w += len - e10 - 1;
    } else {
      *w++ = '0';
      *w++ = '.';
      for (int i = 0; i < -e10 - 1; ++i) *w++ = '0';
      std::memcpy(w, digs, static_cast<size_t>(len));
      w += len;
    }
    return w;
  }
  *w++ = digs[0];  // scientific: d[.ddd]e{+,-}XX
  if (len > 1) {
    *w++ = '.';
    std::memcpy(w, digs + 1, static_cast<size_t>(len - 1));
    w += len - 1;
  }
  *w++ = 'e';
  *w++ = e10 < 0 ? '-' : '+';
  int ae = e10 < 0 ? -e10 : e10;
  *w++ = static_cast<char>('0' + ae / 10);  // decimal exponent is 2 digits (<= 45)
  *w++ = static_cast<char>('0' + ae % 10);
  return w;
}

// Shortest round-trip decimal (Ryu via std::to_chars on the FLOAT
// overload — the same contract as Java's Float.toString, which is what
// the reference's toUpdateJSON emits). Averages ~8 chars/component vs 12
// for fixed 9-significant-digit forms: the update topic is the speed
// layer's dominant byte stream, so this is both a format-parity and an
// I/O-bandwidth win.
inline char* float_append(char* w, float f) {
  if (!std::isfinite(f)) {
    *w++ = '0';  // JSON has no NaN/Infinity literals
    return w;
  }
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto res = std::to_chars(w, w + 32, f);
  return res.ptr;
#else
  // libstdc++ < 11 has no floating-point to_chars. snprintf("%.9g") costs
  // ~250ns per component, which at 50 components x ~60K updates per
  // micro-batch is the single largest line item in the publish stage — so
  // the fallback is a hand-rolled 9-significant-digit %g-equivalent
  // (~30ns): scale into [1e8, 1e9), round to a 9-digit integer, strip
  // trailing zeros, lay the digits out under printf %g placement rules.
  //
  // Round-trip safety: the scaled value carries <= ~1e-6 units of error
  // (one table lookup + one multiply, each 0.5 ulp of a double), so the
  // emitted 9-digit decimal sits within 0.51 units of the exact value,
  // while adjacent float32s are >= 5.9 units apart at the tightest point
  // (2^-24 relative spacing against 1e-9 relative resolution) — parsing
  // always recovers the original float. Divergence from glibc %.9g is
  // possible only on exact decimal ties (glibc rounds half-to-even, this
  // rounds half-away, e.g. 1048576.625f) — both forms round-trip, and
  // self-apply byte-exact skip only ever compares bytes from one build.
  return float_append_9g(w, f);
#endif
}

}  // namespace

// Per-row worst case for als_format_updates' fixed stride.
int64_t als_update_row_cap(int64_t k, int64_t max_id_len) {
  return 16 + 2 * (6 * max_id_len + 2) + 2 + k * 18;
}

// Shared scaffold for the update formatters: each thread writes its rows
// back-to-back inside its own stride-spaced region, then regions compact
// into one contiguous byte run (row offsets shifted). write_row appends
// row i at w and returns the new write head. Returns total bytes.
static int64_t format_rows_parallel(
    int64_t n, int64_t stride, char* out, int64_t* starts, int64_t* ends,
    int64_t num_threads, const std::function<char*(int64_t, char*)>& write_row) {
  if (n == 0) return 0;
  if (num_threads < 1) num_threads = 1;
  if (num_threads > n) num_threads = n;
  const int64_t chunk = (n + num_threads - 1) / num_threads;
  std::vector<int64_t> region_end(num_threads, 0);
  auto worker = [&](int64_t t, int64_t lo, int64_t hi) {
    char* w = out + lo * stride;
    for (int64_t i = lo; i < hi; ++i) {
      starts[i] = w - out;
      w = write_row(i, w);
      ends[i] = w - out;
    }
    region_end[t] = w - out;
  };
  if (num_threads == 1) {
    worker(0, 0, n);
    return region_end[0];
  }
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < num_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, t, lo, hi);
  }
  int64_t used_threads = static_cast<int64_t>(threads.size());
  for (auto& th : threads) th.join();
  // compact regions into one contiguous run, shifting row offsets
  int64_t dst = region_end[0];
  for (int64_t t = 1; t < used_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    int64_t src = lo * stride;
    int64_t len = region_end[t] - src;
    std::memmove(out + dst, out + src, static_cast<size_t>(len));
    int64_t delta = dst - src;
    for (int64_t i = lo; i < hi; ++i) {
      starts[i] += delta;
      ends[i] += delta;
    }
    dst += len;
  }
  return dst;
}

// ["T","id",[v..]] row prefix shared by both formatter variants.
static char* append_row_head(char* w, char matrix_tag, const float* row,
                             int64_t k, const int64_t* id_offs,
                             const char* id_payload, int64_t i) {
  *w++ = '[';
  *w++ = '"';
  *w++ = matrix_tag;
  *w++ = '"';
  *w++ = ',';
  w = json_escape_append(w, id_payload + id_offs[i],
                         static_cast<uint32_t>(id_offs[i + 1] - id_offs[i]));
  *w++ = ',';
  *w++ = '[';
  for (int64_t j = 0; j < k; ++j) {
    if (j) *w++ = ',';
    w = float_append(w, row[j]);
  }
  *w++ = ']';
  return w;
}

// matrix_tag: 'X' or 'Y'. ids/other_ids arrive as (offsets[n+1], payload)
// pairs. include_known: emit the trailing [otherId] element. out must hold
// n * als_update_row_cap(k, max_id_len) bytes.
int64_t als_format_updates(const float* mat, int64_t n, int64_t k,
                           const int64_t* id_offs, const char* id_payload,
                           const int64_t* other_offs, const char* other_payload,
                           char matrix_tag, int include_known,
                           int64_t max_id_len, char* out,
                           int64_t* starts, int64_t* ends, int64_t num_threads) {
  const int64_t stride = als_update_row_cap(k, max_id_len);
  return format_rows_parallel(
      n, stride, out, starts, ends, num_threads, [&](int64_t i, char* w) {
        w = append_row_head(w, matrix_tag, mat + i * k, k, id_offs, id_payload, i);
        if (include_known) {
          *w++ = ',';
          *w++ = '[';
          w = json_escape_append(
              w, other_payload + other_offs[i],
              static_cast<uint32_t>(other_offs[i + 1] - other_offs[i]));
          *w++ = ']';
        }
        *w++ = ']';
        return w;
      });
}

// Multi-known variant: row i carries the known-id list
// known_ids[known_row_offs[i] .. known_row_offs[i+1]) where each known id
// j is known_payload[known_offs[j] .. known_offs[j+1]). Emits
// ["T","id",[v..],["k1","k2",...]] (empty list allowed). The caller
// supplies the per-row stride (worst case including its widest known list).
int64_t als_format_updates_multi(
    const float* mat, int64_t n, int64_t k,
    const int64_t* id_offs, const char* id_payload,
    const int64_t* known_row_offs, const int64_t* known_offs,
    const char* known_payload, char matrix_tag, int64_t stride,
    char* out, int64_t* starts, int64_t* ends, int64_t num_threads) {
  return format_rows_parallel(
      n, stride, out, starts, ends, num_threads, [&](int64_t i, char* w) {
        w = append_row_head(w, matrix_tag, mat + i * k, k, id_offs, id_payload, i);
        *w++ = ',';
        *w++ = '[';
        for (int64_t g = known_row_offs[i]; g < known_row_offs[i + 1]; ++g) {
          if (g > known_row_offs[i]) *w++ = ',';
          w = json_escape_append(
              w, known_payload + known_offs[g],
              static_cast<uint32_t>(known_offs[g + 1] - known_offs[g]));
        }
        *w++ = ']';
        *w++ = ']';
        return w;
      });
}

// Parse a comma-separated run of decimal floats ("1.5,-2,3e-4,nan") into
// out[cap]. Returns the count parsed, or -1 on a malformed token — the
// caller falls back to numpy/per-record parsing. This is the speed
// layer's self-consume hot path: a 50-feature UP delta block at 100K+
// deltas/s is ~10M float tokens/batch, and numpy's S->float astype costs
// ~160ns/token on one core vs ~30ns for a bare strtof loop.
int64_t parse_float_csv(const char* buf, int64_t len, float* out, int64_t cap) {
  // std::from_chars: locale-free and ~3x strtof — this parse is the
  // speed layer's per-delta floor when re-applying its own update topic
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  if (len == 0) return 0;
  while (p < end) {
    if (n >= cap) return -1;
    // from_chars (unlike the strtof it replaced) rejects leading spaces;
    // tolerate them so json.dumps-style "a, b" fallback formatting stays
    // on the fast path
    while (p < end && *p == ' ') ++p;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    auto [next, ec] = std::from_chars(p, end, out[n]);
    if (ec != std::errc() || next == p) return -1;  // malformed token
    ++n;
    p = next;
#else
    // libstdc++ < 11: bounded strtof on a stack copy (the buffer from
    // Python is NUL-terminated, but don't rely on it)
    char tok[64];
    const char* stop = static_cast<const char*>(memchr(p, ',', end - p));
    if (stop == nullptr) stop = end;
    size_t tlen = static_cast<size_t>(stop - p);
    if (tlen == 0 || tlen >= sizeof(tok)) return -1;
    memcpy(tok, p, tlen);
    tok[tlen] = '\0';
    char* tend = nullptr;
    out[n] = strtof(tok, &tend);
    if (tend != tok + tlen) return -1;  // malformed token
    ++n;
    p = stop;
#endif
    if (p < end) {
      if (*p != ',') return -1;
      ++p;
    }
  }
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Tiered cell store (ts_*): the RAM->disk half of the serving layer's
// three-tier item plane (docs/serving-scan.md). Cell blocks — the
// cell-contiguous f32 slabs the IVF host scan gathers — live in one
// append-only backing file mmap'd as the COLD tier; a byte-budgeted LRU
// of malloc'd copies is the WARM tier; the HOT (device/HBM-standing)
// tier is the Python-side ndarray cache in native/store.py. Reads promote
// (disk -> RAM) and count hit/miss; ts_prefetch enqueues cells for a
// background thread so probed cells stream RAM-ward ahead of the scan —
// GIL-free, since ctypes releases the GIL around every call.
// ---------------------------------------------------------------------------

namespace {

struct TierStore {
  std::string path;
  int fd = -1;

  // cell table + mapping, under one shared mutex (writes are rare: the
  // maintainer re-tiers after compaction; reads/promotes dominate)
  mutable std::shared_mutex mu;
  struct CellRef {
    int64_t off = -1;
    int64_t bytes = 0;
  };
  std::vector<CellRef> cells;
  int64_t file_bytes = 0;
  uint8_t* map = nullptr;
  int64_t map_bytes = 0;

  // warm tier: cell id -> heap copy, LRU-evicted under a byte budget
  std::mutex ram_mu;
  std::unordered_map<int64_t, std::vector<uint8_t>> ram;
  std::list<int64_t> lru;  // front = most recently touched
  std::unordered_map<int64_t, std::list<int64_t>::iterator> lru_pos;
  int64_t ram_budget = 0;
  int64_t ram_bytes = 0;

  std::atomic<int64_t> hits{0};
  std::atomic<int64_t> misses{0};
  std::atomic<int64_t> promotions{0};
  std::atomic<int64_t> demotions{0};

  // prefetch worker
  std::thread worker;
  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<int64_t> queue;
  bool stop = false;
};

// caller holds no locks; copies cell bytes out of the mmap (growing the
// mapping first if the cell was appended after the last map). Returns
// bytes copied or -1.
int64_t tier_disk_read(TierStore* t, int64_t cell, uint8_t* out, int64_t cap) {
  std::shared_lock rlock(t->mu);
  if (cell < 0 || cell >= static_cast<int64_t>(t->cells.size())) return -1;
  TierStore::CellRef ref = t->cells[cell];
  if (ref.off < 0) return -1;
  if (ref.bytes > cap) return -1;
  if (ref.off + ref.bytes > t->map_bytes) {
    rlock.unlock();
    std::unique_lock wlock(t->mu);
    if (ref.off + ref.bytes > t->map_bytes) {  // re-check under the write lock
      if (t->map != nullptr) munmap(t->map, t->map_bytes);
      t->map = nullptr;
      t->map_bytes = 0;
      void* m = mmap(nullptr, t->file_bytes, PROT_READ, MAP_SHARED, t->fd, 0);
      if (m == MAP_FAILED) return -1;
      t->map = static_cast<uint8_t*>(m);
      t->map_bytes = t->file_bytes;
    }
    std::memcpy(out, t->map + ref.off, ref.bytes);
    return ref.bytes;
  }
  std::memcpy(out, t->map + ref.off, ref.bytes);
  return ref.bytes;
}

// promote a cell into the warm tier (no-op if present); evicts LRU tail
// cells past the byte budget. Returns 1 if the cell is RAM-resident on
// exit.
int tier_promote(TierStore* t, int64_t cell) {
  {
    std::lock_guard g(t->ram_mu);
    auto it = t->ram.find(cell);
    if (it != t->ram.end()) {
      auto pos = t->lru_pos.find(cell);
      t->lru.erase(pos->second);
      t->lru.push_front(cell);
      pos->second = t->lru.begin();
      return 1;
    }
  }
  int64_t bytes;
  {
    std::shared_lock rlock(t->mu);
    if (cell < 0 || cell >= static_cast<int64_t>(t->cells.size())) return 0;
    bytes = t->cells[cell].bytes;
    if (t->cells[cell].off < 0) return 0;
  }
  if (bytes > t->ram_budget) return 0;  // would evict everything: skip
  std::vector<uint8_t> buf(bytes);
  if (tier_disk_read(t, cell, buf.data(), bytes) != bytes) return 0;
  std::lock_guard g(t->ram_mu);
  if (t->ram.count(cell)) return 1;  // raced another promote: keep theirs
  while (t->ram_bytes + bytes > t->ram_budget && !t->lru.empty()) {
    int64_t victim = t->lru.back();
    t->lru.pop_back();
    t->lru_pos.erase(victim);
    auto vit = t->ram.find(victim);
    t->ram_bytes -= static_cast<int64_t>(vit->second.size());
    t->ram.erase(vit);
    t->demotions.fetch_add(1, std::memory_order_relaxed);
  }
  t->ram_bytes += bytes;
  t->ram.emplace(cell, std::move(buf));
  t->lru.push_front(cell);
  t->lru_pos[cell] = t->lru.begin();
  t->promotions.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

void tier_worker(TierStore* t) {
  for (;;) {
    int64_t cell;
    {
      std::unique_lock lk(t->q_mu);
      t->q_cv.wait(lk, [t] { return t->stop || !t->queue.empty(); });
      if (t->stop) return;
      cell = t->queue.front();
      t->queue.pop_front();
    }
    tier_promote(t, cell);
  }
}

}  // namespace

extern "C" {

void* ts_create(const char* dir, int64_t dir_len, int64_t n_cells,
                int64_t ram_budget_bytes) {
  if (n_cells <= 0 || ram_budget_bytes < 0) return nullptr;
  auto* t = new TierStore();
  t->path = std::string(dir, dir_len) + "/cells.bin";
  t->fd = open(t->path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (t->fd < 0) {
    delete t;
    return nullptr;
  }
  t->cells.resize(n_cells);
  t->ram_budget = ram_budget_bytes;
  t->worker = std::thread(tier_worker, t);
  return t;
}

void ts_destroy(void* p) {
  auto* t = static_cast<TierStore*>(p);
  if (t == nullptr) return;
  {
    std::lock_guard lk(t->q_mu);
    t->stop = true;
  }
  t->q_cv.notify_all();
  if (t->worker.joinable()) t->worker.join();
  if (t->map != nullptr) munmap(t->map, t->map_bytes);
  if (t->fd >= 0) {
    close(t->fd);
    unlink(t->path.c_str());
  }
  delete t;
}

// Append a cell block to the cold tier (the backing file). Rewriting a
// cell appends fresh bytes and abandons the old extent — compaction
// replaces the whole store, so the file never accretes past one
// generation of churn. Returns 0, or -1 on I/O failure.
int64_t ts_put_cell(void* p, int64_t cell, const uint8_t* data,
                    int64_t nbytes) {
  auto* t = static_cast<TierStore*>(p);
  if (cell < 0 || nbytes < 0) return -1;
  std::unique_lock wlock(t->mu);
  if (cell >= static_cast<int64_t>(t->cells.size())) return -1;
  int64_t off = t->file_bytes;
  int64_t done = 0;
  while (done < nbytes) {
    ssize_t w = pwrite(t->fd, data + done, nbytes - done, off + done);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return -1;
    }
    done += w;
  }
  t->cells[cell] = {off, nbytes};
  t->file_bytes = off + nbytes;
  // drop any stale warm copy (readers must see the new bytes)
  std::lock_guard g(t->ram_mu);
  auto it = t->ram.find(cell);
  if (it != t->ram.end()) {
    t->ram_bytes -= static_cast<int64_t>(it->second.size());
    t->ram.erase(it);
    auto pos = t->lru_pos.find(cell);
    t->lru.erase(pos->second);
    t->lru_pos.erase(pos);
  }
  return 0;
}

int64_t ts_cell_bytes(void* p, int64_t cell) {
  auto* t = static_cast<TierStore*>(p);
  std::shared_lock rlock(t->mu);
  if (cell < 0 || cell >= static_cast<int64_t>(t->cells.size())) return -1;
  if (t->cells[cell].off < 0) return -1;
  return t->cells[cell].bytes;
}

// Read a cell into out[cap]: warm tier first (hit), else the mmap'd cold
// tier (miss) with promotion so the next probe of this cell is a hit.
// Returns bytes copied, or -1 (unknown cell / cap too small).
int64_t ts_read_cell(void* p, int64_t cell, uint8_t* out, int64_t cap) {
  auto* t = static_cast<TierStore*>(p);
  {
    std::lock_guard g(t->ram_mu);
    auto it = t->ram.find(cell);
    if (it != t->ram.end()) {
      int64_t bytes = static_cast<int64_t>(it->second.size());
      if (bytes > cap) return -1;
      std::memcpy(out, it->second.data(), bytes);
      auto pos = t->lru_pos.find(cell);
      t->lru.erase(pos->second);
      t->lru.push_front(cell);
      pos->second = t->lru.begin();
      t->hits.fetch_add(1, std::memory_order_relaxed);
      return bytes;
    }
  }
  int64_t bytes = tier_disk_read(t, cell, out, cap);
  if (bytes < 0) return -1;
  t->misses.fetch_add(1, std::memory_order_relaxed);
  tier_promote(t, cell);
  return bytes;
}

// Queue cells for background disk->RAM promotion; returns the number
// actually enqueued (RAM-resident cells are skipped).
int64_t ts_prefetch(void* p, const int64_t* cells, int64_t n) {
  auto* t = static_cast<TierStore*>(p);
  int64_t queued = 0;
  {
    std::lock_guard g(t->ram_mu);
    std::lock_guard lk(t->q_mu);
    for (int64_t i = 0; i < n; ++i) {
      if (t->ram.count(cells[i])) continue;
      t->queue.push_back(cells[i]);
      ++queued;
    }
  }
  if (queued) t->q_cv.notify_all();
  return queued;
}

// Per-cell residency: 0 = no data, 1 = disk only, 2 = RAM. Returns the
// cell count written (min(n_cells, cap)).
int64_t ts_residency(void* p, int64_t* out, int64_t cap) {
  auto* t = static_cast<TierStore*>(p);
  std::shared_lock rlock(t->mu);
  std::lock_guard g(t->ram_mu);
  int64_t n = std::min<int64_t>(t->cells.size(), cap);
  for (int64_t c = 0; c < n; ++c) {
    if (t->cells[c].off < 0)
      out[c] = 0;
    else
      out[c] = t->ram.count(c) ? 2 : 1;
  }
  return n;
}

// out8 = [ram_cells, disk_cells, hits, misses, promotions, demotions,
//         ram_bytes, prefetch_queue_len]
void ts_stats(void* p, int64_t* out8) {
  auto* t = static_cast<TierStore*>(p);
  int64_t disk = 0;
  {
    std::shared_lock rlock(t->mu);
    for (const auto& c : t->cells)
      if (c.off >= 0) ++disk;
  }
  {
    std::lock_guard g(t->ram_mu);
    out8[0] = static_cast<int64_t>(t->ram.size());
    out8[6] = t->ram_bytes;
  }
  out8[1] = disk;
  out8[2] = t->hits.load(std::memory_order_relaxed);
  out8[3] = t->misses.load(std::memory_order_relaxed);
  out8[4] = t->promotions.load(std::memory_order_relaxed);
  out8[5] = t->demotions.load(std::memory_order_relaxed);
  std::lock_guard lk(t->q_mu);
  out8[7] = static_cast<int64_t>(t->queue.size());
}

// Demote a cell out of the warm tier (tests drive eviction directly).
void ts_drop_ram(void* p, int64_t cell) {
  auto* t = static_cast<TierStore*>(p);
  std::lock_guard g(t->ram_mu);
  auto it = t->ram.find(cell);
  if (it == t->ram.end()) return;
  t->ram_bytes -= static_cast<int64_t>(it->second.size());
  t->ram.erase(it);
  auto pos = t->lru_pos.find(cell);
  t->lru.erase(pos->second);
  t->lru_pos.erase(pos);
  t->demotions.fetch_add(1, std::memory_order_relaxed);
}

}  // extern "C"
