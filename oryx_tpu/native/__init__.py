"""Native (C++) components: build-on-demand via g++, bound with ctypes.

The reference outsources its hot CPU paths to the JVM's concurrent
collections; here the serving/speed vector store is real C++ (SURVEY.md:
"the serving layer's concurrent hash-partitioned vector store gets a C++
implementation bound into Python, not a Python stand-in"). The shared
library is compiled once into this package's _build/ directory and reused;
set ORYX_NATIVE=0 to force the pure-Python fallbacks.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["feature_store.cpp", "parse.cpp", "httpfront.cpp"]
_LOCK = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def native_enabled() -> bool:
    return os.environ.get("ORYX_NATIVE", "1") != "0"


def _build_library() -> str | None:
    """Compile the native sources to one .so, keyed by source hash so edits
    rebuild and repeat imports reuse."""
    h = hashlib.sha256()
    paths = [os.path.join(_HERE, s) for s in _SOURCES]
    for path in paths:
        with open(path, "rb") as f:
            h.update(f.read())
    build_dir = os.path.join(_HERE, "_build")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"liboryx_native_{h.hexdigest()[:16]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = [
        "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
        "-o", so_path, *paths, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
        err = getattr(e, "stderr", b"")
        log.warning(
            "native build failed (%s); falling back to pure Python: %s",
            e, (err or b"").decode("utf-8", "replace")[:500],
        )
        return None
    return so_path


_SANITIZE_FLAGS = [
    # -O1 keeps stack traces honest; frame pointers make ASan reports
    # readable. detect_leaks is left to the harness (CPython itself is
    # not leak-clean, so LSan would drown real reports in interpreter
    # noise).
    "-fsanitize=address,undefined",
    "-fno-sanitize-recover=undefined",
    "-fno-omit-frame-pointer",
    "-g",
    "-O1",
]


def build_sanitized_library() -> str | None:
    """Compile an ASan+UBSan instrumented variant of the native sources.

    Kept as a SEPARATE artifact in _build/ (``liboryx_native_san_*``) so
    the production .so is never polluted with sanitizer runtime deps.
    Loading it into CPython requires the ASan runtime to be preloaded
    (see `find_asan_runtime`); the test harness runs the parity suite in
    a subprocess with LD_PRELOAD set. Returns None when the toolchain is
    unavailable — callers skip, they do not fail.
    """
    h = hashlib.sha256()
    paths = [os.path.join(_HERE, s) for s in _SOURCES]
    for path in paths:
        with open(path, "rb") as f:
            h.update(f.read())
    h.update(" ".join(_SANITIZE_FLAGS).encode())
    build_dir = os.path.join(_HERE, "_build")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(
        build_dir, f"liboryx_native_san_{h.hexdigest()[:16]}.so"
    )
    if os.path.exists(so_path):
        return so_path
    cmd = [
        "g++", *_SANITIZE_FLAGS, "-std=c++17", "-shared", "-fPIC",
        "-o", so_path, *paths, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=240)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
        err = getattr(e, "stderr", b"")
        log.warning(
            "sanitized native build unavailable (%s): %s",
            e, (err or b"").decode("utf-8", "replace")[:500],
        )
        return None
    return so_path


def find_asan_runtime() -> str | None:
    """Absolute path to libasan.so for LD_PRELOAD, or None.

    A sanitized .so dlopen()ed into an uninstrumented CPython needs the
    ASan runtime loaded FIRST; g++ knows where its copy lives.
    """
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            check=True, capture_output=True, timeout=30,
        ).stdout.decode().strip()
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError):
        return None
    # when the runtime is missing g++ echoes the bare name back
    if out and os.path.isabs(out) and os.path.exists(out):
        return os.path.realpath(out)
    return None


def get_library() -> ctypes.CDLL | None:
    """The loaded native library, or None (disabled or build failure —
    callers fall back to Python implementations). With
    ORYX_NATIVE_SANITIZE=1 the ASan/UBSan build variant is loaded
    instead (the harness sets this in a subprocess whose LD_PRELOAD
    carries the ASan runtime)."""
    global _lib, _lib_failed
    if not native_enabled():
        return None
    with _LOCK:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("ORYX_NATIVE_SANITIZE") == "1":
            so_path = build_sanitized_library()
        else:
            so_path = _build_library()
        if so_path is None:
            _lib_failed = True
            return None
        lib = ctypes.CDLL(so_path)
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.fs_create.restype = c.c_void_p
    lib.fs_create.argtypes = [c.c_int64, c.c_int64]
    lib.fs_destroy.argtypes = [c.c_void_p]
    lib.fs_dim.restype = c.c_int64
    lib.fs_dim.argtypes = [c.c_void_p]
    lib.fs_set.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.POINTER(c.c_float)]
    lib.fs_get.restype = c.c_int
    lib.fs_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.POINTER(c.c_float)]
    lib.fs_remove.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.fs_size.restype = c.c_int64
    lib.fs_size.argtypes = [c.c_void_p]
    lib.fs_recent_count.restype = c.c_int64
    lib.fs_recent_count.argtypes = [c.c_void_p]
    lib.fs_pack.restype = c.c_int64
    lib.fs_pack.argtypes = [
        c.c_void_p, c.POINTER(c.c_float), c.c_int64, c.c_char_p, c.c_int64,
        c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int,
    ]
    lib.fs_ids.restype = c.c_int64
    lib.fs_ids.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64, c.POINTER(c.c_int64), c.c_int,
    ]
    lib.fs_vtv.argtypes = [c.c_void_p, c.POINTER(c.c_double)]
    lib.fs_retain.argtypes = [c.c_void_p, c.POINTER(c.c_int64), c.c_char_p, c.c_int64]
    lib.fs_get_batch.restype = c.c_int64
    lib.fs_get_batch.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.c_char_p, c.c_int64,
        c.POINTER(c.c_float), c.POINTER(c.c_uint8),
    ]
    lib.fs_set_batch.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.c_char_p, c.c_int64,
        c.POINTER(c.c_float),
    ]
    lib.parse_float_csv.restype = c.c_int64
    lib.parse_float_csv.argtypes = [
        c.c_char_p, c.c_int64, c.POINTER(c.c_float), c.c_int64,
    ]
    lib.json_format_vectors.restype = c.c_int64
    lib.json_format_vectors.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_int64,
        c.POINTER(c.c_char), c.c_int64, c.POINTER(c.c_int64), c.POINTER(c.c_int64),
    ]
    lib.als_update_row_cap.restype = c.c_int64
    lib.als_update_row_cap.argtypes = [c.c_int64, c.c_int64]
    lib.als_format_updates.restype = c.c_int64
    lib.als_format_updates.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_int64,
        c.POINTER(c.c_int64), c.c_char_p, c.POINTER(c.c_int64), c.c_char_p,
        c.c_char, c.c_int, c.c_int64, c.POINTER(c.c_char),
        c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
    ]
    lib.als_parse_text_block.restype = c.c_int64
    lib.als_parse_text_block.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_float),
        c.POINTER(c.c_int64), c.POINTER(c.c_uint8), c.POINTER(c.c_int32),
        c.c_int64,
    ]
    lib.als_format_updates_multi.restype = c.c_int64
    lib.als_format_updates_multi.argtypes = [
        c.POINTER(c.c_float), c.c_int64, c.c_int64,
        c.POINTER(c.c_int64), c.c_char_p,
        c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_char_p,
        c.c_char, c.c_int64, c.POINTER(c.c_char),
        c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_int64,
    ]
    # httpfront.cpp: epoll HTTP/1.1 front (serving/native_front.py owns
    # the handle; ctypes releases the GIL for the blocking hf_poll)
    u8p = c.POINTER(c.c_uint8)
    lib.hf_create.restype = c.c_void_p
    lib.hf_create.argtypes = [
        c.c_int, c.c_int, c.c_int64, c.c_int64, c.c_double, c.c_int64,
    ]
    lib.hf_port.restype = c.c_int
    lib.hf_port.argtypes = [c.c_void_p]
    lib.hf_shutdown.argtypes = [c.c_void_p]
    lib.hf_close.argtypes = [c.c_void_p]
    lib.hf_poll.restype = c.c_int64
    lib.hf_poll.argtypes = [c.c_void_p, u8p, c.c_int64, c.c_int]
    lib.hf_respond.restype = c.c_int
    lib.hf_respond.argtypes = [
        c.c_void_p, c.c_uint32, c.c_uint32, u8p, c.c_int64, c.c_int,
    ]
    lib.hf_set_ladder.argtypes = [c.c_void_p, c.c_int, c.c_int, c.c_uint32]
    lib.hf_set_tenants.argtypes = [c.c_void_p, u8p, c.c_int64]
    lib.hf_set_exempt.argtypes = [c.c_void_p, u8p, c.c_int64]
    lib.hf_set_context.argtypes = [c.c_void_p, u8p, c.c_int64]
    lib.hf_set_shed_template.argtypes = [
        c.c_void_p, u8p, c.c_int64, u8p, c.c_int64, c.c_int64,
    ]
    lib.hf_set_snapshot.argtypes = [
        c.c_void_p, u8p, c.c_int64, u8p, c.c_int64, u8p, c.c_int64,
        c.c_int64, c.c_int,
    ]
    lib.hf_cache_cap.argtypes = [c.c_void_p, c.c_int64]
    lib.hf_cache_put.argtypes = [
        c.c_void_p, u8p, c.c_int64, u8p, c.c_int64, u8p, c.c_int64,
        c.c_int64,
    ]
    lib.hf_cache_clear.argtypes = [c.c_void_p]
    lib.hf_cache_size.restype = c.c_int64
    lib.hf_cache_size.argtypes = [c.c_void_p]
    lib.hf_stats.restype = c.c_int64
    lib.hf_stats.argtypes = [c.c_void_p, c.POINTER(c.c_uint64), c.c_int64, c.c_int]
    lib.hf_drain_trace.restype = c.c_int64
    lib.hf_drain_trace.argtypes = [c.c_void_p, u8p, c.c_int64]
    # tiered cell store (ts_*): RAM->disk item-plane tiers + async
    # prefetch (native/store.py TieredHostPlane owns the handle)
    i64p = c.POINTER(c.c_int64)
    lib.ts_create.restype = c.c_void_p
    lib.ts_create.argtypes = [c.c_char_p, c.c_int64, c.c_int64, c.c_int64]
    lib.ts_destroy.argtypes = [c.c_void_p]
    lib.ts_put_cell.restype = c.c_int64
    lib.ts_put_cell.argtypes = [c.c_void_p, c.c_int64, u8p, c.c_int64]
    lib.ts_cell_bytes.restype = c.c_int64
    lib.ts_cell_bytes.argtypes = [c.c_void_p, c.c_int64]
    lib.ts_read_cell.restype = c.c_int64
    lib.ts_read_cell.argtypes = [c.c_void_p, c.c_int64, u8p, c.c_int64]
    lib.ts_prefetch.restype = c.c_int64
    lib.ts_prefetch.argtypes = [c.c_void_p, i64p, c.c_int64]
    lib.ts_residency.restype = c.c_int64
    lib.ts_residency.argtypes = [c.c_void_p, i64p, c.c_int64]
    lib.ts_stats.argtypes = [c.c_void_p, i64p]
    lib.ts_drop_ram.argtypes = [c.c_void_p, c.c_int64]
