// Epoll HTTP/1.1 front for the serving layer (docs/serving-native.md).
//
// One epoll thread owns the listener and every connection: it accepts,
// reads, parses (keep-alive, pipelining-safe), and classifies requests
// entirely outside the GIL. Three cheap rungs are answered natively from
// state the Python side pushes down on its control tick:
//
//   snapshot  /healthz //readyz //ready bodies pre-rendered by the real
//             Python resources (hf_set_snapshot)
//   shed      overload fast-429 with Retry-After, gated on the ladder
//             stage pushed from overload.py (hf_set_ladder/hf_set_tenants)
//   stale     champion-generation-gated answer-cache hits mirrored from
//             AnswerCache.put (hf_cache_put; hf_cache_clear on swap)
//
// Everything else is assembled into micro-batches framed with the RBLK
// wire codec (bus/blockcodec.py: same 32-byte header, KIND_HTTP payload)
// and handed to the Python dispatch loop via hf_poll; responses come
// back through hf_respond as fully rendered bytes and are written in
// request order per connection (pipelining safety).
//
// Parity contract (tests/serving/test_native_front.py): natively
// answered responses are byte-identical to the Python front's — the
// templates are rendered by the SAME Python code and split around the
// Date header, which this file regenerates in IMF-fixdate form. When a
// request cannot be answered bit-identically (CSV Accept, gzip-eligible
// body, tenant-prefixed control path, ...) it is FORWARDED, never
// approximated — the same decline-over-diverge rule parse.cpp follows.
//
// Ownership: hf_create starts the epoll thread and owns every fd it
// accepts; hf_close stops the thread, closes all fds, and unblocks any
// hf_poll caller (returns -1). All configuration setters may be called
// from any thread; connection state is touched only by the epoll thread.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <deque>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <condition_variable>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// RBLK framing (mirrors bus/blockcodec.py HEADER = "<IHHQIII4x")
// ---------------------------------------------------------------------------

constexpr uint32_t kMagic = 0x4B4C4252;  // b"RBLK"
constexpr uint16_t kKindHttp = 4;        // blockcodec.KIND_HTTP
constexpr size_t kFrameHeader = 32;

inline size_t pad8(size_t n) { return (n + 7) & ~size_t(7); }

uint32_t crc32_zlib(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

inline void put_u16(std::string& b, uint16_t v) { b.append((const char*)&v, 2); }
inline void put_u32(std::string& b, uint32_t v) { b.append((const char*)&v, 4); }
inline void put_u64(std::string& b, uint64_t v) { b.append((const char*)&v, 8); }

// ---------------------------------------------------------------------------
// Latency bucketing (mirrors common/metrics.py Histogram: 1e-6 * 2^i s)
// ---------------------------------------------------------------------------

constexpr int kBuckets = 28;  // + overflow slot = 29 counters

int bucket_index(double seconds) {
  int idx = 0;
  double bound = 1e-6;
  while (idx < kBuckets && seconds > bound) {
    ++idx;
    bound *= 2.0;
  }
  return idx;
}

// ---------------------------------------------------------------------------
// Config / pushed-down state
// ---------------------------------------------------------------------------

struct AnswerTemplate {
  // response = pre + <IMF date> + post; the last body_len bytes of post
  // are the body (suppressed for HEAD)
  std::string pre;
  std::string post;
  uint32_t body_len = 0;
  uint16_t status = 200;
  bool gzip_large = false;  // body > 1024: a gzip-accepting client must forward
};

struct TenantEntry {
  std::string name;
  uint8_t stage = 0;
};

struct CacheEntry {
  AnswerTemplate tpl;
  std::list<std::string>::iterator lru;
};

struct Stats {
  uint64_t conns_accepted = 0, conns_closed = 0;
  uint64_t requests = 0, forwarded = 0, parse_errors = 0;
  uint64_t answered[3] = {0, 0, 0};  // snapshot, shed, stale
  uint64_t by_method[5] = {0, 0, 0, 0, 0};   // GET POST DELETE HEAD other
  uint64_t by_class[5] = {0, 0, 0, 0, 0};    // 1xx..5xx (native answers)
  uint64_t lat_count = 0, lat_sum_us = 0;
  uint64_t events_dropped = 0, responses_dropped = 0;
  uint64_t bytes_in = 0, bytes_out = 0, pending_hwm = 0;
  uint64_t lat_buckets[kBuckets + 1] = {0};
};
constexpr int kStatsScalars = 25;  // scalar slots before the bucket array

struct TenantStats {
  uint64_t count = 0, sum_us = 0;
  uint64_t shed_stale = 0, shed_shed = 0;
  uint64_t buckets[kBuckets + 1] = {0};
};
constexpr int kTenantStatsLen = 4 + kBuckets + 1;  // u64 slots per tenant
constexpr size_t kMaxTenants = 64;

struct TraceEvent {
  uint64_t wall_ms = 0;
  uint32_t dur_us = 0;
  uint16_t status = 0;
  uint8_t rung = 0;    // 0 snapshot, 1 shed, 2 stale
  uint8_t method = 0;  // 0 GET,1 POST,2 DELETE,3 HEAD,4 other
  int16_t tenant = -1;
  uint16_t tp_len = 0, path_len = 0;
  char tp[64];
  char path[96];
};

// ---------------------------------------------------------------------------
// Connection + request parsing
// ---------------------------------------------------------------------------

enum Method : uint8_t { M_GET = 0, M_POST = 1, M_DELETE = 2, M_HEAD = 3, M_OTHER = 4 };

struct ParsedRequest {
  uint32_t conn_id = 0, req_id = 0;
  uint8_t method = M_OTHER;
  uint8_t flags = 0;  // bit0: HTTP/1.0, bit1: close-after
  std::string target;                                  // raw, incl. query
  std::vector<std::pair<std::string, std::string>> headers;
  std::string body;
};

struct Conn {
  int fd = -1;
  uint32_t id = 0;
  std::string rbuf;
  // write side: ordered response bytes; partially written front
  std::deque<std::string> wq;
  size_t woff = 0;
  bool want_write = false;
  // pipelining order: responses are released strictly in req-id order
  uint32_t next_req_id = 1;     // id assigned to the next parsed request
  uint32_t next_write_id = 1;   // id whose response writes next
  std::map<uint32_t, std::pair<std::string, bool>> parked;  // id -> (bytes, close)
  uint32_t outstanding = 0;     // parsed-not-yet-responded
  uint32_t close_after_id = 0;  // stop after this response id (0 = none)
  bool stop_parsing = false;
  double last_activity = 0.0;
  // body accumulation state
  bool in_body = false;
  ParsedRequest cur;
  size_t body_need = 0;
};

double now_mono() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

uint64_t now_wall_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return uint64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

void http_date(char* out, size_t cap) {
  time_t t = time(nullptr);
  struct tm g;
  gmtime_r(&t, &g);
  // IMF-fixdate, identical to BaseHTTPRequestHandler.date_time_string()
  strftime(out, cap, "%a, %d %b %Y %H:%M:%S GMT", &g);
}

inline bool ieq(const std::string& a, const char* b) {
  size_t n = strlen(b);
  if (a.size() != n) return false;
  for (size_t i = 0; i < n; ++i)
    if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i])) return false;
  return true;
}

// ---------------------------------------------------------------------------
// The front
// ---------------------------------------------------------------------------

struct Front {
  int listen_fd = -1, epoll_fd = -1, event_fd = -1;
  int port = 0;
  std::thread loop;
  bool closing = false;

  // limits (hf_create args)
  size_t max_header = 16384, max_body = 1 << 20;
  double idle_timeout = 30.0;
  size_t max_conns = 1024, max_pending = 4096, max_pipeline = 64;

  // connections (epoll thread only)
  std::unordered_map<uint32_t, std::unique_ptr<Conn>> conns;
  std::unordered_map<int, uint32_t> fd_to_id;
  uint32_t next_conn_id = 1;

  // pending parsed requests -> Python (hf_poll)
  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<ParsedRequest> pending;
  uint64_t batch_seq = 0;
  bool q_closed = false;
  bool paused_reads = false;  // backpressure: queue full

  // responses Python -> epoll thread (hf_respond inbox)
  std::mutex r_mu;
  struct Resp { uint32_t conn_id, req_id; std::string data; bool close; };
  std::deque<Resp> inbox;

  // pushed-down classification state (cfg_mu guards; readers = epoll thread)
  std::mutex cfg_mu;
  uint8_t global_stage = 0;
  uint16_t retry_after_s = 1;
  // bit0 snapshots, bit1 shed, bit2 stale, bit3 tenancy-on
  uint32_t flags = 0;
  std::string context_path;
  std::vector<std::string> exempt;  // post-context-strip prefixes
  std::vector<TenantEntry> tenants;
  int default_tenant = -1;
  AnswerTemplate shed_tpl;
  bool have_shed_tpl = false;
  std::unordered_map<std::string, AnswerTemplate> snapshots;  // raw path -> tpl
  std::unordered_map<std::string, CacheEntry> cache;
  std::list<std::string> cache_lru;  // front = most recent
  size_t cache_cap = 256;

  // stats + trace events
  std::mutex s_mu;
  Stats stats;
  std::vector<TenantStats> tstats;
  std::vector<TraceEvent> events;
  static constexpr size_t kMaxEvents = 4096;

  ~Front() { do_close(); }

  // -- lifecycle ------------------------------------------------------------

  bool start(int want_port, int backlog) {
    listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)want_port);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0) return false;
    if (listen(listen_fd, backlog) != 0) return false;
    socklen_t alen = sizeof(addr);
    if (getsockname(listen_fd, (sockaddr*)&addr, &alen) != 0) return false;
    port = ntohs(addr.sin_port);
    epoll_fd = epoll_create1(EPOLL_CLOEXEC);
    event_fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    if (epoll_fd < 0 || event_fd < 0) return false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = listen_fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, listen_fd, &ev);
    ev.data.fd = event_fd;
    epoll_ctl(epoll_fd, EPOLL_CTL_ADD, event_fd, &ev);
    loop = std::thread([this] { run(); });
    return true;
  }

  void do_close() {
    {
      std::lock_guard<std::mutex> lk(r_mu);
      if (closing) return;
      closing = true;
    }
    wake();
    if (loop.joinable()) loop.join();
    {
      std::lock_guard<std::mutex> lk(q_mu);
      q_closed = true;
    }
    q_cv.notify_all();
    for (auto& kv : conns) ::close(kv.second->fd);
    conns.clear();
    fd_to_id.clear();
    if (listen_fd >= 0) ::close(listen_fd);
    if (event_fd >= 0) ::close(event_fd);
    if (epoll_fd >= 0) ::close(epoll_fd);
    listen_fd = event_fd = epoll_fd = -1;
  }

  void wake() {
    uint64_t one = 1;
    ssize_t r = write(event_fd, &one, sizeof(one));
    (void)r;
  }

  bool is_closing() {
    std::lock_guard<std::mutex> lk(r_mu);
    return closing;
  }

  // -- epoll loop -----------------------------------------------------------

  void run() {
    epoll_event evs[64];
    double last_sweep = now_mono();
    while (!is_closing()) {
      int n = epoll_wait(epoll_fd, evs, 64, 500);
      if (is_closing()) break;
      for (int i = 0; i < n; ++i) {
        int fd = evs[i].data.fd;
        if (fd == listen_fd) {
          accept_loop();
        } else if (fd == event_fd) {
          uint64_t junk;
          while (read(event_fd, &junk, sizeof(junk)) > 0) {}
          drain_inbox();
          maybe_resume_reads();
        } else {
          auto it = fd_to_id.find(fd);
          if (it == fd_to_id.end()) continue;
          Conn* c = conns[it->second].get();
          if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
            close_conn(c);
            continue;
          }
          if (evs[i].events & EPOLLIN) on_readable(c);
          // on_readable may close; re-lookup
          auto it2 = fd_to_id.find(fd);
          if (it2 == fd_to_id.end()) continue;
          c = conns[it2->second].get();
          if (evs[i].events & EPOLLOUT) flush_writes(c);
        }
      }
      double t = now_mono();
      if (t - last_sweep >= 1.0) {
        last_sweep = t;
        sweep_idle(t);
      }
    }
    // unblock any hf_poll caller
    {
      std::lock_guard<std::mutex> lk(q_mu);
      q_closed = true;
    }
    q_cv.notify_all();
  }

  void accept_loop() {
    while (true) {
      int fd = accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK | SOCK_CLOEXEC);
      if (fd < 0) break;
      if (conns.size() >= max_conns) {
        ::close(fd);
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto c = std::make_unique<Conn>();
      c->fd = fd;
      c->id = next_conn_id++;
      c->last_activity = now_mono();
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = fd;
      epoll_ctl(epoll_fd, EPOLL_CTL_ADD, fd, &ev);
      fd_to_id[fd] = c->id;
      {
        std::lock_guard<std::mutex> lk(s_mu);
        stats.conns_accepted++;
      }
      conns[c->id] = std::move(c);
    }
  }

  void close_conn(Conn* c) {
    epoll_ctl(epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
    ::close(c->fd);
    fd_to_id.erase(c->fd);
    {
      std::lock_guard<std::mutex> lk(s_mu);
      stats.conns_closed++;
    }
    conns.erase(c->id);
  }

  void sweep_idle(double t) {
    std::vector<Conn*> victims;
    for (auto& kv : conns)
      if (t - kv.second->last_activity > idle_timeout &&
          kv.second->outstanding == 0)
        victims.push_back(kv.second.get());
    for (Conn* c : victims) close_conn(c);
  }

  // -- reads + parsing ------------------------------------------------------

  bool queue_full() {
    std::lock_guard<std::mutex> lk(q_mu);
    return pending.size() >= max_pending;
  }

  void maybe_resume_reads() {
    if (!paused_reads || queue_full()) return;
    paused_reads = false;
    // level-triggered epoll re-delivers readable conns; re-parse any
    // buffered bytes that were left when the queue filled. Iterate by
    // id: parse_loop can close (free) connections as it goes.
    std::vector<uint32_t> ids;
    ids.reserve(conns.size());
    for (auto& kv : conns) ids.push_back(kv.first);
    for (uint32_t id : ids) {
      auto it = conns.find(id);
      if (it != conns.end()) parse_loop(it->second.get());
    }
  }

  void on_readable(Conn* c) {
    char buf[65536];
    while (true) {
      ssize_t r = read(c->fd, buf, sizeof(buf));
      if (r > 0) {
        c->last_activity = now_mono();
        {
          std::lock_guard<std::mutex> lk(s_mu);
          stats.bytes_in += (uint64_t)r;
        }
        if (c->stop_parsing) continue;  // discard post-close pipeline bytes
        c->rbuf.append(buf, (size_t)r);
        if (!c->in_body && c->rbuf.size() > max_header + max_body) {
          // runaway header with no terminator
          native_error(c, 431, "Request Header Fields Too Large");
          return;
        }
      } else if (r == 0) {
        if (c->outstanding == 0 && c->wq.empty()) {
          close_conn(c);
        } else {
          // peer half-closed with requests in flight: answer them,
          // then the ordered-release path closes after the last one
          c->stop_parsing = true;
          if (c->close_after_id == 0) c->close_after_id = c->next_req_id - 1;
        }
        return;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        close_conn(c);
        return;
      }
    }
    parse_loop(c);
  }

  // parse as many complete requests as the buffer holds
  void parse_loop(Conn* c) {
    while (!c->stop_parsing) {
      if (c->outstanding >= max_pipeline) return;
      if (queue_full()) {
        paused_reads = true;
        return;
      }
      if (c->in_body) {
        if (c->rbuf.size() < c->body_need) return;
        c->cur.body.assign(c->rbuf.data(), c->body_need);
        c->rbuf.erase(0, c->body_need);
        c->in_body = false;
        if (!finish_request(c)) return;
        continue;
      }
      size_t hdr_end = c->rbuf.find("\r\n\r\n");
      if (hdr_end == std::string::npos) {
        if (c->rbuf.size() > max_header) {
          native_error(c, 431, "Request Header Fields Too Large");
        }
        return;
      }
      if (hdr_end + 4 > max_header) {
        native_error(c, 431, "Request Header Fields Too Large");
        return;
      }
      if (!parse_headers(c, hdr_end)) return;  // errored + closed
      c->rbuf.erase(0, hdr_end + 4);
      if (c->body_need > 0) {
        if (c->body_need > max_body) {
          native_error(c, 413, "Payload Too Large");
          return;
        }
        c->in_body = true;
        continue;  // loop reads body from rbuf
      }
      if (!finish_request(c)) return;
    }
  }

  // request line + header block into c->cur; sets body_need. On protocol
  // errors answers natively and closes; returns false then.
  bool parse_headers(Conn* c, size_t hdr_end) {
    const std::string& b = c->rbuf;
    size_t line_end = b.find("\r\n");
    if (line_end == std::string::npos || line_end > hdr_end) line_end = hdr_end;
    size_t sp1 = b.find(' ');
    if (sp1 == std::string::npos || sp1 >= line_end) {
      native_error(c, 400, "Bad Request");
      return false;
    }
    size_t sp2 = b.find(' ', sp1 + 1);
    if (sp2 == std::string::npos || sp2 >= line_end) {
      native_error(c, 400, "Bad Request");
      return false;
    }
    std::string method = b.substr(0, sp1);
    std::string target = b.substr(sp1 + 1, sp2 - sp1 - 1);
    std::string version = b.substr(sp2 + 1, line_end - sp2 - 1);
    c->cur = ParsedRequest();
    c->cur.conn_id = c->id;
    c->cur.target = std::move(target);
    if (method == "GET") c->cur.method = M_GET;
    else if (method == "POST") c->cur.method = M_POST;
    else if (method == "DELETE") c->cur.method = M_DELETE;
    else if (method == "HEAD") c->cur.method = M_HEAD;
    else {
      native_error(c, 501, "Unsupported method");
      return false;
    }
    bool http10 = false;
    if (version == "HTTP/1.1") {
    } else if (version == "HTTP/1.0") {
      http10 = true;
      c->cur.flags |= 1;
    } else {
      native_error(c, 505, "HTTP Version Not Supported");
      return false;
    }
    // headers
    size_t pos = line_end + 2;
    size_t content_length = 0;
    bool keep_alive = !http10;
    bool expect_continue = false;
    while (pos < hdr_end) {
      size_t eol = b.find("\r\n", pos);
      if (eol == std::string::npos || eol > hdr_end) eol = hdr_end;
      size_t colon = b.find(':', pos);
      if (colon == std::string::npos || colon >= eol) {
        native_error(c, 400, "Bad Request");
        return false;
      }
      std::string name = b.substr(pos, colon - pos);
      size_t vstart = colon + 1;
      while (vstart < eol && (b[vstart] == ' ' || b[vstart] == '\t')) ++vstart;
      size_t vend = eol;
      while (vend > vstart && (b[vend - 1] == ' ' || b[vend - 1] == '\t')) --vend;
      std::string value = b.substr(vstart, vend - vstart);
      if (ieq(name, "content-length")) {
        char* endp = nullptr;
        unsigned long long cl = strtoull(value.c_str(), &endp, 10);
        if (endp == value.c_str() || *endp != '\0') {
          native_error(c, 400, "Bad Request");
          return false;
        }
        content_length = (size_t)cl;
      } else if (ieq(name, "transfer-encoding")) {
        native_error(c, 501, "Unsupported transfer encoding");
        return false;
      } else if (ieq(name, "connection")) {
        if (ieq(value, "close")) keep_alive = false;
        else if (ieq(value, "keep-alive")) keep_alive = true;
      } else if (ieq(name, "expect") && ieq(value, "100-continue")) {
        expect_continue = true;
      }
      c->cur.headers.emplace_back(std::move(name), std::move(value));
      pos = eol + 2;
    }
    if (!keep_alive) c->cur.flags |= 2;
    c->body_need = content_length;
    if (expect_continue && content_length > 0 && content_length <= max_body)
      if (!raw_write(c, "HTTP/1.1 100 Continue\r\n\r\n")) return false;
    return true;
  }

  // classify a fully parsed request: answer natively or queue to Python.
  // Returns false when the connection was closed.
  bool finish_request(Conn* c) {
    c->cur.req_id = c->next_req_id++;
    c->outstanding++;
    c->last_activity = now_mono();
    bool close_after = (c->cur.flags & 2) != 0;
    if (close_after) {
      c->close_after_id = c->cur.req_id;
      c->stop_parsing = true;
    }
    {
      std::lock_guard<std::mutex> lk(s_mu);
      stats.requests++;
    }
    std::string native;
    uint8_t rung = 0;
    uint16_t status = 0;
    int16_t tenant_idx = -1;
    double t0 = now_mono();
    bool answered = classify(c->cur, &native, &rung, &status, &tenant_idx);
    if (answered) {
      record_native(c->cur, rung, status, tenant_idx, now_mono() - t0);
      uint32_t rid = c->cur.req_id;
      c->cur = ParsedRequest();  // reset BEFORE complete() may free c
      return complete(c, rid, std::move(native), false);
    }
    {
      std::lock_guard<std::mutex> lk(s_mu);
      stats.forwarded++;
    }
    bool notify;
    {
      std::lock_guard<std::mutex> lk(q_mu);
      notify = pending.empty();
      pending.push_back(std::move(c->cur));
      std::lock_guard<std::mutex> lk2(s_mu);
      if (pending.size() > stats.pending_hwm) stats.pending_hwm = pending.size();
    }
    c->cur = ParsedRequest();
    if (notify) q_cv.notify_all();
    return true;
  }

  // -- native classification ------------------------------------------------

  static void split_target(const std::string& target, std::string* path,
                           std::string* query) {
    size_t q = target.find('?');
    if (q == std::string::npos) {
      *path = target;
      query->clear();
    } else {
      *path = target.substr(0, q);
      *query = target.substr(q + 1);
    }
  }

  // mirrors tenancy/context.py split_tenant_path
  static bool split_tenant_path(const std::string& path, std::string* tenant,
                                std::string* rest) {
    if (path.compare(0, 3, "/t/") != 0) return false;
    std::string r = path.substr(3);
    size_t sep = r.find('/');
    if (sep == std::string::npos) {
      *tenant = r;
      *rest = "/";
    } else {
      *tenant = r.substr(0, sep);
      *rest = r.substr(sep);
      if (rest->empty()) *rest = "/";
    }
    return !tenant->empty();
  }

  bool path_exempt(const std::string& path) {
    for (const auto& p : exempt) {
      if (!p.empty() && p.back() == '/') {
        std::string bare = p.substr(0, p.size() - 1);
        if (path == bare || path.compare(0, p.size(), p) == 0) return true;
      } else if (path == p || path.compare(0, p.size(), p) == 0) {
        return true;
      }
    }
    return false;
  }

  const std::string* header_get(const ParsedRequest& r, const char* name) {
    for (const auto& kv : r.headers)
      if (ieq(kv.first, name)) return &kv.second;
    return nullptr;
  }

  bool accept_blocks_native(const ParsedRequest& r, bool gzip_large) {
    // CSV negotiation and gzip-eligible bodies are Python's business:
    // forward rather than diverge (render()/gzip parity)
    const std::string* acc = header_get(r, "accept");
    if (acc != nullptr && acc->find("text/csv") != std::string::npos) return true;
    if (gzip_large) {
      const std::string* ae = header_get(r, "accept-encoding");
      if (ae != nullptr && ae->find("gzip") != std::string::npos) return true;
    }
    return false;
  }

  bool classify(const ParsedRequest& r, std::string* out, uint8_t* rung,
                uint16_t* status, int16_t* tenant_idx) {
    std::lock_guard<std::mutex> lk(cfg_mu);
    std::string path, query;
    split_target(r.target, &path, &query);
    bool tenancy_on = (flags & 8) != 0;
    bool is_get = r.method == M_GET || r.method == M_HEAD;

    // snapshots match the RAW path (context path included, no tenant
    // forms — a tenant-prefixed or tenant-headed control request routes
    // through Python so tenant validation/accounting stays exact)
    if ((flags & 1) != 0 && is_get) {
      auto it = snapshots.find(path);
      if (it != snapshots.end() &&
          !(tenancy_on && (header_get(r, "x-oryx-tenant") != nullptr ||
                           path.compare(0, 3, "/t/") == 0)) &&
          !accept_blocks_native(r, it->second.gzip_large)) {
        *out = render_template(it->second, r.method == M_HEAD);
        *rung = 0;
        *status = it->second.status;
        *tenant_idx = -1;
        return true;
      }
    }
    if ((flags & 6) == 0 || global_stage == 0) {
      // ladder fully released (the common fast path) unless a tenant
      // ladder is raised; check those only when tenancy is on
      bool any_tenant_raised = false;
      if (tenancy_on)
        for (const auto& t : tenants)
          if (t.stage > 0) { any_tenant_raised = true; break; }
      if (!any_tenant_raised) return false;
    }

    // context-path strip (outside-context requests forward: Python 404s)
    std::string sub = path;
    if (!context_path.empty()) {
      if (sub.compare(0, context_path.size(), context_path) != 0) return false;
      sub = sub.substr(context_path.size());
      if (sub.empty()) sub = "/";
    }
    // tenant resolution: /t/<id>/ prefix > X-Oryx-Tenant header > default
    std::string tenant;
    int t_idx = -1;
    std::string stripped = sub;
    if (tenancy_on) {
      std::string tid, rest;
      if (split_tenant_path(sub, &tid, &rest)) {
        tenant = tid;
        stripped = rest;
      } else {
        const std::string* th = header_get(r, "x-oryx-tenant");
        if (th != nullptr) tenant = *th;
      }
      if (tenant.empty() && !path_exempt(stripped) && default_tenant >= 0)
        t_idx = default_tenant;
      else if (!tenant.empty()) {
        for (size_t i = 0; i < tenants.size(); ++i)
          if (tenants[i].name == tenant) { t_idx = (int)i; break; }
        if (t_idx < 0) return false;  // unknown tenant: Python 404s
      }
    }
    *tenant_idx = (int16_t)t_idx;
    if (path_exempt(stripped)) return false;  // control plane: never shed

    uint8_t stage = global_stage;
    if (t_idx >= 0 && tenants[t_idx].stage > stage) stage = tenants[t_idx].stage;
    if (stage >= 3 && (flags & 2) != 0 && have_shed_tpl) {
      *out = render_template(shed_tpl, r.method == M_HEAD);
      *rung = 1;
      *status = 429;
      return true;
    }
    if (stage >= 2 && (flags & 4) != 0 && is_get) {
      std::string key = stripped;
      if (!query.empty()) key += "?" + query;
      if (t_idx >= 0) key = "/t/" + tenants[t_idx].name + key;
      auto it = cache.find(key);
      if (it != cache.end() &&
          !accept_blocks_native(r, it->second.tpl.gzip_large)) {
        cache_lru.splice(cache_lru.begin(), cache_lru, it->second.lru);
        *out = render_template(it->second.tpl, r.method == M_HEAD);
        *rung = 2;
        *status = it->second.tpl.status;
        return true;
      }
    }
    return false;
  }

  std::string render_template(const AnswerTemplate& t, bool head) {
    char date[64];
    http_date(date, sizeof(date));
    std::string out;
    out.reserve(t.pre.size() + t.post.size() + 32);
    out += t.pre;
    out += date;
    if (head) out.append(t.post.data(), t.post.size() - t.body_len);
    else out += t.post;
    return out;
  }

  void record_native(const ParsedRequest& r, uint8_t rung, uint16_t status,
                     int16_t tenant_idx, double dur_s) {
    uint64_t dur_us = (uint64_t)(dur_s * 1e6);
    int bi = bucket_index(dur_s);
    {
      std::lock_guard<std::mutex> lk(s_mu);
      stats.answered[rung]++;
      stats.by_method[r.method < 5 ? r.method : 4]++;
      int cls = status / 100;
      if (cls >= 1 && cls <= 5) stats.by_class[cls - 1]++;
      stats.lat_count++;
      stats.lat_sum_us += dur_us;
      stats.lat_buckets[bi]++;
      if (tenant_idx >= 0) {
        if ((size_t)tenant_idx >= tstats.size()) tstats.resize(tenant_idx + 1);
        TenantStats& ts = tstats[tenant_idx];
        ts.count++;
        ts.sum_us += dur_us;
        ts.buckets[bi]++;
        if (rung == 1) ts.shed_shed++;
        else if (rung == 2) ts.shed_stale++;
      }
      // span emission: only sampled incoming traceparents ride the ring
      const std::string* tp = header_get(r, "traceparent");
      if (tp != nullptr && tp->size() >= 2 && tp->size() < 64 &&
          tp->compare(tp->size() - 2, 2, "01") == 0) {
        if (events.size() >= kMaxEvents) {
          stats.events_dropped++;
        } else {
          TraceEvent ev;
          ev.wall_ms = now_wall_ms();
          ev.dur_us = (uint32_t)dur_us;
          ev.status = status;
          ev.rung = rung;
          ev.method = r.method;
          ev.tenant = tenant_idx;
          ev.tp_len = (uint16_t)tp->size();
          memcpy(ev.tp, tp->data(), tp->size());
          std::string path, query;
          split_target(r.target, &path, &query);
          ev.path_len = (uint16_t)std::min(path.size(), sizeof(ev.path));
          memcpy(ev.path, path.data(), ev.path_len);
          events.push_back(ev);
        }
      }
    }
  }

  // minimal native protocol-error answer; closes after writing. These
  // cover only malformed-wire cases the Python front never sees intact
  // (it would be parsing the same broken bytes), so no parity template.
  void native_error(Conn* c, int status, const char* reason) {
    {
      std::lock_guard<std::mutex> lk(s_mu);
      stats.parse_errors++;
    }
    char date[64];
    http_date(date, sizeof(date));
    char body[128];
    int blen = snprintf(body, sizeof(body), "%d %s\n", status, reason);
    char buf[512];
    int n = snprintf(buf, sizeof(buf),
                     "HTTP/1.1 %d %s\r\nServer: oryx_tpu\r\nDate: %s\r\n"
                     "Content-Type: text/plain\r\nContent-Length: %d\r\n"
                     "Connection: close\r\n\r\n%s",
                     status, reason, date, blen, body);
    c->stop_parsing = true;
    c->in_body = false;
    uint32_t id = c->next_req_id++;
    c->outstanding++;
    c->close_after_id = id;
    complete(c, id, std::string(buf, n), true);
  }

  // returns false when the write error closed (and freed) the conn
  bool raw_write(Conn* c, const char* data) {
    int fd = c->fd;
    c->wq.emplace_back(data);
    flush_writes(c);
    return fd_to_id.count(fd) != 0;
  }

  // -- response ordering + writes ------------------------------------------

  // hand a response for req_id to the connection; releases in order.
  // Returns false when the conn was closed by this call.
  bool complete(Conn* c, uint32_t req_id, std::string data, bool force_close) {
    if (req_id != c->next_write_id) {
      c->parked.emplace(req_id, std::make_pair(std::move(data), force_close));
      return true;
    }
    bool closed = release(c, req_id, std::move(data), force_close);
    if (closed) return false;
    // drain any parked successors
    while (true) {
      auto it = c->parked.find(c->next_write_id);
      if (it == c->parked.end()) break;
      uint32_t id = it->first;
      std::string d = std::move(it->second.first);
      bool fc = it->second.second;
      c->parked.erase(it);
      if (release(c, id, std::move(d), fc)) return false;
    }
    return true;
  }

  // returns true when the conn was closed
  bool release(Conn* c, uint32_t req_id, std::string data, bool force_close) {
    c->wq.push_back(std::move(data));
    c->next_write_id = req_id + 1;
    if (c->outstanding > 0) c->outstanding--;
    bool close_now = force_close ||
                     (c->close_after_id != 0 && req_id >= c->close_after_id);
    int fd = c->fd;  // flush may free c on a dead socket
    flush_writes(c);
    if (!fd_to_id.count(fd)) return true;
    if (close_now && c->wq.empty()) {
      close_conn(c);
      return true;
    }
    if (close_now) c->stop_parsing = true;  // close when the queue drains
    return false;
  }

  void flush_writes(Conn* c) {
    while (!c->wq.empty()) {
      const std::string& front = c->wq.front();
      ssize_t w = write(c->fd, front.data() + c->woff, front.size() - c->woff);
      if (w > 0) {
        {
          std::lock_guard<std::mutex> lk(s_mu);
          stats.bytes_out += (uint64_t)w;
        }
        c->woff += (size_t)w;
        if (c->woff == front.size()) {
          c->wq.pop_front();
          c->woff = 0;
        }
        continue;
      }
      if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (!c->want_write) {
          c->want_write = true;
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.fd = c->fd;
          epoll_ctl(epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
        }
        return;
      }
      close_conn(c);
      return;
    }
    if (c->want_write) {
      c->want_write = false;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = c->fd;
      epoll_ctl(epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
    }
    // writer-side close for conns whose peer half-closed or that were
    // marked close-after once everything has drained
    if (c->wq.empty() && c->stop_parsing && c->outstanding == 0 &&
        c->close_after_id != 0 && c->next_write_id > c->close_after_id) {
      close_conn(c);
    }
  }

  void drain_inbox() {
    std::deque<Resp> batch;
    {
      std::lock_guard<std::mutex> lk(r_mu);
      batch.swap(inbox);
    }
    for (auto& r : batch) {
      auto it = conns.find(r.conn_id);
      if (it == conns.end()) {
        std::lock_guard<std::mutex> lk(s_mu);
        stats.responses_dropped++;
        continue;
      }
      complete(it->second.get(), r.req_id, std::move(r.data), r.close);
    }
  }

  // -- hf_poll frame assembly ----------------------------------------------

  int64_t poll_batch(uint8_t* buf, size_t cap, int timeout_ms) {
    std::unique_lock<std::mutex> lk(q_mu);
    if (pending.empty() && !q_closed)
      q_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                    [this] { return !pending.empty() || q_closed; });
    if (pending.empty()) return q_closed ? -1 : 0;
    std::string payload;
    uint32_t count = 0;
    while (!pending.empty()) {
      const ParsedRequest& r = pending.front();
      size_t rec = 24 + r.target.size() + r.body.size();
      for (const auto& kv : r.headers) rec += 4 + kv.first.size() + kv.second.size();
      rec = pad8(rec);
      if (kFrameHeader + pad8(payload.size() + rec) > cap) break;
      size_t start = payload.size();
      put_u32(payload, r.conn_id);
      put_u32(payload, r.req_id);
      payload.push_back((char)r.method);
      payload.push_back((char)r.flags);
      put_u16(payload, (uint16_t)r.headers.size());
      put_u32(payload, (uint32_t)r.target.size());
      put_u32(payload, (uint32_t)r.body.size());
      put_u32(payload, (uint32_t)rec);
      payload += r.target;
      for (const auto& kv : r.headers) {
        put_u16(payload, (uint16_t)kv.first.size());
        put_u16(payload, (uint16_t)kv.second.size());
        payload += kv.first;
        payload += kv.second;
      }
      payload += r.body;
      payload.resize(start + rec, '\0');
      ++count;
      pending.pop_front();
    }
    if (count == 0) return 0;  // caller buffer too small for one record
    uint64_t seq = batch_seq;
    batch_seq += count;
    bool was_full = pending.size() + count >= max_pending;
    lk.unlock();
    if (was_full) wake();  // nudge the epoll thread to resume paused reads
    std::string frame;
    frame.reserve(kFrameHeader + pad8(payload.size()));
    put_u32(frame, kMagic);
    put_u16(frame, kKindHttp);
    put_u16(frame, 0);
    put_u64(frame, seq);
    put_u32(frame, count);
    put_u32(frame, (uint32_t)payload.size());
    put_u32(frame, crc32_zlib((const uint8_t*)payload.data(), payload.size()));
    put_u32(frame, 0);
    frame += payload;
    frame.resize(kFrameHeader + pad8(payload.size()), '\0');
    memcpy(buf, frame.data(), frame.size());
    return (int64_t)frame.size();
  }
};

AnswerTemplate make_template(const uint8_t* pre, int64_t pre_len,
                             const uint8_t* post, int64_t post_len,
                             int64_t body_len, int status) {
  AnswerTemplate t;
  t.pre.assign((const char*)pre, (size_t)pre_len);
  t.post.assign((const char*)post, (size_t)post_len);
  t.body_len = (uint32_t)body_len;
  t.status = (uint16_t)status;
  t.gzip_large = body_len > 1024;
  return t;
}

}  // namespace

extern "C" {

void* hf_create(int port, int backlog, int64_t max_header, int64_t max_body,
                double idle_timeout_s, int64_t max_conns) {
  auto* f = new Front();
  if (max_header > 0) f->max_header = (size_t)max_header;
  if (max_body > 0) f->max_body = (size_t)max_body;
  if (idle_timeout_s > 0) f->idle_timeout = idle_timeout_s;
  if (max_conns > 0) f->max_conns = (size_t)max_conns;
  if (!f->start(port, backlog > 0 ? backlog : 128)) {
    delete f;
    return nullptr;
  }
  return f;
}

int hf_port(void* h) { return ((Front*)h)->port; }

// two-phase teardown: hf_shutdown stops the epoll thread, closes every
// socket, and unblocks hf_poll (returns -1) while keeping the handle
// alive, so late hf_respond callers see a clean -1 instead of a freed
// pointer; hf_close frees it once the binding has joined its threads.
void hf_shutdown(void* h) { ((Front*)h)->do_close(); }

void hf_close(void* h) { delete (Front*)h; }

int64_t hf_poll(void* h, uint8_t* buf, int64_t cap, int timeout_ms) {
  return ((Front*)h)->poll_batch(buf, (size_t)cap, timeout_ms);
}

int hf_respond(void* h, uint32_t conn_id, uint32_t req_id, const uint8_t* data,
               int64_t len, int close_after) {
  Front* f = (Front*)h;
  {
    std::lock_guard<std::mutex> lk(f->r_mu);
    if (f->closing) return -1;
    f->inbox.push_back({conn_id, req_id,
                        std::string((const char*)data, (size_t)len),
                        close_after != 0});
  }
  f->wake();
  return 0;
}

void hf_set_ladder(void* h, int stage, int retry_after_s, uint32_t flags) {
  Front* f = (Front*)h;
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->global_stage = (uint8_t)stage;
  f->retry_after_s = (uint16_t)retry_after_s;
  f->flags = flags;
}

// blob: [i32 default_idx][u32 n] then n x { u16 name_len, u8 stage, u8 pad,
// name bytes }
void hf_set_tenants(void* h, const uint8_t* blob, int64_t len) {
  Front* f = (Front*)h;
  std::vector<TenantEntry> out;
  int32_t def = -1;
  if (len >= 8) {
    memcpy(&def, blob, 4);
    uint32_t n;
    memcpy(&n, blob + 4, 4);
    size_t pos = 8;
    for (uint32_t i = 0; i < n && i < kMaxTenants; ++i) {
      if (pos + 4 > (size_t)len) break;
      uint16_t nl;
      memcpy(&nl, blob + pos, 2);
      uint8_t stage = blob[pos + 2];
      pos += 4;
      if (pos + nl > (size_t)len) break;
      TenantEntry t;
      t.name.assign((const char*)blob + pos, nl);
      t.stage = stage;
      pos += nl;
      out.push_back(std::move(t));
    }
  }
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->tenants = std::move(out);
  f->default_tenant = (def >= 0 && (size_t)def < f->tenants.size()) ? def : -1;
}

// blob: [u32 n] then n x { u16 len, bytes } — post-context-strip prefixes
void hf_set_exempt(void* h, const uint8_t* blob, int64_t len) {
  Front* f = (Front*)h;
  std::vector<std::string> out;
  if (len >= 4) {
    uint32_t n;
    memcpy(&n, blob, 4);
    size_t pos = 4;
    for (uint32_t i = 0; i < n; ++i) {
      if (pos + 2 > (size_t)len) break;
      uint16_t l;
      memcpy(&l, blob + pos, 2);
      pos += 2;
      if (pos + l > (size_t)len) break;
      out.emplace_back((const char*)blob + pos, l);
      pos += l;
    }
  }
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->exempt = std::move(out);
}

void hf_set_context(void* h, const uint8_t* prefix, int64_t len) {
  Front* f = (Front*)h;
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->context_path.assign((const char*)prefix, (size_t)len);
}

void hf_set_shed_template(void* h, const uint8_t* pre, int64_t pre_len,
                          const uint8_t* post, int64_t post_len,
                          int64_t body_len) {
  Front* f = (Front*)h;
  AnswerTemplate t = make_template(pre, pre_len, post, post_len, body_len, 429);
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->shed_tpl = std::move(t);
  f->have_shed_tpl = true;
}

void hf_set_snapshot(void* h, const uint8_t* path, int64_t path_len,
                     const uint8_t* pre, int64_t pre_len, const uint8_t* post,
                     int64_t post_len, int64_t body_len, int status) {
  Front* f = (Front*)h;
  std::string key((const char*)path, (size_t)path_len);
  AnswerTemplate t = make_template(pre, pre_len, post, post_len, body_len, status);
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->snapshots[std::move(key)] = std::move(t);
}

void hf_cache_cap(void* h, int64_t cap) {
  Front* f = (Front*)h;
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->cache_cap = cap > 0 ? (size_t)cap : 1;
}

void hf_cache_put(void* h, const uint8_t* key, int64_t key_len,
                  const uint8_t* pre, int64_t pre_len, const uint8_t* post,
                  int64_t post_len, int64_t body_len) {
  Front* f = (Front*)h;
  std::string k((const char*)key, (size_t)key_len);
  AnswerTemplate t = make_template(pre, pre_len, post, post_len, body_len, 200);
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  auto it = f->cache.find(k);
  if (it != f->cache.end()) {
    it->second.tpl = std::move(t);
    f->cache_lru.splice(f->cache_lru.begin(), f->cache_lru, it->second.lru);
    return;
  }
  f->cache_lru.push_front(k);
  f->cache.emplace(std::move(k), CacheEntry{std::move(t), f->cache_lru.begin()});
  while (f->cache.size() > f->cache_cap) {
    f->cache.erase(f->cache_lru.back());
    f->cache_lru.pop_back();
  }
}

void hf_cache_clear(void* h) {
  Front* f = (Front*)h;
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  f->cache.clear();
  f->cache_lru.clear();
}

int64_t hf_cache_size(void* h) {
  Front* f = (Front*)h;
  std::lock_guard<std::mutex> lk(f->cfg_mu);
  return (int64_t)f->cache.size();
}

// drain-and-reset aggregate counters into out (u64 slots). Layout:
// [0..23] scalars, [24..52] latency buckets, then per-tenant blocks of
// kTenantStatsLen slots for n_tenants tenants. Returns slots written.
int64_t hf_stats(void* h, uint64_t* out, int64_t cap, int n_tenants) {
  Front* f = (Front*)h;
  Stats s;
  std::vector<TenantStats> ts;
  {
    std::lock_guard<std::mutex> lk(f->s_mu);
    s = f->stats;
    f->stats = Stats();
    ts.swap(f->tstats);
  }
  int64_t need = kStatsScalars + kBuckets + 1 + (int64_t)n_tenants * kTenantStatsLen;
  if (cap < need) return -1;
  uint64_t* p = out;
  *p++ = s.conns_accepted;
  *p++ = s.conns_closed;
  *p++ = s.requests;
  *p++ = s.forwarded;
  *p++ = s.parse_errors;
  *p++ = s.answered[0];
  *p++ = s.answered[1];
  *p++ = s.answered[2];
  for (int i = 0; i < 5; ++i) *p++ = s.by_method[i];
  for (int i = 0; i < 5; ++i) *p++ = s.by_class[i];
  *p++ = s.lat_count;
  *p++ = s.lat_sum_us;
  *p++ = s.events_dropped;
  *p++ = s.responses_dropped;
  *p++ = s.bytes_in;
  *p++ = s.bytes_out;
  *p++ = s.pending_hwm;
  for (int i = 0; i < kBuckets + 1; ++i) *p++ = s.lat_buckets[i];
  for (int t = 0; t < n_tenants; ++t) {
    TenantStats blank;
    const TenantStats& src = (size_t)t < ts.size() ? ts[t] : blank;
    *p++ = src.count;
    *p++ = src.sum_us;
    *p++ = src.shed_stale;
    *p++ = src.shed_shed;
    for (int i = 0; i < kBuckets + 1; ++i) *p++ = src.buckets[i];
  }
  return p - out;
}

// drain trace events; each record is a fixed 184-byte struct:
// u64 wall_ms, u32 dur_us, u16 status, u8 rung, u8 method, i16 tenant,
// u16 tp_len, u16 path_len, 2 pad, char tp[64], char path[96].
int64_t hf_drain_trace(void* h, uint8_t* out, int64_t cap) {
  Front* f = (Front*)h;
  std::vector<TraceEvent> evs;
  {
    std::lock_guard<std::mutex> lk(f->s_mu);
    evs.swap(f->events);
  }
  constexpr int64_t kRec = 184;
  int64_t n = 0;
  uint8_t* p = out;
  for (const TraceEvent& e : evs) {
    if ((n + 1) * kRec > cap) break;
    memset(p, 0, kRec);
    memcpy(p, &e.wall_ms, 8);
    memcpy(p + 8, &e.dur_us, 4);
    memcpy(p + 12, &e.status, 2);
    p[14] = e.rung;
    p[15] = e.method;
    memcpy(p + 16, &e.tenant, 2);
    memcpy(p + 18, &e.tp_len, 2);
    memcpy(p + 20, &e.path_len, 2);
    memcpy(p + 24, e.tp, e.tp_len);
    memcpy(p + 88, e.path, e.path_len);
    p += kRec;
    ++n;
  }
  return n;
}

}  // extern "C"
