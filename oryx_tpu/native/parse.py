"""ctypes binding for the native columnar text parser (parse.cpp).

One GIL-released pass turns a text-frame block's fixed-width S-array of
``user,item,value[,timestamp]`` lines into the typed int32/int32/f32/i64
columns a KIND_COLS frame would have carried, plus the block-uniform id
prefixes. Strictly conservative: any line the native grammar cannot
reproduce bit-identically (quotes, JSON, non-canonical ids, oddball
numerics, malformed rows) makes the WHOLE block return None, and the
caller runs the Python parser — which also owns raising ``ValueError``
on genuinely bad input. ``None`` likewise when the library is absent
(build failure or ORYX_NATIVE=0), so pure-Python remains a clean
fallback everywhere.
"""

from __future__ import annotations

import ctypes
from typing import NamedTuple

import numpy as np

from oryx_tpu.native import get_library


class ParsedTextColumns(NamedTuple):
    """Typed columns for one text block, ready for
    ``rating_matrix_from_int_columns``."""

    users: np.ndarray  # int32
    items: np.ndarray  # int32
    values: np.ndarray  # float32
    timestamps: np.ndarray | None  # int64, None when no line carried one
    user_prefix: bytes
    item_prefix: bytes


def parse_text_columns(
    messages: np.ndarray | list[bytes], threads: int = 1
) -> ParsedTextColumns | None:
    """Parse a block of interaction lines natively, or None to fall back.

    ``messages`` is the S-dtype array a decoded RecordBlock holds (a list
    of bytes works too, for the non-block path). ``threads`` bounds the
    native worker threads; rows are split across them and the pass is
    GIL-released either way.
    """
    lib = get_library()
    if lib is None:
        return None
    if isinstance(messages, np.ndarray):
        arr = messages
    else:
        if not messages:
            return None
        try:
            arr = np.asarray(messages, dtype="S")
        except (TypeError, ValueError):
            return None
    if arr.dtype.kind != "S" or arr.ndim != 1:
        return None
    n = len(arr)
    w = arr.dtype.itemsize
    if n == 0 or w == 0:
        return None
    arr = np.ascontiguousarray(arr)
    users = np.empty(n, np.int32)
    items = np.empty(n, np.int32)
    values = np.empty(n, np.float32)
    ts = np.empty(n, np.int64)
    prefixes = np.zeros(32, np.uint8)
    flags = np.zeros(1, np.int32)
    c = ctypes
    rc = lib.als_parse_text_block(
        arr.ctypes.data_as(c.c_char_p),
        n,
        w,
        users.ctypes.data_as(c.POINTER(c.c_int32)),
        items.ctypes.data_as(c.POINTER(c.c_int32)),
        values.ctypes.data_as(c.POINTER(c.c_float)),
        ts.ctypes.data_as(c.POINTER(c.c_int64)),
        prefixes.ctypes.data_as(c.POINTER(c.c_uint8)),
        flags.ctypes.data_as(c.POINTER(c.c_int32)),
        max(1, int(threads)),
    )
    if rc != 0:
        return None
    uplen = int(prefixes[0])
    iplen = int(prefixes[16])
    return ParsedTextColumns(
        users,
        items,
        values,
        ts if int(flags[0]) & 1 else None,
        bytes(prefixes[1 : 1 + uplen]),
        bytes(prefixes[17 : 17 + iplen]),
    )
