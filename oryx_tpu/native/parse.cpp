// Columnar text-frame parser for the speed layer's KIND_TEXT control path.
//
// Input is the fixed-width S-array buffer a decoded RecordBlock holds: n
// rows of `width` bytes, each a `user,item,value[,timestamp]` line padded
// with trailing NULs. One GIL-released pass turns the block into typed
// u-i32 / i-i32 / v-f32 / ts-i64 columns plus the shared id prefixes —
// the same columns a typed KIND_COLS frame would have carried, feeding
// rating_matrix_from_int_columns directly.
//
// Parity contract (tests/native/test_native_parse.py): the parser either
// produces columns BIT-IDENTICAL to app/als/data.py's Python path, or
// returns -1 and the caller falls back to Python for the whole block. It
// therefore accepts only the strict canonical grammar it can reproduce
// exactly:
//   - ids are <ascii-prefix><canonical int32 decimal> (no leading zeros,
//     prefix uniform across the block, printable ASCII, <= 15 bytes) —
//     exactly the strings "u%d" re-rendering round-trips;
//   - values/timestamps are plain decimal floats (optional sign, dot,
//     exponent), parsed strtod -> double -> (float|int64) cast, matching
//     numpy's astype(f64).astype(f32|i64); empty value = NaN delete
//     marker, missing/empty timestamp = 0;
//   - anything else — quotes, JSON lines, >3 commas, non-ascii ids,
//     truncated/malformed rows, out-of-range numbers — rejects the whole
//     block so Python's slow paths (and its ValueError on <3 fields)
//     stay authoritative.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxPrefix = 15;
constexpr int kMaxNumber = 63;

inline bool is_digit(unsigned char c) { return c >= '0' && c <= '9'; }

// prefix bytes must round-trip ("u%d" % id == original): printable ascii,
// never a digit (the digit run must be maximal), a field comma, a quote
// (Python whole-block slow path) or a backslash (wire-escape ambiguity)
inline bool prefix_byte_ok(unsigned char c) {
  return c >= 0x20 && c <= 0x7e && !is_digit(c) && c != ',' && c != '"' &&
         c != '\\';
}

// <prefix><canonical-decimal-int32>: returns false when the field cannot
// round-trip bit-identically through the int fast path
bool parse_id(const char* p, const char* e, const char** pfx, int* pfx_len,
              int32_t* out) {
  const char* q = p;
  while (q < e && !is_digit((unsigned char)*q)) {
    if (!prefix_byte_ok((unsigned char)*q)) return false;
    ++q;
  }
  if (q == e || q - p > kMaxPrefix) return false;
  *pfx = p;
  *pfx_len = (int)(q - p);
  const char* d = q;
  while (q < e && is_digit((unsigned char)*q)) ++q;
  if (q != e) return false;  // trailing junk after the digit run
  int64_t ndig = q - d;
  if (ndig > 10) return false;
  if (*d == '0' && ndig > 1) return false;  // leading zero: "%d" won't round-trip
  int64_t v = 0;
  for (const char* c = d; c < q; ++c) v = v * 10 + (*c - '0');
  if (v > INT32_MAX) return false;
  *out = (int32_t)v;
  return true;
}

// strict decimal-float grammar: a subset of what strtod/numpy accept, so
// accepted fields parse to the identical double on both sides
bool float_grammar_ok(const char* p, const char* e) {
  if (p < e && (*p == '+' || *p == '-')) ++p;
  const char* int_start = p;
  while (p < e && is_digit((unsigned char)*p)) ++p;
  bool have_digits = p > int_start;
  if (p < e && *p == '.') {
    ++p;
    const char* frac_start = p;
    while (p < e && is_digit((unsigned char)*p)) ++p;
    have_digits = have_digits || p > frac_start;
  }
  if (!have_digits) return false;
  if (p < e && (*p == 'e' || *p == 'E')) {
    ++p;
    if (p < e && (*p == '+' || *p == '-')) ++p;
    const char* exp_start = p;
    while (p < e && is_digit((unsigned char)*p)) ++p;
    if (p == exp_start) return false;
  }
  return p == e;
}

bool parse_double(const char* p, const char* e, double* out) {
  if (e - p > kMaxNumber || !float_grammar_ok(p, e)) return false;
  char tmp[kMaxNumber + 1];
  size_t len = (size_t)(e - p);
  memcpy(tmp, p, len);
  tmp[len] = '\0';
  errno = 0;
  char* endp = nullptr;
  double d = strtod(tmp, &endp);
  if (endp != tmp + len || errno == ERANGE) return false;
  *out = d;
  return true;
}

struct RowRange {
  int64_t lo = 0, hi = 0;
  bool bad = false;
  bool has_ts = false;
  // block-uniform prefixes as observed by this range's first row
  const char* up = nullptr;
  int uplen = -1;  // -1: range empty / saw no rows
  const char* ip = nullptr;
  int iplen = -1;
};

void parse_rows(const char* buf, int64_t width, RowRange* rr, int32_t* users,
                int32_t* items, float* values, int64_t* ts_out) {
  for (int64_t r = rr->lo; r < rr->hi; ++r) {
    const char* p = buf + r * width;
    int64_t len = width;
    while (len > 0 && p[len - 1] == '\0') --len;
    if (len == 0 || memchr(p, '\0', (size_t)len) != nullptr) {
      rr->bad = true;  // empty row, or interior NUL (not S-padding)
      return;
    }
    const char* e = p + len;
    if (*p == '[' || *p == '{') {  // JSON line: Python slow path owns it
      rr->bad = true;
      return;
    }
    const char* c1 = (const char*)memchr(p, ',', (size_t)len);
    if (c1 == nullptr) {
      rr->bad = true;
      return;
    }
    const char* c2 = (const char*)memchr(c1 + 1, ',', (size_t)(e - c1 - 1));
    if (c2 == nullptr) {
      rr->bad = true;
      return;
    }
    const char* c3 = (const char*)memchr(c2 + 1, ',', (size_t)(e - c2 - 1));
    if (c3 != nullptr &&
        memchr(c3 + 1, ',', (size_t)(e - c3 - 1)) != nullptr) {
      rr->bad = true;  // >3 commas: Python's slow path drops extra tokens
      return;
    }
    const char* up;
    const char* ip;
    int uplen, iplen;
    if (!parse_id(p, c1, &up, &uplen, &users[r]) ||
        !parse_id(c1 + 1, c2, &ip, &iplen, &items[r])) {
      rr->bad = true;
      return;
    }
    if (rr->uplen < 0) {
      rr->up = up;
      rr->uplen = uplen;
      rr->ip = ip;
      rr->iplen = iplen;
    } else if (uplen != rr->uplen || iplen != rr->iplen ||
               memcmp(up, rr->up, (size_t)uplen) != 0 ||
               memcmp(ip, rr->ip, (size_t)iplen) != 0) {
      rr->bad = true;  // mixed prefixes cannot share one int vocab
      return;
    }
    const char* vend = (c3 != nullptr) ? c3 : e;
    if (c2 + 1 == vend) {
      values[r] = (float)NAN;  // empty value = delete marker
    } else {
      double v;
      if (!parse_double(c2 + 1, vend, &v)) {
        rr->bad = true;
        return;
      }
      values[r] = (float)v;  // f64 -> f32, same as astype chain
    }
    if (c3 == nullptr || c3 + 1 == e) {
      ts_out[r] = 0;  // missing/empty timestamp
    } else {
      double t;
      if (!parse_double(c3 + 1, e, &t) || !(t > -9.2e18 && t < 9.2e18)) {
        rr->bad = true;  // int64-cast of out-of-range double is UB
        return;
      }
      ts_out[r] = (int64_t)t;  // trunc toward zero, same as astype(i64)
    }
    if (c3 != nullptr) rr->has_ts = true;  // present (even empty) ts field
  }
}

}  // namespace

extern "C" {

// Parse n rows of `width` bytes into typed columns. prefix_out is 32
// bytes: [0]=uplen, [1..15]=user prefix, [16]=iplen, [17..31]=item
// prefix. flags_out bit0 = any row carried a timestamp field. Returns 0
// on success, -1 when the block must fall back to the Python parser.
int64_t als_parse_text_block(const char* buf, int64_t n, int64_t width,
                             int32_t* users, int32_t* items, float* values,
                             int64_t* ts_out, uint8_t* prefix_out,
                             int32_t* flags_out, int64_t num_threads) {
  if (n <= 0 || width <= 0) return -1;
  int64_t t = num_threads < 1 ? 1 : num_threads;
  if (t > 16) t = 16;
  int64_t min_rows = 8192;  // below this, thread spawn costs more than it saves
  if (t > (n + min_rows - 1) / min_rows) t = (n + min_rows - 1) / min_rows;
  std::vector<RowRange> ranges((size_t)t);
  int64_t per = (n + t - 1) / t;
  for (int64_t i = 0; i < t; ++i) {
    ranges[(size_t)i].lo = i * per;
    ranges[(size_t)i].hi = (i + 1) * per < n ? (i + 1) * per : n;
  }
  std::vector<std::thread> workers;
  for (int64_t i = 1; i < t; ++i)
    workers.emplace_back(parse_rows, buf, width, &ranges[(size_t)i], users,
                         items, values, ts_out);
  parse_rows(buf, width, &ranges[0], users, items, values, ts_out);
  for (auto& w : workers) w.join();
  const char* up = nullptr;
  const char* ip = nullptr;
  int uplen = -1, iplen = -1;
  bool has_ts = false;
  for (auto& rr : ranges) {
    if (rr.bad) return -1;
    has_ts = has_ts || rr.has_ts;
    if (rr.uplen < 0) continue;  // empty range
    if (uplen < 0) {
      up = rr.up;
      uplen = rr.uplen;
      ip = rr.ip;
      iplen = rr.iplen;
    } else if (rr.uplen != uplen || rr.iplen != iplen ||
               memcmp(rr.up, up, (size_t)uplen) != 0 ||
               memcmp(rr.ip, ip, (size_t)iplen) != 0) {
      return -1;  // ranges disagree on the block prefix
    }
  }
  if (uplen < 0) return -1;
  memset(prefix_out, 0, 32);
  prefix_out[0] = (uint8_t)uplen;
  if (uplen > 0) memcpy(prefix_out + 1, up, (size_t)uplen);
  prefix_out[16] = (uint8_t)iplen;
  if (iplen > 0) memcpy(prefix_out + 17, ip, (size_t)iplen);
  *flags_out = has_ts ? 1 : 0;
  return 0;
}

}  // extern "C"
