"""SLO specs and burn-rate verdicts over an open-loop run.

The harness treats SLOs as first-class test outcomes: a scenario
declares its SLO (p99 threshold, error-rate budget, burn-rate window)
and the run FAILS — as a pytest assertion or a nonzero fleet.py exit —
when any replica or the fleet as a whole burns budget faster than the
declared multiple. Definitions follow the SRE-workbook convention
implemented by ``common.metrics.SLOWindow``: burn rate = observed bad
fraction / budgeted bad fraction over a trailing window, so 1.0 means
"spending budget exactly as fast as allowed".

Two evidence sources compose:

- engine records (``LoadResult``) — client-observed truth, including
  queueing delay and requests that never reached a replica
  (``no-ready-replica``);
- replica ``/metrics`` snapshots — server-side truth per replica, from
  which ``burn_from_metrics`` computes error burn over a window by
  differencing the 5xx / request counters between polls.

Both must be green for the verdict to pass: a replica that 500s while
the router has already dropped it burns server-side budget even though
clients never saw it.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from collections import Counter

from oryx_tpu.loadgen.engine import LoadResult

__all__ = [
    "SLOSpec",
    "SLOVerdict",
    "burn_from_metrics",
    "evaluate_slo",
    "evaluate_tenant_slos",
]


@dataclass
class SLOSpec:
    """Declared SLO for a scenario run.

    p99_ms: client-observed p99 (including queueing delay) must be under
    this. error_rate: budgeted failure fraction (0.0 = zero-downtime — a
    single failed request fails the run). window_s: trailing window for
    burn-rate computation. max_burn: maximum tolerated burn rate over
    that window (ignored when error_rate is 0 — any failure is infinite
    burn by definition).
    """

    p99_ms: float = 500.0
    error_rate: float = 0.0
    window_s: float = 5.0
    max_burn: float = 1.0
    # quality dimension: minimum fraction of answered requests that must
    # be served at FULL quality (shed-ladder stage 0). None = no quality
    # assertion. Deliberate sheds never count against error_rate — they
    # count against this instead.
    min_full_quality: float | None = None


@dataclass
class SLOVerdict:
    passed: bool
    p99_ms: float
    error_rate: float
    failed_requests: int
    burn_rates: dict[str, float] = field(default_factory=dict)  # scope -> burn
    violations: list[str] = field(default_factory=list)
    # fraction of answered requests per shed-ladder stage (engine
    # LoadResult.quality()); {} for runs recorded before the ladder
    quality: dict[str, float] = field(default_factory=dict)

    def __bool__(self) -> bool:  # `assert verdict, verdict.violations`
        return self.passed


def evaluate_slo(result: LoadResult, spec: SLOSpec) -> SLOVerdict:
    """Judge one open-loop run against its declared SLO: fleet-wide p99
    and error rate from the engine's client-side records, plus per-replica
    error burn rates from each target's SLOWindow."""
    violations: list[str] = []
    p99_ms = result.latency_quantile(0.99) * 1000.0
    if p99_ms > spec.p99_ms:
        violations.append(f"fleet p99 {p99_ms:.1f} ms > SLO {spec.p99_ms:.1f} ms")
    if spec.error_rate <= 0.0:
        if result.failed:
            violations.append(
                f"zero-downtime SLO: {result.failed} failed request(s) "
                f"({dict(result.error_kinds)})"
            )
    elif result.error_rate > spec.error_rate:
        violations.append(
            f"fleet error rate {result.error_rate:.5f} > SLO {spec.error_rate:.5f}"
        )
    quality = result.quality()
    if spec.min_full_quality is not None:
        full = quality.get("full", 0.0)
        if full < spec.min_full_quality:
            violations.append(
                f"quality SLO: {full:.4f} full-quality answers < "
                f"required {spec.min_full_quality:.4f} "
                f"(per-stage {quality})"
            )
    burns: dict[str, float] = {}
    for name, target in result.per_target.items():
        burn = target.slo.error_burn_rate(spec.window_s, spec.error_rate)
        burns[name] = burn
        if spec.error_rate > 0.0 and burn > spec.max_burn:
            violations.append(
                f"replica {name} error burn {burn:.2f} > {spec.max_burn:.2f} "
                f"over {spec.window_s:.0f}s"
            )
    return SLOVerdict(
        passed=not violations,
        p99_ms=p99_ms,
        error_rate=result.error_rate,
        failed_requests=result.failed,
        burn_rates=burns,
        violations=violations,
        quality=quality,
    )


def evaluate_tenant_slos(
    result: LoadResult, specs: dict[str, SLOSpec]
) -> dict[str, "SLOVerdict"]:
    """Per-tenant verdicts over one multi-tenant open-loop run.

    Each tenant's records are carved out of the shared run and judged
    against the tenant's own declared SLO — the fairness contract
    (docs/multi-tenancy.md) is exactly that a noisy neighbour's burst
    must not flip a victim tenant's verdict. Tenants with a declared
    spec but no records get a failing verdict (a tenant that was starved
    out of the run entirely is the worst possible violation, not a
    vacuous pass). Per-replica burn windows are fleet-scoped, not
    tenant-scoped, so they are judged once in :func:`evaluate_slo`, not
    here."""
    grouped = result.tenant_records()
    verdicts: dict[str, SLOVerdict] = {}
    for tid, spec in specs.items():
        recs = grouped.get(tid, [])
        if not recs:
            verdicts[tid] = SLOVerdict(
                passed=False,
                p99_ms=0.0,
                error_rate=1.0,
                failed_requests=0,
                violations=[f"tenant {tid}: no completed requests in the run"],
            )
            continue
        n_ok = sum(1 for r in recs if r.ok)
        n_shed = sum(1 for r in recs if r.kind == "shed")
        sub = LoadResult(
            duration_s=result.duration_s,
            offered=len(recs),
            completed=len(recs),
            ok=n_ok,
            failed=len(recs) - n_ok - n_shed,
            error_kinds=Counter(
                r.kind for r in recs if not r.ok and r.kind != "shed"
            ),
            records=recs,
            queued_arrivals=0,
            peak_inflight=result.peak_inflight,
            per_target={},  # replica burn is fleet-scoped; judged once
            shed=n_shed,
        )
        verdict = evaluate_slo(sub, spec)
        verdict.violations = [f"tenant {tid}: {v}" for v in verdict.violations]
        verdict.passed = not verdict.violations
        verdicts[tid] = verdict
    return verdicts


def burn_from_metrics(
    before: dict, after: dict, window_s: float, slo_error_rate: float
) -> float:
    """Server-side error burn rate between two /metrics snapshots of one
    replica: delta(5xx) / delta(total responses), divided by the budgeted
    error fraction. Snapshots are the JSON bodies /metrics serves; missing
    counters count as 0 (a replica that served nothing burned nothing)."""

    def counter(snap: dict, name: str) -> float:
        entry = snap.get(name) or {}
        return float(entry.get("value") or 0.0)

    bad = counter(after, "serving.responses.5xx") - counter(before, "serving.responses.5xx")
    total = 0.0
    for klass in ("2xx", "3xx", "4xx", "5xx"):
        total += counter(after, f"serving.responses.{klass}") - counter(
            before, f"serving.responses.{klass}"
        )
    if total <= 0:
        return 0.0
    observed = bad / total
    if slo_error_rate <= 0.0:
        return float("inf") if observed > 0 else 0.0
    return observed / slo_error_rate
