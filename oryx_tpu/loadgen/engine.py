"""The open-loop engine: fire arrivals on their own clock, measure what
production users would feel.

Closed-loop generators (tools/traffic.py) hide overload: a slow server
slows the *generator*, so measured latency stays flat while real demand
would be queueing. This engine schedules requests from an arrival
process and fires them regardless of outstanding responses; latency is
measured **from the scheduled arrival time**, so scheduler lag and
worker-queue wait — the queueing delay open-loop exists to expose — land
in the reported percentiles instead of vanishing (the
coordinated-omission correction, per Tene's HdrHistogram argument).

Concurrency is bounded (``max_inflight`` pool workers) but *accounted*:
an arrival that finds every worker busy queues, and its eventual latency
includes the wait. ``LoadResult.queued_arrivals`` counts them — a
nonzero value at a sustainable rate means the bound, not the server, is
the bottleneck, and the run should be re-read accordingly.

Routing is readiness-aware across N replica targets: a poller thread
watches each target's /readyz and arrivals only route to ready replicas
(round-robin). A draining or faulted replica drops out of rotation
exactly the way it would behind a production load balancer — and if NO
replica is ready, the arrival is recorded as a ``no-ready-replica``
failure, which is what makes "zero-downtime" an assertable outcome.

Failures are classified by kind (timeout / http-5xx / http-4xx /
connection / no-ready-replica), never folded into latency stats.
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
import urllib.error
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from urllib.parse import urlsplit

from oryx_tpu.common import tracing
from oryx_tpu.common.metrics import SLOWindow

__all__ = [
    "KeepAliveClient",
    "LoadResult",
    "OpenLoopEngine",
    "RequestRecord",
    "Target",
    "classify_error",
]

# Mirrors oryx_tpu.serving.overload.SHED_HEADER / STAGE_NAMES — declared
# locally because importing the serving package would drag the whole
# layer (and jax) into the loadgen client; tests/serving/test_overload.py
# asserts the two stay in sync.
SHED_HEADER = "X-Oryx-Shed-Stage"
SHED_STAGES = ("full", "reduced-probe", "stale", "shed")
# Mirrors oryx_tpu.experiments.routing.ARM_HEADER the same way;
# tests/experiments/test_routing.py asserts the two stay in sync.
ARM_HEADER = "X-Oryx-Experiment-Arm"
# Mirrors oryx_tpu.tenancy.context.TENANT_HEADER / TENANT_PATH_PREFIX
# the same way; tests/tenancy/test_spec.py asserts they stay in sync.
TENANT_HEADER = "X-Oryx-Tenant"
TENANT_PATH_PREFIX = "/t/"


def _quantile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def classify_error(exc: Exception) -> str:
    """Map a request exception to an error KIND — timeouts must never be
    indistinguishable from 5xx (they exhaust client patience and server
    capacity in completely different ways)."""
    if isinstance(exc, urllib.error.HTTPError):
        return f"http-{exc.code // 100}xx"
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return "timeout"
    if isinstance(exc, urllib.error.URLError):
        reason = getattr(exc, "reason", None)
        if isinstance(reason, (socket.timeout, TimeoutError)):
            return "timeout"
        return "connection"
    return "connection"


class KeepAliveClient:
    """Persistent-connection HTTP client: one ``http.client``
    connection per (worker thread, scheme+host), reused across requests.

    urllib.request stamps ``Connection: close`` on every request, so
    each request pays a fresh TCP connect — which dominates the
    single-digit-ms latencies the native serving front produces and is
    the cost its keep-alive epoll path exists to amortize. Connect time
    is returned separately per request (0.0 on a reused socket) so
    reports can split connect from service.

    Failure semantics preserve crash-failover detection: a connection
    that dies after serving at least one request is retried ONCE on a
    fresh socket (the server may simply have reaped it idle between
    requests); a first-use failure, a timeout, or a repeat failure
    propagates, so a SIGKILLed replica still surfaces as an immediate
    connection error to the failover logic upstream.
    """

    def __init__(self, timeout_s: float = 10.0) -> None:
        self.timeout_s = float(timeout_s)
        self._local = threading.local()

    def _cache(self) -> dict:
        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
        return cache

    def _connect(self, key, timeout: float):
        scheme, netloc = key
        t0 = time.perf_counter()
        if scheme == "https":
            import ssl

            conn = http.client.HTTPSConnection(
                netloc, timeout=timeout,
                context=ssl._create_unverified_context(),
            )
        else:
            conn = http.client.HTTPConnection(netloc, timeout=timeout)
        conn.connect()
        return conn, time.perf_counter() - t0

    def close(self) -> None:
        """Close this THREAD's cached connections."""
        cache = self._cache()
        for entry in cache.values():
            try:
                entry[0].close()
            except Exception:  # noqa: BLE001
                pass
        cache.clear()

    def request(
        self, url: str, method: str = "GET", headers=None, body=None,
        timeout: float | None = None,
    ):
        """One request over a (possibly reused) persistent connection.

        Returns ``(status, headers, body_bytes, connect_s)`` — never
        raises for HTTP error statuses, only for transport failures.
        """
        parts = urlsplit(url)
        key = (parts.scheme or "http", parts.netloc)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        t = timeout if timeout is not None else self.timeout_s
        cache = self._cache()
        for attempt in (0, 1):
            entry = cache.get(key)
            connect_s = 0.0
            if entry is None:
                conn, connect_s = self._connect(key, t)
                entry = cache[key] = [conn, 0]
            conn, served = entry
            if conn.sock is not None:
                conn.sock.settimeout(t)
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
            except Exception as e:  # noqa: BLE001 - classified below
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                cache.pop(key, None)
                # only a previously-working keep-alive socket earns a
                # silent retry; timeouts are real latency, never retried
                retryable = isinstance(
                    e, (http.client.HTTPException, OSError)
                ) and not isinstance(e, (socket.timeout, TimeoutError))
                if served > 0 and attempt == 0 and retryable:
                    continue
                raise
            if resp.will_close:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                cache.pop(key, None)
            else:
                entry[1] = served + 1
            return resp.status, resp.msg, data, connect_s
        raise RuntimeError("unreachable")  # pragma: no cover


class Target:
    """One serving replica the engine routes to."""

    def __init__(self, name: str, base_url: str) -> None:
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.ready = True  # until the poller learns otherwise
        self.slo = SLOWindow()
        self.ok = 0
        self.failed = 0
        self.shed = 0  # deliberate 429s from the overload ladder
        self.error_kinds: Counter = Counter()

    def __repr__(self) -> str:  # pragma: no cover
        return f"Target({self.name} @ {self.base_url}, ready={self.ready})"


@dataclass
class RequestRecord:
    t_sched: float  # scheduled arrival, seconds from run start
    latency: float  # completion - scheduled arrival (includes queueing)
    service: float  # completion - send (server + network only)
    target: str
    ok: bool
    kind: str  # "ok" or an error kind
    # sampled requests carry a traceparent header, so the client-side
    # record can be joined against the server's spans in GET /trace
    trace_id: str | None = None
    # the X-Oryx-Shed-Stage response header: which overload-ladder rung
    # actually served the answer ("full" when absent)
    shed_stage: str = "full"
    # the X-Oryx-Experiment-Arm response header: which experiment arm
    # served the answer (None when no experiment attributed the request)
    arm: str | None = None
    # the user the request was issued for (arm-stickiness assertions
    # group records by user)
    user: int | None = None
    # the tenant the request was issued for (per-tenant SLO verdicts
    # group records by tenant); None on a single-tenant run
    tenant: str | None = None
    # seconds spent establishing TCP connections for this request (0.0
    # when the keep-alive socket was reused); reported separately so
    # connect cost never hides inside service latency
    connect_ms: float = 0.0


@dataclass
class LoadResult:
    duration_s: float
    offered: int  # arrivals scheduled
    completed: int  # responses received (ok or failed)
    ok: int
    failed: int
    error_kinds: Counter
    records: list[RequestRecord]
    queued_arrivals: int  # arrivals that found all workers busy
    peak_inflight: int
    per_target: dict[str, Target]
    # deliberate overload-ladder 429s (X-Oryx-Shed-Stage: shed). Counted
    # separately from `failed`: a shed is the server absorbing excess load
    # by design, not an outage — "zero failed requests" stays assertable
    # through a spike while quality() reports what the shedding cost.
    shed: int = 0
    # connection-refused attempts that failed over to a surviving replica
    # (crash failover); nonzero during a SIGKILL campaign, not an error
    retried: int = 0

    def tenant_records(self) -> dict[str, list[RequestRecord]]:
        """Records grouped by tenant (tenanted runs only)."""
        grouped: dict[str, list[RequestRecord]] = {}
        for r in self.records:
            if r.tenant is not None:
                grouped.setdefault(r.tenant, []).append(r)
        return grouped

    @property
    def offered_rate(self) -> float:
        return self.offered / self.duration_s if self.duration_s else 0.0

    @property
    def achieved_rate(self) -> float:
        return self.ok / self.duration_s if self.duration_s else 0.0

    @property
    def error_rate(self) -> float:
        return self.failed / self.completed if self.completed else 0.0

    def latency_quantile(self, q: float) -> float:
        lats = sorted(r.latency for r in self.records if r.ok)
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(q * len(lats)))]

    def service_quantile(self, q: float) -> float:
        svc = sorted(r.service for r in self.records if r.ok)
        if not svc:
            return 0.0
        return svc[min(len(svc) - 1, int(q * len(svc)))]

    def quality(self) -> dict[str, float]:
        """Fraction of ANSWERED requests served at each ladder stage —
        the achieved-quality dimension next to latency. Answered = ok
        responses plus deliberate sheds (the 429 IS the ladder's answer);
        genuine failures are excluded, they're accounted in `failed`."""
        answered = [r for r in self.records if r.ok or r.kind == "shed"]
        if not answered:
            return {stage: 0.0 for stage in SHED_STAGES}
        counts = Counter(r.shed_stage for r in answered)
        return {
            stage: counts.get(stage, 0) / len(answered) for stage in SHED_STAGES
        }

    def summary(self) -> dict:
        return {
            "duration_s": round(self.duration_s, 3),
            "offered": self.offered,
            "offered_rate": round(self.offered_rate, 2),
            "achieved_rate": round(self.achieved_rate, 2),
            "ok": self.ok,
            "failed": self.failed,
            "shed": self.shed,
            "quality": {k: round(v, 4) for k, v in self.quality().items()},
            "error_rate": round(self.error_rate, 6),
            "error_kinds": dict(self.error_kinds),
            "p50_ms": round(self.latency_quantile(0.50) * 1000, 2),
            "p99_ms": round(self.latency_quantile(0.99) * 1000, 2),
            "service_p99_ms": round(self.service_quantile(0.99) * 1000, 2),
            "connects": sum(1 for r in self.records if r.connect_ms > 0),
            "connect_p99_ms": round(
                _quantile([r.connect_ms for r in self.records
                           if r.connect_ms > 0], 0.99), 2),
            "queued_arrivals": self.queued_arrivals,
            "peak_inflight": self.peak_inflight,
            "retried": self.retried,
            "per_target": {
                name: {
                    "ok": t.ok,
                    "failed": t.failed,
                    "shed": t.shed,
                    "errors": dict(t.error_kinds),
                }
                for name, t in self.per_target.items()
            },
        }


class OpenLoopEngine:
    def __init__(
        self,
        targets: list[Target],
        template: str = "/probe/recommend/u%d",
        max_inflight: int = 128,
        timeout_s: float = 10.0,
        readiness_poll_s: float = 0.2,
        on_response=None,
        connect_retries: int = 1,
        tenant_mix: dict[str, float] | None = None,
        tenant_templates: dict[str, str] | None = None,
        tenant_seed: int = 0,
    ) -> None:
        if not targets:
            raise ValueError("need at least one target")
        self.targets = targets
        self.template = template
        # per-tenant traffic mix: tenant -> weight. Each arrival draws a
        # tenant (seeded, reproducible), routes under /t/<tenant>/ with
        # the tenant's own path template, and stamps the tenant on its
        # record so per-tenant SLOs are judged from the same run.
        self.tenant_mix = dict(tenant_mix) if tenant_mix else None
        self.tenant_templates = dict(tenant_templates or {})
        self._tenant_dist = None  # (sorted items, total weight)
        if self.tenant_mix:
            import random

            self._tenant_rng = random.Random(tenant_seed)
            items = sorted(self.tenant_mix.items())
            self._tenant_dist = (items, sum(w for _, w in items))
        self.max_inflight = int(max_inflight)
        self.timeout_s = float(timeout_s)
        self.readiness_poll_s = float(readiness_poll_s)
        # callable(user:int, status:int, headers, body:bytes) invoked for
        # every 2xx response — the hook scripted interaction feedback
        # (oryx_tpu/loadgen/feedback.py) uses to close the loop. Errors
        # are swallowed: feedback must never fail the load run.
        self.on_response = on_response
        # crash failover: a connection-refused attempt demotes its target
        # and retries on a surviving replica up to this many times — the
        # GET endpoints are idempotent, so failover cannot double-apply
        self.connect_retries = int(connect_retries)
        # persistent connections, one per (worker thread, target)
        self._client = KeepAliveClient(timeout_s=self.timeout_s)
        self._rr = 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._peak_inflight = 0
        self._retried = 0
        self._stop = threading.Event()

    # -- readiness routing ---------------------------------------------------

    def _poll_readiness(self) -> None:
        while not self._stop.wait(self.readiness_poll_s):
            for t in self.targets:
                try:
                    status, _, _, _ = self._client.request(
                        f"{t.base_url}/readyz"
                    )
                    # 404 = no /readyz resource on this server: treat as
                    # ready (bare routers); 503 = deliberately not ready
                    t.ready = status in (200, 404)
                except Exception:
                    t.ready = False

    def _pick_target(self) -> Target | None:
        with self._lock:
            n = len(self.targets)
            for i in range(n):
                t = self.targets[(self._rr + i) % n]
                if t.ready:
                    self._rr = (self._rr + i + 1) % n
                    return t
        return None

    # -- request execution ---------------------------------------------------

    def set_tenant_mix(self, tenant_mix: dict[str, float]) -> None:
        """Retune the per-tenant mix mid-run — how a scenario scripts a
        noisy-neighbor burst on a tenanted fleet. Only valid on an engine
        constructed with a tenant mix (the RNG is seeded there). The new
        distribution is swapped in as one tuple, so the arrival thread
        always reads a consistent (items, total) pair."""
        if self._tenant_dist is None:
            raise RuntimeError("engine was not constructed with a tenant mix")
        self.tenant_mix = dict(tenant_mix)
        items = sorted(self.tenant_mix.items())
        self._tenant_dist = (items, sum(w for _, w in items))

    def _pick_tenant(self) -> str | None:
        """Weighted seeded tenant draw for one arrival (None = untenanted)."""
        dist = self._tenant_dist
        if dist is None:
            return None
        items, total = dist
        r = self._tenant_rng.random() * total
        acc = 0.0
        for tid, w in items:
            acc += w
            if r < acc:
                return tid
        return items[-1][0]

    def _attempt(
        self, target: Target, user: int, ctx, tenant: str | None = None
    ) -> tuple[bool, str, str, str | None, float]:
        """One HTTP attempt against one target:
        (ok, kind, shed_stage, arm, connect_s)."""
        template = (
            self.tenant_templates.get(tenant, self.template)
            if tenant is not None
            else self.template
        )
        path = template % user if "%d" in template else template
        if tenant is not None:
            path = f"{TENANT_PATH_PREFIX}{tenant}{path}"
        headers = {}
        if ctx is not None:
            headers["traceparent"] = ctx.traceparent()
        try:
            status, hdrs, data, connect_s = self._client.request(
                target.base_url + path, headers=headers
            )
        except Exception as e:  # noqa: BLE001 - classified, not swallowed
            return False, classify_error(e), "full", None, 0.0
        shed_stage = hdrs.get(SHED_HEADER) or "full"
        arm = hdrs.get(ARM_HEADER)
        if 200 <= status < 300:
            if self.on_response is not None:
                try:
                    self.on_response(user, status, hdrs, data)
                except Exception:  # noqa: BLE001
                    pass
            return True, "ok", shed_stage, arm, connect_s
        if status < 400:  # 3xx
            return False, f"http-{status // 100}xx", shed_stage, arm, connect_s
        # a 429 stamped by the shed ladder is the overload controller
        # doing its job — account it as shed load, not as a failure
        if status == 429 and hdrs.get(SHED_HEADER) == "shed":
            return False, "shed", "shed", None, connect_s
        return False, f"http-{status // 100}xx", "full", None, connect_s

    def _execute(
        self,
        t_run0: float,
        t_sched: float,
        user: int,
        sink: list,
        tenant: str | None = None,
    ) -> None:
        t_send = time.perf_counter()
        t_wall0 = time.time()
        target = self._pick_target()
        ok = False
        kind = "ok"
        shed_stage = "full"
        arm = None
        connect_s = 0.0
        # client root span: sampled requests ship their context as a
        # traceparent header, so the server's serving.request (and the
        # queue-wait/scan/rescore spans under it) land in the same trace
        ctx = tracing.sample_root()
        if target is None:
            kind = "no-ready-replica"
        else:
            retries = 0
            while True:
                ok, kind, shed_stage, arm, c_s = self._attempt(
                    target, user, ctx, tenant
                )
                connect_s += c_s
                if kind != "connection" or retries >= self.connect_retries:
                    break
                # a replica refusing connections is GONE (SIGKILLed, not
                # draining — a drain answers 503s). Demote it now instead
                # of waiting out a readiness-poll tick, and fail the
                # request over to a surviving replica; the poller
                # re-promotes the slot when its /readyz answers 200 again
                target.ready = False
                nxt = self._pick_target()
                if nxt is None:
                    # no survivor to fail over to: keep the lone replica
                    # routable (the failure is recorded either way) and
                    # let the poller, if any, own its readiness
                    target.ready = True
                    break
                with self._lock:
                    self._retried += 1
                retries += 1
                target = nxt
        t_end = time.perf_counter()
        if ctx is not None:
            tracing.record_span(
                "client.request", ctx, None, t_wall0, t_end - t_send,
                {"target": target.name if target is not None else "-",
                 "kind": kind},
            )
        rec = RequestRecord(
            t_sched=t_sched,
            latency=(t_end - t_run0) - t_sched,
            service=t_end - t_send,
            target=target.name if target is not None else "-",
            ok=ok,
            kind=kind,
            trace_id=ctx.trace_id if ctx is not None else None,
            shed_stage=shed_stage,
            arm=arm,
            user=user,
            tenant=tenant,
            connect_ms=connect_s * 1000.0,
        )
        with self._lock:
            sink.append(rec)
            self._inflight -= 1
        if target is not None:
            if kind != "shed":
                # sheds stay out of the SLO window: the 429 is deliberate
                # absorption, not an error burning budget, and its tiny
                # latency would skew the quantiles the SLO is about
                target.slo.record(ok, rec.latency)
            with self._lock:
                if ok:
                    target.ok += 1
                elif kind == "shed":
                    target.shed += 1
                else:
                    target.failed += 1
                    target.error_kinds[kind] += 1

    # -- the run -------------------------------------------------------------

    def run(self, arrivals, users, duration_s: float) -> LoadResult:
        """Drive `arrivals` over `duration_s` seconds against the targets,
        users drawn from `users` (PowerLawUsers or any .one() provider).
        Returns after all scheduled requests complete (each is bounded by
        the request timeout, so the tail is bounded too)."""
        records: list[RequestRecord] = []
        offered = 0
        queued = 0
        self._stop.clear()
        poller = None
        if self.readiness_poll_s > 0:
            poller = threading.Thread(
                target=self._poll_readiness, name="LoadgenReadiness", daemon=True
            )
            poller.start()
        pool = ThreadPoolExecutor(
            max_workers=self.max_inflight, thread_name_prefix="LoadgenWorker"
        )
        t_run0 = time.perf_counter()
        try:
            for t_sched in arrivals.times(duration_s):
                # open loop: sleep until the scheduled arrival, then fire
                # whether or not earlier requests came back
                delay = t_sched - (time.perf_counter() - t_run0)
                if delay > 0:
                    time.sleep(delay)
                user = users.one()
                tenant = self._pick_tenant()
                with self._lock:
                    self._inflight += 1
                    if self._inflight > self.max_inflight:
                        queued += 1
                    self._peak_inflight = max(self._peak_inflight, self._inflight)
                offered += 1
                pool.submit(self._execute, t_run0, t_sched, user, records, tenant)
            pool.shutdown(wait=True)
        finally:
            self._stop.set()
            pool.shutdown(wait=False)
            if poller is not None:
                poller.join(timeout=self.readiness_poll_s + self.timeout_s + 1.0)
        with self._lock:
            recs = list(records)
        kinds = Counter(r.kind for r in recs if not r.ok and r.kind != "shed")
        n_ok = sum(1 for r in recs if r.ok)
        n_shed = sum(1 for r in recs if r.kind == "shed")
        return LoadResult(
            # rates are over the SCHEDULED window: the post-deadline tail
            # draining responses is not extra serving time
            duration_s=duration_s,
            offered=offered,
            completed=len(recs),
            ok=n_ok,
            failed=len(recs) - n_ok - n_shed,
            error_kinds=kinds,
            records=recs,
            queued_arrivals=queued,
            peak_inflight=self._peak_inflight,
            per_target={t.name: t for t in self.targets},
            shed=n_shed,
            retried=self._retried,
        )
