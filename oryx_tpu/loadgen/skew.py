"""User-id skew: which simulated user each arrival belongs to.

Production recommendation traffic is never uniform — a small head of
highly active users dominates, and operational incidents (a viral item,
a retry storm from one client) concentrate traffic onto a handful of hot
keys. Both shapes matter to the serving tier: power-law skew stresses
per-user state (known-items filters, batcher coalescing), hot keys
stress whatever caching or per-key locking exists.

``PowerLawUsers`` samples user INDICES in [0, n_users) with density
proportional to (i+1)^-exponent via inverse-CDF on the continuous
approximation — O(1) per sample and O(1) memory, so "millions of
simulated users" costs nothing. An optional hot-key set overlays it:
with probability ``hot_weight`` the sample comes uniformly from the
first ``hot_count`` ids instead.

Deterministic per seed; batched sampling for the engine's scheduler.
"""

from __future__ import annotations

import numpy as np

__all__ = ["PowerLawUsers"]


class PowerLawUsers:
    def __init__(
        self,
        n_users: int,
        exponent: float = 1.1,
        hot_count: int = 0,
        hot_weight: float = 0.0,
        seed: int = 0,
    ) -> None:
        if n_users < 1:
            raise ValueError(f"n_users must be >= 1, got {n_users}")
        if exponent <= 0:
            raise ValueError(f"exponent must be positive, got {exponent}")
        if not (0.0 <= hot_weight <= 1.0):
            raise ValueError(f"hot_weight must be in [0,1], got {hot_weight}")
        if hot_weight > 0.0 and hot_count < 1:
            raise ValueError("hot_weight set but hot_count < 1")
        self.n_users = int(n_users)
        self.exponent = float(exponent)
        self.hot_count = int(hot_count)
        self.hot_weight = float(hot_weight)
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)

    def _power_law(self, u: np.ndarray) -> np.ndarray:
        """Inverse CDF of density ~ x^-a on [1, n+1), mapped to [0, n)."""
        n = self.n_users
        a = self.exponent
        if abs(a - 1.0) < 1e-9:
            # a == 1: CDF is log(x)/log(n+1)
            x = np.power(float(n + 1), u)
        else:
            top = float(n + 1) ** (1.0 - a)
            x = np.power(1.0 + u * (top - 1.0), 1.0 / (1.0 - a))
        return np.minimum(x.astype(np.int64) - 1, n - 1)

    def sample(self, count: int) -> np.ndarray:
        """`count` user indices, power-law body + hot-key overlay."""
        rng = self._rng
        u = rng.random(count)
        ids = self._power_law(u)
        if self.hot_weight > 0.0:
            hot = rng.random(count) < self.hot_weight
            n_hot = int(hot.sum())
            if n_hot:
                ids[hot] = rng.integers(0, self.hot_count, n_hot)
        return ids

    def one(self) -> int:
        return int(self.sample(1)[0])
