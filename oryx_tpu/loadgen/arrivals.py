"""Open-loop arrival processes: when requests WOULD arrive.

An arrival process yields absolute arrival offsets (seconds from the
start of the run) independent of how the server is doing — that
independence is the entire point of open-loop load generation. All
randomness comes from one seeded numpy generator per process instance,
so a scenario re-runs with the identical arrival schedule.

Two processes cover the production shapes the harness needs:

- ``PoissonProcess`` — homogeneous Poisson arrivals at a fixed offered
  rate (exponential inter-arrivals), the memoryless baseline open-loop
  benchmarks assume.
- ``DiurnalRampProcess`` — a non-homogeneous Poisson process whose rate
  follows a raised-cosine diurnal curve between ``base_rate`` (trough)
  and ``peak_rate`` (peak) over ``period_s``, sampled by Lewis-Shedler
  thinning against the peak rate. Compressing a day into a bench-sized
  period exercises ramp-up behavior (batcher adaptation, autoscaling
  headroom) that a flat rate never touches.
"""

from __future__ import annotations

import math
from typing import Iterator

import numpy as np

__all__ = ["DiurnalRampProcess", "PoissonProcess"]


class PoissonProcess:
    """Homogeneous Poisson arrivals at `rate` requests/second."""

    def __init__(self, rate: float, seed: int = 0) -> None:
        if rate <= 0:
            raise ValueError(f"rate must be positive, got {rate}")
        self.rate = float(rate)
        self.seed = int(seed)

    def offered_rate(self, t: float) -> float:
        return self.rate

    def times(self, duration_s: float) -> Iterator[float]:
        """Arrival offsets in [0, duration_s), in increasing order."""
        rng = np.random.default_rng(self.seed)
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / self.rate))
            if t >= duration_s:
                return
            yield t

    def expected_arrivals(self, duration_s: float) -> float:
        return self.rate * duration_s


class DiurnalRampProcess:
    """Non-homogeneous Poisson arrivals on a raised-cosine diurnal curve.

    rate(t) = base + (peak - base) * (1 - cos(2*pi*(t/period + phase)))/2

    starts at the trough (phase 0), peaks at period/2. Thinning: candidate
    arrivals are drawn at the peak rate and accepted with probability
    rate(t)/peak — exact for any bounded rate function.
    """

    def __init__(
        self,
        base_rate: float,
        peak_rate: float,
        period_s: float,
        seed: int = 0,
        phase: float = 0.0,
    ) -> None:
        if base_rate <= 0 or peak_rate < base_rate:
            raise ValueError(
                f"need 0 < base_rate <= peak_rate, got {base_rate}/{peak_rate}"
            )
        if period_s <= 0:
            raise ValueError(f"period_s must be positive, got {period_s}")
        self.base_rate = float(base_rate)
        self.peak_rate = float(peak_rate)
        self.period_s = float(period_s)
        self.phase = float(phase)
        self.seed = int(seed)

    def offered_rate(self, t: float) -> float:
        swing = (self.peak_rate - self.base_rate) / 2.0
        c = 1.0 - math.cos(2.0 * math.pi * (t / self.period_s + self.phase))
        return self.base_rate + swing * c

    def times(self, duration_s: float) -> Iterator[float]:
        rng = np.random.default_rng(self.seed)
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / self.peak_rate))
            if t >= duration_s:
                return
            if rng.random() < self.offered_rate(t) / self.peak_rate:
                yield t

    def expected_arrivals(self, duration_s: float) -> float:
        # integrate rate(t) numerically — good enough for test tolerances
        n = max(100, int(duration_s * 10))
        ts = np.linspace(0.0, duration_s, n)
        return float(np.trapezoid([self.offered_rate(t) for t in ts], ts))
