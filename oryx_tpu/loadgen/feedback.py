"""Scripted interaction feedback: close the recommendation loop.

The online experiment evaluator (oryx_tpu/experiments/) can only judge
arms if served recommendations are followed by interaction events on the
input topic. In production those come from real users; in the harness,
:class:`ScriptedFeedback` plays the user: it parses each served response,
rolls a *deterministic* per-serve die against the serving generation's
scripted engagement rate, and on a hit emits a ``user,item,value`` event
for one of the served items — exactly the wire format the speed layer
(and the evaluator) already parse.

Determinism matters: the roll hashes (seed, user, per-user serve count),
so a run is reproducible and the realized engagement rate per generation
converges on the scripted one regardless of thread interleaving. Keep
the module stdlib-only: it runs inside the loadgen client.
"""

from __future__ import annotations

import hashlib
import json
import threading


def roll(seed: int, user, serve_index: int) -> float:
    """Deterministic uniform [0, 1) draw for one (user, serve)."""
    digest = hashlib.blake2b(
        f"{seed}:{user}:{serve_index}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


class ScriptedFeedback:
    """An ``on_response`` hook for :class:`~oryx_tpu.loadgen.engine.
    OpenLoopEngine` that emits scripted interaction events.

    ``send``: callable(line) delivering one ``user,item,value`` line to
    the input topic (the fleet harness wires a raw-broker producer).
    ``hit_rate_of``: callable(generation_id) -> engagement probability
    for answers served by that generation — the scripted ground truth
    that makes one arm genuinely better than the other.
    """

    def __init__(self, send, hit_rate_of, seed: int = 7) -> None:
        self.send = send
        self.hit_rate_of = hit_rate_of
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._serve_counts: dict[str, int] = {}
        self.sent = 0

    def on_response(self, user, status, headers, body: bytes) -> None:
        if status != 200 or not body:
            return
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(payload, dict):
            return
        items = payload.get("items")
        served_user = payload.get("user", user)
        generation = payload.get("generation_id")
        if not isinstance(items, list) or not items:
            return
        with self._lock:
            index = self._serve_counts.get(str(served_user), 0)
            self._serve_counts[str(served_user)] = index + 1
        p = float(self.hit_rate_of(generation))
        draw = roll(self.seed, served_user, index)
        if draw >= p:
            return  # no engagement for this serve
        # pick the engaged item from the served list, biased to the top
        # rank the way real click distributions are: reuse the sub-p
        # draw, squared, as the rank position
        rank = int((draw / p) ** 2 * len(items))
        item = items[min(rank, len(items) - 1)]
        self.send(f"{served_user},{item},1")
        with self._lock:
            self.sent += 1
