"""Scripted scenarios: what happens to the fleet WHILE traffic flows.

A scenario file (JSON; format documented in docs/traffic-harness.md)
declares the arrival process, the user skew, the SLO, and a timeline of
actions the driver executes mid-run — publish a new model generation,
roll back to an old one, open and close a chaos window on the update
bus, drain-restart a replica. The generator holds its offered rate
throughout; the SLO verdict at the end says whether the fleet absorbed
the timeline without letting users notice.

Example:

    {
      "duration_s": 10,
      "template": "/probe/recommend/u%d",
      "arrivals": {"process": "poisson", "rate": 150, "seed": 7},
      "skew": {"users": 1000000, "exponent": 1.1,
               "hot_count": 16, "hot_weight": 0.2, "seed": 7},
      "slo": {"p99_ms": 500, "error_rate": 0.0, "window_s": 5},
      "actions": [
        {"at": 2.0, "do": "publish", "metric": 0.95},
        {"at": 3.0, "do": "chaos", "drop": 0.2, "delay_ms": 5, "dup": 0.2},
        {"at": 5.0, "do": "chaos", "drop": 0, "delay_ms": 0, "dup": 0},
        {"at": 6.5, "do": "rollback", "generation": "first"}
      ]
    }

Action verbs are resolved by the driver (tools/fleet.py registers
publish / rollback / chaos / restart); this module owns parsing and the
timed execution thread, so tests can script scenarios against fakes.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from oryx_tpu.loadgen.arrivals import DiurnalRampProcess, PoissonProcess
from oryx_tpu.loadgen.skew import PowerLawUsers
from oryx_tpu.loadgen.slo import SLOSpec

__all__ = ["Action", "Scenario", "ScenarioRunner"]


@dataclass
class Action:
    at: float
    do: str
    args: dict[str, Any] = field(default_factory=dict)


@dataclass
class Scenario:
    duration_s: float = 10.0
    template: str = "/probe/recommend/u%d"
    arrivals_spec: dict[str, Any] = field(default_factory=lambda: {"process": "poisson", "rate": 100.0})
    skew_spec: dict[str, Any] = field(default_factory=lambda: {"users": 1_000_000})
    slo: SLOSpec = field(default_factory=SLOSpec)
    actions: list[Action] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Scenario":
        actions = [
            Action(
                at=float(a["at"]),
                do=str(a["do"]),
                args={k: v for k, v in a.items() if k not in ("at", "do")},
            )
            for a in d.get("actions", [])
        ]
        actions.sort(key=lambda a: a.at)
        slo = SLOSpec(**d.get("slo", {}))
        return cls(
            duration_s=float(d.get("duration_s", 10.0)),
            template=str(d.get("template", "/probe/recommend/u%d")),
            arrivals_spec=dict(d.get("arrivals", {"process": "poisson", "rate": 100.0})),
            skew_spec=dict(d.get("skew", {"users": 1_000_000})),
            slo=slo,
            actions=actions,
        )

    @classmethod
    def from_file(cls, path: str) -> "Scenario":
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def build_arrivals(self):
        spec = dict(self.arrivals_spec)
        process = spec.pop("process", "poisson")
        if process == "poisson":
            return PoissonProcess(rate=float(spec.get("rate", 100.0)), seed=int(spec.get("seed", 0)))
        if process == "diurnal":
            return DiurnalRampProcess(
                base_rate=float(spec.get("base_rate", 50.0)),
                peak_rate=float(spec.get("peak_rate", 200.0)),
                period_s=float(spec.get("period_s", self.duration_s)),
                seed=int(spec.get("seed", 0)),
                phase=float(spec.get("phase", 0.0)),
            )
        raise ValueError(f"unknown arrival process {process!r}")

    def build_skew(self) -> PowerLawUsers:
        spec = self.skew_spec
        return PowerLawUsers(
            n_users=int(spec.get("users", 1_000_000)),
            exponent=float(spec.get("exponent", 1.1)),
            hot_count=int(spec.get("hot_count", 0)),
            hot_weight=float(spec.get("hot_weight", 0.0)),
            seed=int(spec.get("seed", 0)),
        )


class ScenarioRunner(threading.Thread):
    """Executes a scenario's action timeline on its own thread while the
    engine generates load on the caller's. Handlers is a verb -> callable
    mapping; each callable receives the action's args as kwargs. Handler
    exceptions are recorded, never raised into the timer thread — the
    run's verdict surfaces them."""

    def __init__(
        self,
        actions: list[Action],
        handlers: dict[str, Callable[..., Any]],
        clock=time.monotonic,
    ) -> None:
        super().__init__(name="ScenarioRunner", daemon=True)
        self._actions = sorted(actions, key=lambda a: a.at)
        self._handlers = handlers
        self._clock = clock
        # NB: not `_stop` — threading.Thread uses that name internally
        self._halt = threading.Event()
        self.executed: list[Action] = []
        self.errors: list[tuple[Action, Exception]] = []

    def run(self) -> None:
        t0 = self._clock()
        for action in self._actions:
            delay = action.at - (self._clock() - t0)
            if delay > 0 and self._halt.wait(delay):
                return
            handler = self._handlers.get(action.do)
            if handler is None:
                self.errors.append(
                    (action, ValueError(f"no handler for action {action.do!r}"))
                )
                continue
            try:
                handler(**action.args)
                self.executed.append(action)
            except Exception as e:  # noqa: BLE001 - surfaced in verdict
                self.errors.append((action, e))

    def stop(self) -> None:
        self._halt.set()
