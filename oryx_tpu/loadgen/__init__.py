"""Open-loop production traffic generation for the serving tier.

Every number the repo produced before this package came from
*closed-loop* clients: each worker waits for its response before sending
the next request, so the offered rate collapses to whatever the server
sustains and queueing delay is structurally invisible (the
coordinated-omission trap). Production traffic does not wait. This
package generates traffic the way users do — arrivals fire on their own
clock regardless of outstanding responses — so saturation shows up as
queueing delay and shed load in the numbers instead of silently lowering
the measured rate.

- ``arrivals``  — Poisson and diurnal-ramp (non-homogeneous Poisson)
  arrival processes, seeded and deterministic.
- ``skew``      — power-law + hot-key user-id skew over millions of
  simulated users, without materializing a distribution table.
- ``engine``    — the open-loop engine: schedules arrivals, routes to N
  replica targets by readiness, bounds in-flight concurrency while
  *accounting* for queueing (latency is measured from the scheduled
  arrival, not from socket connect), and classifies failures by kind.
- ``slo``       — SLO specs and per-replica / fleet-wide burn-rate
  verdicts over the engine's records and replica /metrics.

The multi-replica fleet driver that composes these against real
ServingLayer replicas lives in tools/fleet.py; the scenario file format
and burn-rate definitions are documented in docs/traffic-harness.md.
"""

from oryx_tpu.loadgen.arrivals import DiurnalRampProcess, PoissonProcess
from oryx_tpu.loadgen.engine import LoadResult, OpenLoopEngine, Target
from oryx_tpu.loadgen.feedback import ScriptedFeedback
from oryx_tpu.loadgen.scenario import Action, Scenario, ScenarioRunner
from oryx_tpu.loadgen.skew import PowerLawUsers
from oryx_tpu.loadgen.slo import SLOSpec, SLOVerdict, evaluate_slo

__all__ = [
    "Action",
    "DiurnalRampProcess",
    "LoadResult",
    "OpenLoopEngine",
    "PoissonProcess",
    "PowerLawUsers",
    "Scenario",
    "ScenarioRunner",
    "ScriptedFeedback",
    "SLOSpec",
    "SLOVerdict",
    "Target",
    "evaluate_slo",
]
