"""Deploy-manifest pass (moved from tools/lint_deploy.py; the tool
remains as a thin shim).

The deploy/ tree is the part of the repo no test executes: a GKE
manifest whose container args name a CLI command that doesn't exist, a
probe pointing at a health path the serving layer never registered, a
Dockerfile COPY of a directory that was renamed, or an `oryx.*` key in
the shipped ConfigMap that reference.conf stopped declaring — all fail
at DEPLOY time, on someone else's pager. This pass cross-checks the
manifests against the code's actual surfaces.
"""

from __future__ import annotations

import re
from pathlib import Path

from oryx_tpu.analysis.core import (
    REPO_ROOT,
    AnalysisPass,
    Finding,
    Module,
    finding_from_problem,
    register,
)

DEFAULT_TARGETS = [REPO_ROOT / "deploy"]

# endpoints the serving layer's router registers unconditionally
# (oryx_tpu/serving/layer.py _ready/_healthz/_readyz/_metrics)
KNOWN_PROBE_PATHS = {"/ready", "/healthz", "/readyz", "/metrics"}

_ARGS_LINE = re.compile(r"""(?:args|command):\s*\[\s*["']([^"']+)["']""")
_PROBE_PATH = re.compile(r"httpGet:\s*\{?\s*path:\s*([^\s,}]+)")
_DOTTED_ORYX = re.compile(r"\boryx(?:\.[A-Za-z0-9_-]+)+")
_COPY = re.compile(r"^\s*COPY\s+(?:--[^\s]+\s+)*(.+)$")
_CASE_BRANCH = re.compile(r"^\s*([a-z|-]+)\)\s*$")
# script-local meta commands oryx-run.sh resolves itself, not via the CLI
_SCRIPT_META_COMMANDS = {"all", "*"}


def cli_commands() -> set[str]:
    """The real CLI dispatch table (oryx_tpu/cli.py COMMANDS)."""
    from oryx_tpu.cli import COMMANDS

    return set(COMMANDS)


def known_config_keys() -> set[str]:
    """Every dotted key AND block prefix reference.conf declares —
    flattened from the raw tree (not to_properties, which drops
    null-valued keys like oryx.als.rescorer-provider-class)."""
    from oryx_tpu.common import config as C

    keys: set[str] = set()

    def walk(node, path: str) -> None:
        if path:
            keys.add(path)
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}.{k}" if path else k)

    walk(C.get_default().as_dict(), "")
    return keys


def _lint_yaml(path: Path, text: str, commands: set[str], keys: set[str]) -> list[str]:
    problems: list[str] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        m = _ARGS_LINE.search(line)
        if m and m.group(1) not in commands:
            problems.append(
                f"{path}:{lineno}: container command {m.group(1)!r} is not an "
                f"oryx_tpu CLI command (have: {', '.join(sorted(commands))})"
            )
        for m in _PROBE_PATH.finditer(line):
            probe = m.group(1).strip("\"'")
            if probe not in KNOWN_PROBE_PATHS:
                problems.append(
                    f"{path}:{lineno}: probe path {probe!r} is not served "
                    f"(known: {', '.join(sorted(KNOWN_PROBE_PATHS))})"
                )
    problems.extend(_lint_config_keys(path, text, keys))
    return problems


def _lint_config_keys(path: Path, text: str, keys: set[str]) -> list[str]:
    problems: list[str] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        for m in _DOTTED_ORYX.finditer(line):
            ref = m.group(0).rstrip(".")
            if ref == "oryx.conf":  # the config FILE name, not a key
                continue
            if ref not in keys:
                problems.append(
                    f"{path}:{lineno}: config key {ref!r} is not declared "
                    "in reference.conf"
                )
    return problems


def _lint_dockerfile(path: Path, text: str, commands: set[str]) -> list[str]:
    problems: list[str] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        m = _COPY.match(line)
        if m:
            parts = m.group(1).split()
            for src in parts[:-1]:  # last token is the image destination
                if not (REPO_ROOT / src).exists():
                    problems.append(
                        f"{path}:{lineno}: COPY source {src!r} does not exist "
                        "in the repo (build context is the repo root)"
                    )
        m = re.match(r"^\s*CMD\s*\[\s*\"([^\"]+)\"", line)
        if m and m.group(1) not in commands:
            problems.append(
                f"{path}:{lineno}: CMD command {m.group(1)!r} is not an "
                f"oryx_tpu CLI command"
            )
    return problems


def _lint_run_script(path: Path, text: str, commands: set[str]) -> list[str]:
    problems: list[str] = []
    in_dispatch = False
    for lineno, line in enumerate(text.splitlines(), 1):
        # only the COMMAND dispatch table names CLI commands; other case
        # blocks (option parsing) are out of scope
        if re.match(r'^\s*case\s+"\$\{?COMMAND\}?"', line):
            in_dispatch = True
            continue
        if in_dispatch and re.match(r"^\s*esac", line):
            in_dispatch = False
            continue
        if not in_dispatch:
            continue
        m = _CASE_BRANCH.match(line)
        if not m:
            continue
        for cmd in m.group(1).split("|"):
            if cmd and cmd not in commands and cmd not in _SCRIPT_META_COMMANDS:
                problems.append(
                    f"{path}:{lineno}: dispatches {cmd!r}, which is not an "
                    f"oryx_tpu CLI command"
                )
    return problems


def _iter_files(paths: list[Path]):
    for p in paths:
        if p.is_dir():
            yield from sorted(f for f in p.rglob("*") if f.is_file())
        else:
            yield p


def run_lint(paths: list[Path] | None = None) -> tuple[int, list[str], str]:
    """Returns (exit code, problem lines, engine used)."""
    paths = paths or DEFAULT_TARGETS
    commands = cli_commands()
    keys = known_config_keys()
    problems: list[str] = []
    for f in _iter_files(paths):
        try:
            text = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            problems.append(f"{f}: unreadable: {e}")
            continue
        if f.suffix in (".yaml", ".yml"):
            problems.extend(_lint_yaml(f, text, commands, keys))
        elif f.name == "Dockerfile":
            problems.extend(_lint_dockerfile(f, text, commands))
        elif f.suffix == ".sh":
            problems.extend(_lint_run_script(f, text, commands))
        elif f.suffix in (".md", ".conf"):
            problems.extend(_lint_config_keys(f, text, keys))
    return (1 if problems else 0), problems, "deploy-manifests"


@register
class DeployManifestsPass(AnalysisPass):
    pass_id = "deploy"
    description = (
        "deploy manifests cross-checked against CLI commands, probe "
        "endpoints, COPY sources, and reference.conf keys"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        _, problems, _ = run_lint()
        return [
            finding_from_problem(self.pass_id, "ORX403", p) for p in problems
        ]
