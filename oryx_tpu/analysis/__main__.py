"""``python -m oryx_tpu.analysis`` — run oryxlint over the tree."""

import sys

from oryx_tpu.analysis.core import main

if __name__ == "__main__":
    sys.exit(main())
