"""Resource-lifecycle analysis (ownership + release obligations).

The lambda architecture runs for weeks; a leaked thread, bus consumer,
shm guard slot, mmap, socket, or device-resident fold-in session per
restart/chaos event is a slow death. This pass walks the AST hunting
*acquisition sites* and checks that every acquired resource has a
reachable — and idempotent — release path.

What counts as an acquisition (the repo's resource vocabulary):

- ``threading.Thread`` / ``SupervisedThread`` / ``Timer`` construction
- broker handles: ``*.consumer(...)`` (guard slots, sockets,
  server-side sessions) and long-lived ``*.producer(...)`` handles held
  on ``self`` (local producers are almost always ``with``-scoped)
- raw OS resources: ``open``/``*.open``, ``mmap.mmap``,
  ``socket.socket``/``create_connection``, ``subprocess.Popen``
- device-resident fold state: ``FoldInSession`` /
  ``PartitionedFoldInSession`` (HBM buffers live as long as the ref)
- shm ring attach (``_Ring(...)``) and broker/layer/server objects that
  own rings and threads (``ShmBroker``, ``*Layer``, ``*Server``)

Ownership model: a resource assigned to ``self.X`` (or stored into a
``self.X`` container) is *owned by the class* — some method must release
it (call ``close/stop/join/...`` on it, pass it to a releaser like
``join_or_report_leak``, or explicitly drop the reference with
``self.X = None``). A resource bound to a local is *owned by the
function* unless it escapes (returned, yielded, stored on an object,
put in a container, or passed to another call — ownership transfer).

Rules:

- ORX501 exception-path leak: a function-local acquisition IS released
  later in the same function, but the release is not in a ``finally``
  (nor is the acquisition ``with``-managed) and statements that can
  raise sit between acquire and release — an exception strands it.
- ORX502 close-unreachable: a class owns a resource attribute no method
  ever releases.
- ORX503 non-idempotent double-close: a ``close()`` that releases owned
  resources with no idempotency idiom (no ``_closed``-flag check, no
  per-handle None-guard/pop) — double close from a driver + atexit
  double-releases guard slots / sockets.
- ORX504 thread without join/stop wiring: an owned thread object no
  method ever ``join``s (or hands to a joiner).
- ORX505 live-handle overwrite: ``self.X = <acquire>`` outside
  ``__init__`` with no preceding release or None-guard on ``self.X`` —
  the old handle is dropped live.
- ORX506 never-released local: a function-local acquisition that never
  escapes and is never released on ANY path.

Like the lockset pass, this errs quiet: one-level aliasing only, any
call that receives the handle counts as a release/transfer, and
``with``-managed acquisitions are always fine. What still fires is
either a real leak (fix it) or a deliberate design (baseline it with a
justification comment).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from oryx_tpu.analysis.core import AnalysisPass, Finding, Module, register

_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__enter__"}

# attribute-call names that release / tear down a resource
_RELEASE_METHODS = {
    "close", "stop", "shutdown", "join", "terminate", "kill", "release",
    "release_slot", "drain", "drop", "disconnect", "server_close", "wait",
    "communicate", "cancel", "unlink", "cleanup", "deinstrument",
}
# a method with one of these names is a teardown context: assigning None
# to an owned attr there counts as an explicit release (drop-the-ref is
# the only way to free GC-owned resources like fold-in sessions)
_TEARDOWN_METHODS = {"close", "stop", "shutdown", "reset", "clear", "teardown",
                     "drain", "__exit__", "__del__", "_reset", "release"}

_THREAD_CTORS = {"Thread", "SupervisedThread", "Timer"}
# constructor names (last dotted segment) -> resource kind
_ACQUIRE_CTORS = {
    "Thread": "thread",
    "SupervisedThread": "thread",
    "Timer": "thread",
    "Popen": "subprocess",
    "FoldInSession": "session",
    "PartitionedFoldInSession": "session",
    "_Ring": "ring",
    "ShmBroker": "broker",
}
# method-call names (x.consumer(...)) -> resource kind
_ACQUIRE_METHODS = {
    "consumer": "consumer",
    "mmap": "mmap",
    "socket": "socket",
    "create_connection": "socket",
}
_OPEN_NAMES = {"open"}
# class-name suffixes that denote resource-owning objects with close()
_ACQUIRE_SUFFIXES = (("Layer", "layer"), ("Server", "server"))


def _call_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _acquire_kind(node: ast.AST) -> str | None:
    """Resource kind for an expression, or None. Recognizes direct
    constructor/factory calls only — wrappers are the caller's problem."""
    if not isinstance(node, ast.Call):
        return None
    name = _call_name(node)
    if name is None:
        return None
    if name in _ACQUIRE_CTORS:
        return _ACQUIRE_CTORS[name]
    if isinstance(node.func, ast.Attribute) and name in _ACQUIRE_METHODS:
        return _ACQUIRE_METHODS[name]
    if name in _OPEN_NAMES:
        return "file"
    for suffix, kind in _ACQUIRE_SUFFIXES:
        if name.endswith(suffix) and name != suffix:
            return kind
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mentions_self_attr(node: ast.AST, attr: str) -> bool:
    for sub in ast.walk(node):
        if _self_attr(sub) == attr:
            return True
        # getattr(self, "attr", ...) is a mention too — the defensive
        # spelling used before __init__ has run
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "getattr"
            and len(sub.args) >= 2
            and isinstance(sub.args[0], ast.Name)
            and sub.args[0].id == "self"
            and isinstance(sub.args[1], ast.Constant)
            and sub.args[1].value == attr
        ):
            return True
    return False


def _mentions(node: ast.AST, attr: str, aliases: set) -> bool:
    """self.attr, getattr(self, "attr"), or a one-level local alias."""
    if _mentions_self_attr(node, attr):
        return True
    return any(
        isinstance(n, ast.Name) and n.id in aliases for n in ast.walk(node)
    )


def _can_raise(stmt: ast.stmt) -> bool:
    """Could this statement raise? Calls, raises, attribute chases —
    close enough; pure constants/pass/continue cannot."""
    for sub in ast.walk(stmt):
        if isinstance(sub, (ast.Call, ast.Raise, ast.Assert, ast.Subscript)):
            return True
    return False


# ---------------------------------------------------------------------------
# class-level ownership


@dataclass
class OwnedAttr:
    attr: str
    kind: str
    line: int
    method: str
    container: bool  # stored via self.X[...] = / self.X.append(...)


@dataclass
class ClassOwnership:
    name: str
    path: Path
    owned: dict = field(default_factory=dict)  # attr -> OwnedAttr (first site)
    methods: dict = field(default_factory=dict)  # name -> ast node
    released: set = field(default_factory=set)  # attrs with a release path
    joined: set = field(default_factory=set)  # thread attrs join()ed / handed off
    guarded_overwrites: set = field(default_factory=set)
    overwrites: list = field(default_factory=list)  # (attr, method, line)


def _attr_aliases(body: list[ast.stmt], attr: str) -> set:
    """Local names bound (one level) from an expression mentioning
    ``self.attr`` — loop vars iterating it, pops, direct reads."""
    names: set[str] = set()
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Assign) and _mentions_self_attr(sub.value, attr):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(sub, (ast.For, ast.AsyncFor)) and _mentions_self_attr(
                sub.iter, attr
            ):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(sub, ast.comprehension) and _mentions_self_attr(
                sub.iter, attr
            ):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
    return names


def _method_releases(node: ast.AST, attr: str, aliases: set) -> tuple[bool, bool]:
    """(released, joined) for ``self.attr`` within one method body."""
    released = joined = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            # self.X.close() / self.X[i].join() / alias.close() / alias.join()
            if isinstance(fn, ast.Attribute) and fn.attr in _RELEASE_METHODS:
                base = fn.value
                hit = _mentions_self_attr(base, attr) or (
                    isinstance(base, ast.Name) and base.id in aliases
                )
                if hit:
                    released = True
                    if fn.attr in ("join", "stop", "terminate", "kill", "cancel"):
                        joined = True
            # self.X (or alias/starred) passed to any call: handoff —
            # join_or_report_leak(self._t), atexit.register(c.close), ...
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                inner = arg.value if isinstance(arg, ast.Starred) else arg
                if _mentions_self_attr(inner, attr) or (
                    isinstance(inner, ast.Name) and inner.id in aliases
                ):
                    # reading an attr of it (self.X.foo as arg) is not a
                    # handoff; the bare handle (or something derived by
                    # subscript/iteration) is
                    if not (
                        isinstance(inner, ast.Attribute)
                        and _self_attr(inner) is None
                    ):
                        released = True
                        joined = True
    return released, joined


def _collect_class(cls: ast.ClassDef, path: Path) -> ClassOwnership:
    own = ClassOwnership(cls.name, path)
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            own.methods[node.name] = node

    for mname, mnode in own.methods.items():
        # one-level transfer: "x = Acquire(...)" then "self.attr = x"
        local_kinds: dict[str, str] = {}
        for sub in ast.walk(mnode):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                k = _acquire_kind(sub.value)
                if k:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            local_kinds[tgt.id] = k
        for sub in ast.walk(mnode):
            # self.X = ACQ  /  self.X: T = ACQ  /  a = self.X = ACQ
            targets, value = [], None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                targets, value = [sub.target], sub.value
            if value is None:
                continue
            kind = _acquire_kind(value)
            if kind is None and isinstance(value, ast.Name):
                kind = local_kinds.get(value.id)
            direct_kind = kind
            container = False
            if kind is None and isinstance(value, (ast.ListComp, ast.List)):
                # self.X = [ACQ for ...] / [ACQ, ...]
                for inner in ast.walk(value):
                    k = _acquire_kind(inner)
                    if k:
                        kind, container = k, True
                        break
            if kind is None:
                continue
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    own.owned.setdefault(
                        attr, OwnedAttr(attr, kind, sub.lineno, mname, container)
                    )
                    if direct_kind and mname not in _INIT_METHODS:
                        own.overwrites.append((attr, mname, sub.lineno, sub))
                elif isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value)
                    if attr is not None:
                        own.owned.setdefault(
                            attr, OwnedAttr(attr, kind, sub.lineno, mname, True)
                        )
        # self.X.append(ACQ) / self.X.setdefault(k, ACQ)
        for sub in ast.walk(mnode):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("append", "add", "setdefault", "insert")
            ):
                attr = _self_attr(fn.value)
                if attr is None:
                    continue
                for arg in sub.args:
                    k = _acquire_kind(arg) or (
                        "thread"
                        if isinstance(arg, ast.Name)
                        and _local_is_thread(mnode, arg.id)
                        else None
                    )
                    if k:
                        own.owned.setdefault(
                            attr, OwnedAttr(attr, k, sub.lineno, mname, True)
                        )

    # release reachability: scan every method for each owned attr
    for attr in own.owned:
        for mname, mnode in own.methods.items():
            aliases = _attr_aliases(mnode.body, attr)
            released, joined = _method_releases(mnode, attr, aliases)
            if released:
                own.released.add(attr)
            if joined:
                own.joined.add(attr)
            # explicit drop in a teardown method: self.X = None / del
            if mname in _TEARDOWN_METHODS or any(
                t in mname for t in ("close", "stop", "shutdown")
            ):
                for sub in ast.walk(mnode):
                    if (
                        isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Constant)
                        and sub.value.value is None
                        and any(_self_attr(t) == attr for t in sub.targets)
                    ):
                        own.released.add(attr)
                        own.joined.add(attr)
                    elif isinstance(sub, ast.Delete) and any(
                        _self_attr(t) == attr for t in sub.targets
                    ):
                        own.released.add(attr)
                        own.joined.add(attr)
    return own


def _local_is_thread(fn_node: ast.AST, name: str) -> bool:
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            cname = _call_name(sub.value)
            if cname in _THREAD_CTORS and any(
                isinstance(t, ast.Name) and t.id == name for t in sub.targets
            ):
                return True
    return False


def _check_overwrites(own: ClassOwnership) -> list[Finding]:
    """ORX505: re-acquire into an owned attr with no release/guard."""
    out = []
    flagged = set()
    for attr, mname, line, assign in own.overwrites:
        if own.owned[attr].method == mname and own.owned[attr].line == line:
            # the first (defining) acquisition — only re-acquisitions
            # outside init are overwrite candidates
            if mname in _INIT_METHODS:
                continue
        if (attr, mname) in flagged:
            continue
        mnode = own.methods[mname]
        safe = False
        # preceding release of self.attr in the same method — either
        # self.X.close()/alias.close(), or a bare self-release method
        # ("self.drop(); ... self._sock = sock" — release-before-reacquire)
        aliases = _attr_aliases(mnode.body, attr)
        for sub in ast.walk(mnode):
            if getattr(sub, "lineno", line) >= line:
                continue
            if isinstance(sub, ast.Call):
                fn = sub.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _RELEASE_METHODS
                    and (
                        _mentions_self_attr(fn.value, attr)
                        or (isinstance(fn.value, ast.Name) and fn.value.id in aliases)
                        or (isinstance(fn.value, ast.Name) and fn.value.id == "self")
                    )
                ):
                    safe = True
        # or the assignment sits under a test mentioning self.attr (or a
        # local alias of it): "if self.X is None: self.X = acquire()",
        # "ps = self._s; if ps is None ...: ps = Acquire(); self._s = ps"
        for sub in ast.walk(mnode):
            if isinstance(sub, (ast.If, ast.IfExp, ast.While)) and _mentions(
                sub.test, attr, aliases
            ):
                # ast.IfExp carries single expression nodes where If/While
                # carry statement lists — normalize both arms to lists
                arm = sub.body if isinstance(sub.body, list) else [sub.body]
                orelse = getattr(sub, "orelse", [])
                if not isinstance(orelse, list):
                    orelse = [orelse]
                if any(s is assign for st in arm for s in ast.walk(st)) or any(
                    s is assign for st in orelse for s in ast.walk(st)
                ):
                    safe = True
        # or it's a conditional-expression guard on the same line
        if not safe and isinstance(assign.value, ast.IfExp):
            safe = _mentions(assign.value.test, attr, aliases)
        # or a guard clause earlier in the method bails out when the
        # handle is live: "if self.X is not None: raise/return"
        if not safe:
            for sub in ast.walk(mnode):
                if (
                    isinstance(sub, ast.If)
                    and getattr(sub, "lineno", line) < line
                    and _mentions(sub.test, attr, aliases)
                    and any(
                        isinstance(s, (ast.Raise, ast.Return)) for s in sub.body
                    )
                ):
                    safe = True
        if not safe:
            flagged.add((attr, mname))
            out.append(
                Finding(
                    "lifecycle",
                    "ORX505",
                    own.path,
                    line,
                    f"{own.name}.{attr}",
                    f"{mname}() re-acquires into {attr!r} without releasing "
                    f"or None-checking the live handle it may overwrite "
                    f"(line {line})",
                )
            )
    return out


def _check_double_close(own: ClassOwnership) -> list[Finding]:
    """ORX503: close() releases owned resources with no idempotency
    idiom (flag check, per-handle None-guard, pop-and-release)."""
    out = []
    close = own.methods.get("close")
    if close is None or not own.owned:
        return out
    direct = []  # owned attrs this close() releases directly
    for attr in own.owned:
        aliases = _attr_aliases(close.body, attr)
        released, _ = _method_releases(close, attr, aliases)
        if released:
            direct.append(attr)
    if not direct:
        return out
    # idiom 1: a closed/stopped flag tested anywhere in close()
    for sub in ast.walk(close):
        if isinstance(sub, (ast.If, ast.While, ast.IfExp)):
            for n in ast.walk(sub.test):
                a = _self_attr(n)
                if a and any(t in a for t in ("closed", "stopped", "shut", "done")):
                    return out
    # idiom 2: every directly-released attr is None-guarded or popped,
    # or nulled out after release
    for attr in direct:
        guarded = False
        for sub in ast.walk(close):
            if isinstance(sub, (ast.If, ast.IfExp)) and _mentions_self_attr(
                sub.test, attr
            ):
                guarded = True
            if (
                isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Constant)
                and sub.value.value is None
                and any(_self_attr(t) == attr for t in sub.targets)
            ):
                guarded = True
            if isinstance(sub, ast.Call) and (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "pop"
                and _mentions_self_attr(sub.func.value, attr)
            ):
                guarded = True
        if not guarded:
            out.append(
                Finding(
                    "lifecycle",
                    "ORX503",
                    own.path,
                    close.lineno,
                    f"{own.name}.close",
                    f"close() releases {attr!r} with no idempotency idiom "
                    f"(no closed-flag check, None-guard, or pop) — a second "
                    f"close() double-releases it",
                )
            )
            return out  # one finding per close() is enough signal
    return out


def _check_class(own: ClassOwnership) -> list[Finding]:
    findings: list[Finding] = []
    for attr, o in sorted(own.owned.items()):
        if o.kind == "thread":
            if attr not in own.joined and attr not in own.released:
                findings.append(
                    Finding(
                        "lifecycle",
                        "ORX504",
                        own.path,
                        o.line,
                        f"{own.name}.{attr}",
                        f"thread(s) stored in {attr!r} (line {o.line}) are "
                        f"never join()ed or handed to a joiner — stop/join "
                        f"wiring is missing",
                    )
                )
        elif attr not in own.released:
            findings.append(
                Finding(
                    "lifecycle",
                    "ORX502",
                    own.path,
                    o.line,
                    f"{own.name}.{attr}",
                    f"{o.kind} resource {attr!r} acquired in {o.method}() "
                    f"(line {o.line}) has no reachable release path in any "
                    f"method of {own.name}",
                )
            )
    findings.extend(_check_double_close(own))
    findings.extend(_check_overwrites(own))
    return findings


# ---------------------------------------------------------------------------
# function-local ownership


@dataclass
class _Local:
    name: str
    kind: str
    line: int
    stmt_idx: int  # index in the flattened statement order
    node: ast.stmt


class _FunctionScan:
    """Lifecycle of locals within one function body."""

    def __init__(self, fn: ast.AST, path: Path, qualname: str):
        self.fn = fn
        self.path = path
        self.qualname = qualname

    def findings(self) -> list[Finding]:
        acquires: list[_Local] = []
        order: list[ast.stmt] = []

        def flatten(body):
            for st in body:
                order.append(st)
                for f in ast.iter_child_nodes(st):
                    pass
        # flatten all statements in document order
        order = [
            n for n in ast.walk(self.fn) if isinstance(n, ast.stmt) and n is not self.fn
        ]
        order.sort(key=lambda n: (n.lineno, n.col_offset))

        with_managed: set[int] = set()  # id of Call nodes under a with-item
        for sub in ast.walk(self.fn):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    for n in ast.walk(item.context_expr):
                        with_managed.add(id(n))

        for idx, st in enumerate(order):
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                tgt = st.targets[0]
                if isinstance(tgt, ast.Name):
                    kind = _acquire_kind(st.value)
                    if kind and id(st.value) not in with_managed:
                        acquires.append(_Local(tgt.id, kind, st.lineno, idx, st))

        out: list[Finding] = []
        for loc in acquires:
            state = self._classify(loc, order)
            if state == "leak":
                out.append(
                    Finding(
                        "lifecycle",
                        "ORX506",
                        self.path,
                        loc.line,
                        f"{self.qualname}.{loc.name}",
                        f"{loc.kind} {loc.name!r} acquired at line {loc.line} "
                        f"in {self.qualname}() is never released and never "
                        f"escapes — leaked on every path",
                    )
                )
            elif state == "exception-path":
                out.append(
                    Finding(
                        "lifecycle",
                        "ORX501",
                        self.path,
                        loc.line,
                        f"{self.qualname}.{loc.name}",
                        f"{loc.kind} {loc.name!r} (line {loc.line}) is "
                        f"released outside any finally block; an exception "
                        f"between acquire and release strands it — use "
                        f"try/finally or a context manager",
                    )
                )
        return out

    # -- helpers --------------------------------------------------------

    def _classify(self, loc: _Local, order: list[ast.stmt]) -> str | None:
        """'leak' | 'exception-path' | None (safe)."""
        releases: list[ast.stmt] = []  # statements releasing the local
        risky_between = False
        escaped = False
        rebound = False

        finally_stmts: set[int] = set()
        for sub in ast.walk(self.fn):
            if isinstance(sub, ast.Try):
                for st in sub.finalbody:
                    for n in ast.walk(st):
                        if isinstance(n, ast.stmt):
                            finally_stmts.add(id(n))
                for h in sub.handlers:
                    for st in h.body:
                        for n in ast.walk(st):
                            if isinstance(n, ast.stmt):
                                finally_stmts.add(id(n))

        for idx, st in enumerate(order):
            if idx <= loc.stmt_idx:
                continue
            for sub in ast.walk(st):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    if (
                        isinstance(fn, ast.Attribute)
                        and fn.attr in _RELEASE_METHODS
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == loc.name
                    ):
                        releases.append(st)
                        continue
                    for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                        inner = arg.value if isinstance(arg, ast.Starred) else arg
                        for n in ast.walk(inner):
                            if isinstance(n, ast.Name) and n.id == loc.name:
                                escaped = True
                elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                    v = getattr(sub, "value", None)
                    if v is not None:
                        for n in ast.walk(v):
                            if isinstance(n, ast.Name) and n.id == loc.name:
                                escaped = True
                elif isinstance(sub, ast.Assign):
                    # stored somewhere (attr/subscript/other name): transfer
                    if any(
                        isinstance(n, ast.Name) and n.id == loc.name
                        for n in ast.walk(sub.value)
                    ):
                        for t in sub.targets:
                            if not isinstance(t, ast.Name):
                                escaped = True
                            elif isinstance(t, ast.Name) and t.id != loc.name:
                                escaped = True  # aliased: give up
                    # rebound before release: original may be overwritten —
                    # conservatively stop tracking
                    if any(
                        isinstance(t, ast.Name) and t.id == loc.name
                        for t in sub.targets
                    ) and not releases:
                        rebound = True
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        for n in ast.walk(item.context_expr):
                            if isinstance(n, ast.Name) and n.id == loc.name:
                                escaped = True  # with x: manages it
        if escaped or rebound:
            return None
        if not releases:
            return "leak"
        if any(id(st) in finally_stmts for st in releases):
            return None
        # release exists but only on the straight-line path: risky iff a
        # raising statement sits between acquire and the first release
        first_release_idx = min(order.index(st) for st in releases)
        for idx in range(loc.stmt_idx + 1, first_release_idx):
            if _can_raise(order[idx]):
                risky_between = True
                break
        return "exception-path" if risky_between else None


# ---------------------------------------------------------------------------


def _iter_functions(tree: ast.AST):
    """(qualname, node) for module-level and nested functions NOT inside
    a class (class methods go through the ownership analysis)."""

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[str] = []
            self.out: list[tuple[str, ast.AST]] = []

        def visit_ClassDef(self, node):
            pass  # methods handled by class analysis

        def visit_FunctionDef(self, node):
            qual = ".".join(self.stack + [node.name]) if self.stack else node.name
            self.out.append((qual, node))
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    v.visit(tree)
    return v.out


@register
class LifecyclePass(AnalysisPass):
    pass_id = "lifecycle"
    description = (
        "resource-lifecycle analysis: acquisition sites must have "
        "reachable, exception-safe, idempotent release paths "
        "(ORX501-ORX506)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    own = _collect_class(node, mod.path)
                    findings.extend(_check_class(own))
                    # locals inside methods still get the function scan
                    for mname, mnode in own.methods.items():
                        findings.extend(
                            _FunctionScan(
                                mnode, mod.path, f"{node.name}.{mname}"
                            ).findings()
                        )
            for qual, fn in _iter_functions(mod.tree):
                findings.extend(_FunctionScan(fn, mod.path, qual).findings())
        return findings
