"""oryxlint core: pass registry, finding model, baseline, runner, CLI.

One runner fronts every static check in the repo (the lockset race
detector, the lock-order analyzer, the JAX hot-path hygiene pass, and
the four legacy lints that used to live as separate tools/ scripts).
Tier-1 invokes it once (tests/analysis/test_tree_clean.py); operators
invoke it as ``python -m oryx_tpu.analysis`` or ``oryx-tpu lint``.

Findings are keyed *without* line numbers —
``pass_id:relpath:code:symbol`` — so the checked-in baseline
(oryx_tpu/analysis/baseline.txt) survives unrelated edits to a file.
A baselined finding is suppressed; anything new fails the run. Passes
that model deliberate design decisions (e.g. per-level host syncs in
the batch trainers) are baselined with a justification comment rather
than weakening the rule.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"
# the tree the AST passes walk by default: the package + the tools
DEFAULT_TARGETS = (REPO_ROOT / "oryx_tpu", REPO_ROOT / "tools")


@dataclass
class Finding:
    """One problem one pass found at one place."""

    pass_id: str
    code: str  # stable rule id, e.g. ORX101
    path: Path
    line: int
    symbol: str  # the thing flagged (Class.attr, lock pair, call) — part of the baseline key
    message: str

    def key(self, root: Path = REPO_ROOT) -> str:
        try:
            rel = self.path.resolve().relative_to(root)
        except ValueError:
            rel = self.path
        return f"{self.pass_id}:{rel.as_posix()}:{self.code}:{self.symbol}"

    def render(self, root: Path = REPO_ROOT) -> str:
        try:
            rel = self.path.resolve().relative_to(root)
        except ValueError:
            rel = self.path
        return f"{rel.as_posix()}:{self.line}: {self.code} [{self.pass_id}] {self.message}"

    def as_json(self, root: Path = REPO_ROOT) -> dict:
        return {
            "pass": self.pass_id,
            "code": self.code,
            "path": str(self.path),
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "key": self.key(root),
        }


@dataclass
class Module:
    """A parsed source file shared across AST passes (parse once)."""

    path: Path
    text: str
    tree: ast.AST | None
    error: str | None = None


class AnalysisPass:
    """Base class: subclass, set ``pass_id``, implement ``run``."""

    pass_id: str = "?"
    description: str = ""

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        raise NotImplementedError


_REGISTRY: dict[str, AnalysisPass] = {}


def register(p):
    """Class decorator (or instance call): adds the pass to the registry."""
    obj = p() if isinstance(p, type) else p
    _REGISTRY[obj.pass_id] = obj
    return p


def all_passes() -> dict[str, AnalysisPass]:
    _load_builtin_passes()
    return dict(_REGISTRY)


_loaded = False


def _load_builtin_passes() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    # import for side effect: each module register()s its pass
    from oryx_tpu.analysis import (  # noqa: F401
        configkeys,
        deploymanifests,
        durability,
        jaxhot,
        lifecycle,
        lockorder,
        lockset,
        metricscatalog,
        registryhygiene,
    )


def finding_from_problem(pass_id: str, code: str, problem: str) -> Finding:
    """Adapt a legacy ``path:lineno: message`` problem line to a Finding.

    The baseline symbol is the first quoted token in the message (the
    offending key/name), keeping the key line-number free like every
    other pass."""
    import re

    path, line, msg = Path("<unknown>"), 1, problem
    m = re.match(r"(?P<path>[^:]+):(?P<line>\d+):\s*(?P<msg>.*)", problem)
    if m:
        path, line, msg = Path(m.group("path")), int(m.group("line")), m.group("msg")
    else:
        m2 = re.match(r"(?P<path>[^:]+):\s*(?P<msg>.*)", problem)
        if m2 and "/" in m2.group("path"):
            path, msg = Path(m2.group("path")), m2.group("msg")
    q = re.search(r"'([^']+)'", msg)
    symbol = q.group(1) if q else ""
    return Finding(pass_id, code, path, line, symbol, msg)


def iter_py_files(targets: list[Path]) -> list[Path]:
    out: list[Path] = []
    for t in targets:
        if t.is_dir():
            out.extend(sorted(t.rglob("*.py")))
        elif t.suffix == ".py":
            out.append(t)
    # fixture trees carry seeded bugs on purpose; never scan them
    return [p for p in out if "fixtures" not in p.parts]


def parse_modules(targets: list[Path]) -> list[Module]:
    modules: list[Module] = []
    for f in iter_py_files(targets):
        try:
            text = f.read_text(encoding="utf-8")
        except OSError as e:
            modules.append(Module(f, "", None, error=f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(text, filename=str(f))
        except SyntaxError as e:
            modules.append(Module(f, text, None, error=f"syntax error: {e.msg}"))
            continue
        modules.append(Module(f, text, tree))
    return modules


# --------------------------------------------------------------------
# baseline


def load_baseline(path: Path | None) -> set[str]:
    if path is None or not path.exists():
        return set()
    keys: set[str] = set()
    for line in path.read_text(encoding="utf-8").splitlines():
        line = line.split("#", 1)[0].strip()
        if line:
            keys.add(line)
    return keys


def write_baseline(
    path: Path,
    findings: list[Finding],
    root: Path = REPO_ROOT,
    keep_lines: list[str] = (),
) -> None:
    """Write the baseline: ``keep_lines`` are verbatim entry lines carried
    over from the previous file (justification comments intact), then any
    finding keys not already among them."""
    lines = [
        "# oryxlint baseline: accepted findings, one key per line.",
        "# Key format: pass_id:relpath:code:symbol (line-number free, so",
        "# unrelated edits don't churn this file). Regenerate with:",
        "#   python -m oryx_tpu.analysis --update-baseline",
        "# Entries should carry a trailing '# why accepted' comment.",
        "",
    ]
    kept_keys = {ln.split("#", 1)[0].strip() for ln in keep_lines}
    lines.extend(sorted(keep_lines, key=lambda ln: ln.split("#", 1)[0].strip()))
    for key in sorted({f.key(root) for f in findings} - kept_keys):
        lines.append(key)
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")


# --------------------------------------------------------------------
# runner


@dataclass
class RunResult:
    findings: list[Finding]  # unsuppressed
    suppressed: list[Finding]
    stale_baseline: set[str] = field(default_factory=set)

    @property
    def rc(self) -> int:
        return 1 if self.findings else 0


def run_passes(
    targets: list[Path] | None = None,
    select: set[str] | None = None,
    ignore: set[str] | None = None,
    baseline: Path | None = DEFAULT_BASELINE,
) -> RunResult:
    targets = [Path(t) for t in (targets or DEFAULT_TARGETS)]
    passes = all_passes()
    chosen = [
        p
        for pid, p in sorted(passes.items())
        if (select is None or pid in select) and (ignore is None or pid not in ignore)
    ]
    modules = parse_modules(targets)
    findings: list[Finding] = []
    for m in modules:
        if m.error:
            findings.append(
                Finding("parse", "ORX000", m.path, 1, m.path.name, m.error)
            )
    for p in chosen:
        findings.extend(p.run(modules, targets))
    keys = load_baseline(baseline)
    live = [f for f in findings if f.key() not in keys]
    supp = [f for f in findings if f.key() in keys]
    # an entry is stale only when this run could have re-fired it: its
    # pass ran, and its file was in the scan set or is gone from disk
    # entirely (a --select or explicit-path run must not report merely
    # out-of-scope entries as dead); non-.py surfaces belong to the
    # legacy passes, which scan their whole fixed surface when they run
    ran = {p.pass_id for p in chosen} | {"parse"}
    scanned = set()
    for m in modules:
        try:
            rel = m.path.resolve().relative_to(REPO_ROOT)
        except ValueError:
            rel = m.path
        scanned.add(rel.as_posix())
    stale = set()
    for k in keys - {f.key() for f in findings}:
        parts = k.split(":")
        if len(parts) < 4:
            stale.add(k)  # malformed entry: never matchable, surface it
            continue
        pid, rel = parts[0], parts[1]
        judgeable = (
            not rel.endswith(".py")
            or rel in scanned
            or not (REPO_ROOT / rel).exists()
        )
        if pid in ran and judgeable:
            stale.add(k)
    return RunResult(live, supp, stale)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="oryxlint",
        description="Unified static analysis for the oryx_tpu tree.",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: oryx_tpu/ tools/)")
    ap.add_argument(
        "--select", help="comma-separated pass ids to run (default: all)"
    )
    ap.add_argument("--ignore", help="comma-separated pass ids to skip")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help="baseline file (default: the checked-in one)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true", help="report baselined findings too"
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to accept every current finding",
    )
    ap.add_argument(
        "--list-passes", action="store_true", help="list registered passes and exit"
    )
    args = ap.parse_args(argv)

    if args.list_passes:
        for pid, p in sorted(all_passes().items()):
            print(f"{pid:18s} {p.description}")
        return 0

    targets = [Path(p) for p in args.paths] or None
    select = set(args.select.split(",")) if args.select else None
    ignore = set(args.ignore.split(",")) if args.ignore else None
    baseline = None if args.no_baseline else args.baseline

    if args.update_baseline:
        # MERGE, never clobber: a scoped run (--select / explicit paths)
        # must not drop accepted entries it couldn't re-judge. Entries
        # this run proved stale are pruned; everything else keeps its
        # line verbatim (justification comments survive); new findings
        # land as fresh unannotated keys.
        res = run_passes(targets, select, ignore, baseline=args.baseline)
        keep: list[str] = []
        if args.baseline.exists():
            for ln in args.baseline.read_text(encoding="utf-8").splitlines():
                key = ln.split("#", 1)[0].strip()
                if key and key not in res.stale_baseline:
                    keep.append(ln)
        write_baseline(args.baseline, res.findings, keep_lines=keep)
        print(
            f"oryxlint: baseline rewritten: {len(res.findings)} new, "
            f"{len(keep)} kept, {len(res.stale_baseline)} pruned"
        )
        return 0

    res = run_passes(targets, select, ignore, baseline)
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.as_json() for f in res.findings],
                    "suppressed": len(res.suppressed),
                    "stale_baseline": sorted(res.stale_baseline),
                    "rc": res.rc,
                },
                indent=2,
            )
        )
    else:
        for f in res.findings:
            print(f.render())
        for key in sorted(res.stale_baseline):
            print(f"note: stale baseline entry (no longer fires): {key}")
        tail = f"{len(res.findings)} finding(s), {len(res.suppressed)} baselined"
        print(f"oryxlint: {'clean' if res.rc == 0 else tail}" + (f" ({tail})" if res.rc == 0 and res.suppressed else ""))
    return res.rc
