"""Registry-subsystem hygiene pass (moved from tools/lint_registry.py;
the tool remains as a thin shim).

Runs `ruff check` over oryx_tpu/registry/ when ruff is on PATH; in
environments without ruff (the CI image bakes no extra tools) it
degrades to a stdlib AST pass that still catches the high-signal
problems a subsystem boundary cares about: syntax errors, unused
imports, wildcard imports, and mutable default arguments.
"""

from __future__ import annotations

import ast
import shutil
import subprocess
from pathlib import Path

from oryx_tpu.analysis.core import (
    REPO_ROOT,
    AnalysisPass,
    Finding,
    Module,
    finding_from_problem,
    register,
)

DEFAULT_TARGET = REPO_ROOT / "oryx_tpu" / "registry"


def _ruff_lint(paths: list[Path]) -> tuple[int, list[str]]:
    proc = subprocess.run(
        ["ruff", "check", *[str(p) for p in paths]],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    out = (proc.stdout + proc.stderr).strip()
    return proc.returncode, out.splitlines() if out else []


def _iter_py_files(paths: list[Path]):
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def _fallback_lint_file(path: Path) -> list[str]:
    problems: list[str] = []
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    imported: dict[str, int] = {}  # local name -> lineno
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "*":
                    problems.append(f"{path}:{node.lineno}: wildcard import")
                else:
                    imported[a.asname or a.name] = node.lineno
        elif isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in [*node.args.defaults, *node.args.kw_defaults]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{default.lineno}: mutable default argument"
                    )
    # names re-exported via __all__ count as used (registry/__init__.py)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    for name, lineno in sorted(imported.items(), key=lambda kv: kv[1]):
        if name not in used and name != "annotations":
            problems.append(f"{path}:{lineno}: unused import {name!r}")
    return problems


def run_lint(paths: list[Path] | None = None) -> tuple[int, list[str], str]:
    """Returns (exit code, problem lines, engine used)."""
    paths = paths or [DEFAULT_TARGET]
    if shutil.which("ruff"):
        rc, lines = _ruff_lint(paths)
        return rc, lines, "ruff"
    problems: list[str] = []
    for f in _iter_py_files(paths):
        problems.extend(_fallback_lint_file(f))
    return (1 if problems else 0), problems, "ast-fallback"


@register
class RegistryHygienePass(AnalysisPass):
    pass_id = "registry"
    description = (
        "registry-subsystem hygiene: ruff when available, stdlib AST "
        "fallback (syntax/unused/wildcard/mutable-default)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        _, problems, _ = run_lint()
        return [
            finding_from_problem(self.pass_id, "ORX402", p) for p in problems
        ]
