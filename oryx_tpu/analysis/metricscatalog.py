"""Metric/span catalog pass: docs/observability.md vs the code, both
directions (moved from tools/lint_metrics.py; the tool remains as a
thin shim).

A metric that exists in code but not in the catalog is invisible to
operators (nobody alerts on a name they don't know exists); a cataloged
name that no longer exists in code is worse — a dashboard or alert
silently watching nothing. The pass checks literal registration and
span sites against the doc tables, placeholder families by fragment,
and the tracing knob table against reference.conf.

The analyzer's own package (oryx_tpu/analysis/) is excluded from the
source scan: these files *describe* registration patterns in prose and
regexes without emitting telemetry.
"""

from __future__ import annotations

import re
from pathlib import Path

from oryx_tpu.analysis.core import (
    REPO_ROOT,
    AnalysisPass,
    Finding,
    Module,
    finding_from_problem,
    register,
)

DOC = REPO_ROOT / "docs" / "observability.md"
# tools/ emit operator-facing metrics too (fleet recovery.seconds) — the
# catalog covers both trees
SOURCE_ROOTS = (REPO_ROOT / "oryx_tpu", REPO_ROOT / "tools")
_SELF_DIR = Path(__file__).resolve().parent

# literal registration sites; f-strings deliberately don't match (their
# families are cataloged with <...> placeholders instead)
_METRIC_CALL = re.compile(r'\.(?:counter|gauge|histogram)\(\s*"([^"]+)"\s*\)')
_SPAN_CALL = re.compile(r'(?:tracing\.span|record_span)\(\s*\n?\s*"([^"]+)"')
_DOC_ROW = re.compile(r"^\|\s*`([^`]+)`")


def _sources() -> list[tuple[Path, str]]:
    return [
        (f, f.read_text(encoding="utf-8"))
        for root in SOURCE_ROOTS
        for f in sorted(root.rglob("*.py"))
        if _SELF_DIR not in f.resolve().parents
    ]


def code_names() -> tuple[dict[str, Path], dict[str, Path]]:
    """(metric name -> file, span name -> file) from literal call sites."""
    metrics: dict[str, Path] = {}
    spans: dict[str, Path] = {}
    for f, text in _sources():
        for name in _METRIC_CALL.findall(text):
            metrics.setdefault(name, f)
        for name in _SPAN_CALL.findall(text):
            spans.setdefault(name, f)
    return metrics, spans


def doc_names() -> tuple[set[str], set[str], set[str]]:
    """(metric, span, oryx.tracing knob) names cataloged in the doc.

    Section-driven: the knob table lives under '## Tracing', the span
    table under '### Span catalog', metric tables under
    '## Metric catalog'."""
    metrics: set[str] = set()
    spans: set[str] = set()
    knobs: set[str] = set()
    mode = None
    for line in DOC.read_text(encoding="utf-8").splitlines():
        if line.startswith("#"):
            if "Span catalog" in line:
                mode = "spans"
            elif "Metric catalog" in line:
                mode = "metrics"
            elif line.startswith("## Tracing"):
                mode = "knobs"
            elif line.startswith("## "):
                mode = None
            continue
        m = _DOC_ROW.match(line)
        if not m or mode is None:
            continue
        name = m.group(1)
        if name in ("metric", "span", "knob"):  # header rows
            continue
        if mode == "spans":
            spans.add(name)
        elif mode == "metrics":
            metrics.add(name)
        elif mode == "knobs":
            knobs.add(name)
    return metrics, spans, knobs


def _fragments(pattern: str) -> list[str]:
    """Literal fragments of a catalog entry around <...> placeholders."""
    return [frag for frag in re.split(r"<[^>]*>", pattern) if frag]


def _exists_in_code(pattern: str, blob: str) -> bool:
    if "<" in pattern:
        return all(frag in blob for frag in _fragments(pattern))
    return f'"{pattern}"' in blob or f"'{pattern}'" in blob


def tracing_knob_keys() -> set[str]:
    """reference.conf's oryx.tracing block (the knob source of truth)."""
    from oryx_tpu.common import config as C

    return set(C.get_default().get_config("oryx.tracing").as_dict().keys())


def run_lint(code_names_fn=None) -> tuple[int, list[str], str]:
    """Legacy entry point. ``code_names_fn`` lets the tools/ shim keep
    its module-level ``code_names`` monkeypatchable (tests patch the
    shim's attribute and expect run_lint to see it)."""
    collect = code_names_fn or code_names
    problems: list[str] = []
    if not DOC.exists():
        return 1, [f"{DOC}: missing"], "lint_metrics"
    code_metrics, code_spans = collect()
    doc_metrics, doc_spans, doc_knobs = doc_names()
    blob = "\n".join(text for _, text in _sources())

    for name, f in sorted(code_metrics.items()):
        if name not in doc_metrics:
            problems.append(
                f"{f}: metric {name!r} is not cataloged in {DOC.name}"
            )
    for name, f in sorted(code_spans.items()):
        if name not in doc_spans:
            problems.append(
                f"{f}: span {name!r} is not cataloged in {DOC.name}"
            )
    for name in sorted(doc_metrics):
        if not _exists_in_code(name, blob):
            problems.append(
                f"{DOC.name}: cataloged metric {name!r} does not appear in "
                f"the code"
            )
    for name in sorted(doc_spans):
        if not _exists_in_code(name, blob):
            problems.append(
                f"{DOC.name}: cataloged span {name!r} does not appear in "
                f"the code"
            )

    knobs = {f"oryx.tracing.{k}" for k in tracing_knob_keys()}
    for knob in sorted(knobs - doc_knobs):
        problems.append(f"{DOC.name}: tracing knob {knob!r} is not cataloged")
    for knob in sorted(doc_knobs - knobs):
        problems.append(
            f"{DOC.name}: cataloged knob {knob!r} is not in reference.conf"
        )
    return (1 if problems else 0), problems, "lint_metrics"


@register
class MetricsCatalogPass(AnalysisPass):
    pass_id = "metrics"
    description = (
        "metric/span names vs docs/observability.md catalog, both "
        "directions, plus the tracing knob table"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        _, problems, _ = run_lint()
        return [
            finding_from_problem(self.pass_id, "ORX404", p) for p in problems
        ]
