"""Lockset race detector (Eraser-style, adapted to Python AST).

Per class, the pass answers three questions and cross-checks them:

1. Which instance attributes are *locks*? (``self.X = threading.Lock()
   / RLock() / Condition()`` in any method; ``Condition(self._lock)``
   aliases X to the canonical underlying lock so ``with self._cv:`` and
   ``with self._lock:`` count as the same guard.)
2. Which methods run on *other threads*? (``threading.Thread(
   target=self.m)``, ``SupervisedThread(..., self.m, ...)``,
   ``layer.supervise("name", self.m)``, ``executor.submit(self.m)``,
   ``do_GET``-style handler methods, ``run`` on Thread subclasses —
   plus everything reachable from those through self-calls.)
3. Which attribute accesses happen under which locks? ``with
   self._lock:`` regions extend the current lockset; a method whose
   intra-class call sites *all* hold a lock inherits it (the repo's
   documented "caller holds ``_lock``" idiom); a ``with`` over an
   expression we can't resolve statically (e.g. a lock picked by a
   conditional) taints the region as *unknown* rather than unguarded,
   so dynamic-lock code doesn't false-positive.

Rules (all error severity; fire against the baseline):

- ORX101 mixed-guard write: an attribute accessed under its guard lock
  somewhere is *written* with no lock somewhere else (both outside
  ``__init__``). This is the Eraser condition: the candidate lockset
  for the attribute became empty.
- ORX102 unguarded cross-thread write: in a class with no relevant
  guard at all, an attribute is written from a thread-entry-reachable
  method and also accessed from a non-entry method.
- ORX103 cross-object write to a guarded private attribute: code
  outside class C writes ``obj._attr`` where ``C._attr`` is
  lock-guarded — bypassing C's own discipline (the pipeline
  ``layer._batch_count += 1`` bug shape).
- ORX105 module-global mixed write: a module global is written both
  inside and outside ``with <module lock>:`` (in functions declaring
  ``global``).

Attributes only ever written in ``__init__`` are immutable-after-init
and exempt; reads are never flagged on their own (GIL-atomic reads of
a published reference are the repo's accepted idiom — the analyzer
hunts lost updates and torn multi-field transitions, not volatile
reads).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from oryx_tpu.analysis.core import AnalysisPass, Finding, Module, register

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
# construction and finalization are single-threaded boundaries: the
# object is not yet / no longer shared when these run
_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__init_subclass__", "__del__"}
_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD", "handle"}
_THREAD_CTORS = {"Thread", "SupervisedThread", "Timer"}
_SPAWN_METHODS = {"supervise", "submit", "start_new_thread", "spawn"}
_UNKNOWN = "<?>"


def _lock_factory_name(call: ast.AST) -> str | None:
    """'Lock' for threading.Lock(...) / Lock(...) / locks.OrderedLock()."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name is None:
        return None
    if name in _LOCK_FACTORIES or name in ("OrderedLock", "OrderedRLock"):
        return "Condition" if name == "Condition" else "Lock"
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class Access:
    attr: str
    write: bool
    method: str
    line: int
    locks: frozenset


@dataclass
class ClassInfo:
    name: str
    path: Path
    lock_attrs: dict = field(default_factory=dict)  # attr -> canonical attr
    methods: dict = field(default_factory=dict)  # name -> ast.FunctionDef
    accesses: list = field(default_factory=list)
    entries: set = field(default_factory=set)  # thread-entry method names
    call_sites: dict = field(default_factory=dict)  # callee -> [frozenset locks]
    bases: list = field(default_factory=list)


def _collect_lock_attrs(cls: ast.ClassDef) -> dict:
    """attr -> canonical underlying lock attr (Condition(self._x) -> _x)."""
    locks: dict[str, str] = {}
    aliases: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = _self_attr(node.targets[0])
            if tgt is None:
                continue
            kind = _lock_factory_name(node.value)
            if kind is None:
                continue
            locks[tgt] = tgt
            if kind == "Condition" and isinstance(node.value, ast.Call) and node.value.args:
                src = _self_attr(node.value.args[0])
                if src is not None:
                    aliases[tgt] = src
    for a, src in aliases.items():
        locks[a] = locks.get(src, src)
        locks.setdefault(src, src)
    return locks


class _MethodWalker(ast.NodeVisitor):
    """Walk one method body tracking the current lockset."""

    def __init__(self, info: ClassInfo, method: str, module_locks: set):
        self.info = info
        self.method = method
        self.module_locks = module_locks
        self.locks: tuple = ()

    # -- lock regions -------------------------------------------------
    def _canon(self, expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and attr in self.info.lock_attrs:
            return "self." + self.info.lock_attrs[attr]
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return "mod." + expr.id
        return None

    def visit_With(self, node: ast.With) -> None:
        added = []
        for item in node.items:
            canon = self._canon(item.context_expr)
            if canon is not None:
                added.append(canon)
            elif _looks_like_lock(item.context_expr):
                added.append(_UNKNOWN)
        old = self.locks
        self.locks = old + tuple(a for a in added if a not in old)
        for stmt in node.body:
            self.visit(stmt)
        self.locks = old

    visit_AsyncWith = visit_With

    # -- accesses -----------------------------------------------------
    def _record(self, attr: str, write: bool, line: int) -> None:
        if attr in self.info.lock_attrs:
            return
        self.info.accesses.append(
            Access(attr, write, self.method, line, frozenset(self.locks))
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, isinstance(node.ctx, (ast.Store, ast.Del)), node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.X[k] = v mutates the container held by X: count as a write
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._record(attr, True, node.lineno)
                self.visit(node.slice)
                return
        self.generic_visit(node)

    # -- calls / thread spawns ---------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # self.m(...)
        callee = _self_attr(fn)
        if callee is not None and callee in self.info.methods:
            self.info.call_sites.setdefault(callee, []).append(frozenset(self.locks))
        # thread-entry registration: any self.m passed to a spawner
        ctor = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if ctor in _THREAD_CTORS or ctor in _SPAWN_METHODS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                m = _self_attr(arg)
                if m is not None and m in self.info.methods:
                    self.info.entries.add(m)
        self.generic_visit(node)

    # nested defs run in their own context; still record entry handoffs
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        old = self.locks
        # a closure may run on another thread; analyze it lock-free is
        # too pessimistic, with current locks too optimistic — keep the
        # enclosing lockset (the dominant repo idiom is inline helpers).
        for stmt in node.body:
            self.visit(stmt)
        self.locks = old

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = lambda self, node: self.generic_visit(node)  # noqa: E731


def _looks_like_lock(expr: ast.AST) -> bool:
    """Heuristic: `with <something lockish>:` — a name/attr containing
    'lock', 'cv', 'cond', or 'mu'. Anything else (files, contexts,
    tracing spans) is not a guard and must not taint the region."""
    label = None
    if isinstance(expr, ast.Attribute):
        label = expr.attr
    elif isinstance(expr, ast.Name):
        label = expr.id
    if label is None:
        return False
    low = label.lower()
    return any(tok in low for tok in ("lock", "_cv", "cond", "_mu", "mutex"))


def _module_locks(tree: ast.AST) -> set:
    out = set()
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and _lock_factory_name(node.value):
                out.add(tgt.id)
    return out


def _analyze_class(cls: ast.ClassDef, path: Path, module_locks: set) -> ClassInfo:
    info = ClassInfo(cls.name, path)
    info.bases = [
        b.attr if isinstance(b, ast.Attribute) else getattr(b, "id", "")
        for b in cls.bases
    ]
    info.lock_attrs = _collect_lock_attrs(cls)
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[node.name] = node
    subclasses_thread = any("Thread" in b for b in info.bases)
    handlerish = any("Handler" in b or "Server" in b for b in info.bases)
    for name, node in info.methods.items():
        if name in _HANDLER_METHODS and handlerish:
            info.entries.add(name)
        if name == "run" and subclasses_thread:
            info.entries.add(name)
        walker = _MethodWalker(info, name, module_locks)
        for stmt in node.body:
            walker.visit(stmt)
    return info


def _entry_reachable(info: ClassInfo) -> set:
    """Methods reachable from thread entries via self-calls."""
    reach = set(info.entries)
    # call graph: caller info is not tracked per-edge; approximate with
    # callee sets per method body
    callees: dict[str, set] = {m: set() for m in info.methods}
    for name, node in info.methods.items():
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee = _self_attr(sub.func)
                if callee in info.methods:
                    callees[name].add(callee)
    changed = True
    while changed:
        changed = False
        for m in list(reach):
            for c in callees.get(m, ()):
                if c not in reach:
                    reach.add(c)
                    changed = True
    return reach


def _propagate_caller_locks(info: ClassInfo) -> dict:
    """Locks every call site of a method provably holds ('caller holds
    the lock' idiom). Entry methods are invoked lock-free by the runtime
    and get none."""
    inherited: dict[str, frozenset] = {}
    for _ in range(4):  # small fixpoint: chains are short
        changed = False
        for m in info.methods:
            if m in info.entries or m in _INIT_METHODS:
                continue
            sites = info.call_sites.get(m)
            if not sites:
                continue
            eff = None
            for s in sites:
                # a caller's own inherited locks extend its sites too —
                # handled by rerunning the loop with updated accesses
                eff = s if eff is None else (eff & s)
            eff = frozenset(eff or ())
            if inherited.get(m, None) != eff:
                inherited[m] = eff
                changed = True
        if not changed:
            break
    return inherited


def analyze_module(mod: Module) -> tuple[list[ClassInfo], list[Finding]]:
    """All ClassInfos plus the module-global (ORX105) findings."""
    if mod.tree is None:
        return [], []
    module_locks = _module_locks(mod.tree)
    infos = [
        _analyze_class(node, mod.path, module_locks)
        for node in ast.walk(mod.tree)
        if isinstance(node, ast.ClassDef)
    ]
    findings = _check_module_globals(mod, module_locks)
    return infos, findings


def _check_module_globals(mod: Module, module_locks: set) -> list[Finding]:
    if not module_locks:
        return []
    writes: dict[str, list] = {}  # global -> [(guarded, line)]
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        declared = {
            n for sub in ast.walk(node) if isinstance(sub, ast.Global) for n in sub.names
        }
        if not declared:
            continue

        class W(ast.NodeVisitor):
            def __init__(self):
                self.locks = ()

            def visit_With(self, w):
                added = [
                    "mod." + i.context_expr.id
                    for i in w.items
                    if isinstance(i.context_expr, ast.Name)
                    and i.context_expr.id in module_locks
                ]
                if not added and any(
                    _looks_like_lock(i.context_expr) for i in w.items
                ):
                    added = [_UNKNOWN]
                old = self.locks
                self.locks = old + tuple(added)
                for s in w.body:
                    self.visit(s)
                self.locks = old

            visit_AsyncWith = visit_With

            def visit_Name(self, n):
                if n.id in declared and isinstance(n.ctx, ast.Store):
                    writes.setdefault(n.id, []).append((bool(self.locks), n.lineno))

        w = W()
        for stmt in node.body:
            w.visit(stmt)
    out = []
    for name, ws in sorted(writes.items()):
        if name in module_locks:
            continue
        guarded = [line for ok, line in ws if ok]
        unguarded = [line for ok, line in ws if not ok]
        if guarded and unguarded:
            out.append(
                Finding(
                    "lockset",
                    "ORX105",
                    mod.path,
                    unguarded[0],
                    f"<module>.{name}",
                    f"module global {name!r} is written under the module "
                    f"lock (line {guarded[0]}) and without it "
                    f"(line {unguarded[0]})",
                )
            )
    return out


def _check_class(info: ClassInfo) -> list[Finding]:
    inherited = _propagate_caller_locks(info)
    reach = _entry_reachable(info)
    findings: list[Finding] = []

    # effective lockset per access
    by_attr: dict[str, list[Access]] = {}
    eff_locks: dict[int, frozenset] = {}
    for i, a in enumerate(info.accesses):
        eff = a.locks | inherited.get(a.method, frozenset())
        eff_locks[i] = eff
        by_attr.setdefault(a.attr, []).append(a)

    for attr, accesses in sorted(by_attr.items()):
        post_init_writes = [
            a for a in accesses if a.write and a.method not in _INIT_METHODS
        ]
        if not post_init_writes:
            continue  # immutable after construction
        idx = {id(a): eff_locks[i] for i, a in enumerate(info.accesses)}
        guarded = [
            a
            for a in accesses
            if a.method not in _INIT_METHODS
            and any(lk != _UNKNOWN for lk in idx[id(a)])
        ]
        unknown = [a for a in accesses if _UNKNOWN in idx[id(a)]]
        naked_writes = [
            a for a in post_init_writes if not idx[id(a)]
        ]
        guarded_writes = [a for a in guarded if a.write]
        naked_entry_reads = [
            a
            for a in accesses
            if not a.write
            and a.method in reach
            and a.method not in _INIT_METHODS
            and not idx[id(a)]
        ]
        if guarded and naked_writes:
            locks_used = sorted(
                {lk for a in guarded for lk in idx[id(a)] if lk != _UNKNOWN}
            )
            w = naked_writes[0]
            findings.append(
                Finding(
                    "lockset",
                    "ORX101",
                    info.path,
                    w.line,
                    f"{info.name}.{attr}",
                    f"attribute {attr!r} is guarded by "
                    f"{'/'.join(locks_used)} elsewhere but written "
                    f"without a lock in {w.method}() "
                    f"(line {w.line}); candidate lockset is empty",
                )
            )
            continue
        if guarded_writes and naked_entry_reads and info.entries:
            # writes keep the discipline but a hot-path thread reads the
            # attribute lock-free: lost-update-adjacent (stale/torn view)
            r = naked_entry_reads[0]
            locks_used = sorted(
                {lk for a in guarded_writes for lk in idx[id(a)] if lk != _UNKNOWN}
            )
            findings.append(
                Finding(
                    "lockset",
                    "ORX104",
                    info.path,
                    r.line,
                    f"{info.name}.{attr}",
                    f"attribute {attr!r} is written under "
                    f"{'/'.join(locks_used)} but read lock-free on the "
                    f"{r.method}() thread (line {r.line})",
                )
            )
            continue
        if guarded or unknown or not info.entries:
            continue
        entry_writes = [a for a in naked_writes if a.method in reach]
        outside = [
            a for a in accesses if a.method not in reach and a.method not in _INIT_METHODS
        ]
        if entry_writes and outside:
            w = entry_writes[0]
            findings.append(
                Finding(
                    "lockset",
                    "ORX102",
                    info.path,
                    w.line,
                    f"{info.name}.{attr}",
                    f"attribute {attr!r} is written from thread entry "
                    f"{w.method}() (line {w.line}) with no lock and "
                    f"accessed from {outside[0].method}() "
                    f"(line {outside[0].line}) on other threads",
                )
            )
    return findings


def _check_cross_object(
    modules: list[Module], guarded_attrs: dict
) -> list[Finding]:
    findings = []
    for mod in modules:
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            target = None
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                continue
            for target in targets:
                if not isinstance(target, ast.Attribute):
                    continue
                base = target.value
                if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                    continue
                attr = target.attr
                owner = guarded_attrs.get(attr)
                if owner is None or not attr.startswith("_"):
                    continue
                findings.append(
                    Finding(
                        "lockset",
                        "ORX103",
                        mod.path,
                        node.lineno,
                        f"{owner}.{attr}",
                        f"write to {attr!r} from outside its class "
                        f"bypasses the lock that guards {owner}.{attr}",
                    )
                )
    return findings


@register
class LocksetPass(AnalysisPass):
    pass_id = "lockset"
    description = (
        "Eraser-style race detector: attributes accessed both inside and "
        "outside their guarding lock (ORX101/102/103/105)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        findings: list[Finding] = []
        guarded_attrs: dict[str, str] = {}
        infos_per_mod = []
        for mod in modules:
            infos, global_findings = analyze_module(mod)
            findings.extend(global_findings)
            infos_per_mod.append(infos)
            for info in infos:
                inherited = _propagate_caller_locks(info)
                for i, a in enumerate(info.accesses):
                    eff = a.locks | inherited.get(a.method, frozenset())
                    if any(lk != _UNKNOWN for lk in eff):
                        guarded_attrs.setdefault(a.attr, info.name)
        for infos in infos_per_mod:
            for info in infos:
                findings.extend(_check_class(info))
        findings.extend(_check_cross_object(modules, guarded_attrs))
        return findings
