"""Config-key pass: the silent-failure knob blocks (moved here from
tools/lint_config.py, which remains as a thin shim).

A mistyped key under these prefixes fails SILENTLY: the HOCON overlay
accepts any path, the subsystem only reads the keys it knows, and the
operator ships with the default behavior still on. The pass walks the
repo's Python/conf/markdown sources for dotted key references and
rejects any key the matching reference.conf block (the single source
of truth for each knob set) does not declare.

Linted prefixes:
  oryx.serving.scan.ann   — ANN tier of the serving scan (incl. maintain.*)
  oryx.serving.store.tier — tiered HBM/RAM/disk item store
  oryx.serving.ab         — online experiment traffic split (docs/experiments.md)
  oryx.serving.overload   — admission control / shed ladder
  oryx.fleet.autoscale    — predictive fleet autoscaler
  oryx.bus.shm            — shared-memory ring transport
  oryx.ml.gate.online     — evidence-gated online promotion
  oryx.speed.parse        — native columnar input parse stage
  oryx.speed.pipeline     — three-stage speed-layer pipeline
  oryx.tenancy            — multi-tenant lambda (oryx_tpu/tenancy/)
  oryx.tracing            — distributed tracer (common/tracing.py)
"""

from __future__ import annotations

import re
from pathlib import Path

from oryx_tpu.analysis.core import (
    REPO_ROOT,
    AnalysisPass,
    Finding,
    Module,
    finding_from_problem,
    register,
)

ANN_PREFIX = "oryx.serving.scan.ann"
LINTED_PREFIXES = (
    ANN_PREFIX,
    "oryx.bus.shm",
    "oryx.fleet.autoscale",
    "oryx.ml.gate.online",
    "oryx.serving.ab",
    "oryx.serving.native",
    "oryx.serving.overload",
    "oryx.serving.store.tier",
    "oryx.speed.parse",
    "oryx.speed.pipeline",
    "oryx.tenancy",
    "oryx.tracing",
)
DEFAULT_TARGETS = [
    REPO_ROOT / "oryx_tpu",
    REPO_ROOT / "tools",
    REPO_ROOT / "tests",
    REPO_ROOT / "docs",
]
# self-referential tooling: the analyzer's own sources (and the legacy
# shims) describe key patterns, they don't consume knobs
_SELF_DIRS = (Path(__file__).resolve().parent,)
_SELF_FILES = {REPO_ROOT / "tools" / "lint_config.py"}

# dotted reference in code/docs/conf: <prefix>.<key>
_DOTTED = {
    prefix: re.compile(re.escape(prefix) + r"\.([A-Za-z0-9][A-Za-z0-9-]*)")
    for prefix in LINTED_PREFIXES
}


def known_keys(prefix: str) -> set[str]:
    """The knob set reference.conf declares under `prefix`."""
    from oryx_tpu.common import config as C

    block = C.get_default().get_config(prefix)
    return set(block.as_dict().keys())


def known_ann_keys() -> set[str]:
    """The ANN knob set (kept for the original single-prefix API)."""
    return known_keys(ANN_PREFIX)


def _iter_source_files(paths: list[Path]):
    for p in paths:
        if p.is_dir():
            for ext in ("*.py", "*.conf", "*.md"):
                yield from sorted(p.rglob(ext))
        elif p.suffix in (".py", ".conf", ".md"):
            yield p


def _skip(path: Path) -> bool:
    rp = path.resolve()
    if rp in {f.resolve() for f in _SELF_FILES}:
        return True
    return any(str(rp).startswith(str(d) + "/") for d in _SELF_DIRS)


def _lint_file(path: Path, known: dict[str, set[str]]) -> list[str]:
    problems: list[str] = []
    try:
        text = path.read_text(encoding="utf-8")
    except OSError as e:  # unreadable file: surface, don't crash the gate
        return [f"{path}: unreadable: {e}"]
    for lineno, line in enumerate(text.splitlines(), 1):
        for prefix, pattern in _DOTTED.items():
            for m in pattern.finditer(line):
                key = m.group(1)
                if key not in known[prefix]:
                    problems.append(
                        f"{path}:{lineno}: unknown config key "
                        f"{prefix}.{key!r} (declared: "
                        f"{', '.join(sorted(known[prefix]))})"
                    )
    return problems


def run_lint(paths: list[Path] | None = None) -> tuple[int, list[str], str]:
    """Returns (exit code, problem lines, engine used) — the legacy
    shape tests/registry/test_lint.py exercises."""
    paths = paths or DEFAULT_TARGETS
    known = {prefix: known_keys(prefix) for prefix in LINTED_PREFIXES}
    problems: list[str] = []
    for f in _iter_source_files(paths):
        if _skip(f):
            continue
        problems.extend(_lint_file(f, known))
    return (1 if problems else 0), problems, "config-keys"


@register
class ConfigKeysPass(AnalysisPass):
    pass_id = "config-keys"
    description = (
        "dotted oryx.* knob references must exist in reference.conf "
        "(silent-failure prevention)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        # the knob check has its own default target set (docs + tests
        # included); explicit CLI paths narrow it
        from oryx_tpu.analysis import core as _core

        on_defaults = {Path(t).resolve() for t in targets} == {
            Path(t).resolve() for t in _core.DEFAULT_TARGETS
        }
        _, problems, _ = run_lint(None if on_defaults else list(targets))
        return [
            finding_from_problem(self.pass_id, "ORX401", p) for p in problems
        ]
