"""oryxlint: the repo's unified static-analysis subsystem.

Run it as ``python -m oryx_tpu.analysis`` (or ``tools/oryxlint.py`` /
``oryx-tpu lint``). Passes: the lockset race detector, the lock-order
analyzer (static half of the common/locks.py runtime watchdog), the
JAX hot-path hygiene pass, and the four migrated repo lints
(config-keys, registry, deploy, metrics). See docs/static-analysis.md.
"""

from oryx_tpu.analysis.core import (  # noqa: F401
    DEFAULT_BASELINE,
    AnalysisPass,
    Finding,
    RunResult,
    all_passes,
    load_baseline,
    main,
    register,
    run_passes,
    write_baseline,
)
