"""JAX hot-path hygiene pass: recompile hazards and host syncs in loops.

The PR-4 trainers earned their zero-recompile regression tests by
caching jitted callables (``functools.lru_cache`` around the builder,
or a module-level memo dict keyed by mesh/shape signature). This pass
keeps the tree honest about that idiom:

- ORX301 jit-in-loop: a jitted callable is *constructed* (``jax.jit(
  ...)`` / ``functools.partial(jax.jit, ...)``) inside a ``for`` /
  ``while`` body — every iteration retraces and recompiles.
- ORX302 host-sync-in-loop: inside a loop, ``.block_until_ready()``,
  ``jax.device_get(...)``, or ``np.asarray(x)`` / ``float(x)`` where
  ``x`` was produced by a jitted callable in the same function — the
  loop serializes on device->host transfers (the scan/fold hot-path
  antipattern). Deliberate host orchestration points (the level-by-
  level forest grower) are baselined with a justification, not
  exempted by rule.
- ORX303 uncached-jit: a jitted callable is constructed inside a
  function with *no* caching idiom in sight: the enclosing function is
  not ``lru_cache``-decorated, no module function memoizes its result
  into a module-level dict, and the result is not stored on ``self``
  (instance-lifetime cache). Such call sites recompile on every
  invocation once shapes vary.

Only loops spelled ``for``/``while`` count; comprehensions over small
static tuples are the repo's unpacking idiom, not hot loops.
"""

from __future__ import annotations

import ast
from pathlib import Path

from oryx_tpu.analysis.core import AnalysisPass, Finding, Module, register

_SYNC_WRAPPERS = {"asarray", "array", "float"}


def _tail_name(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_jit_construction(call: ast.Call) -> bool:
    """Does this call expression produce a fresh jitted callable?"""
    fn = call.func
    name = _tail_name(fn)
    if name == "jit":
        return True
    if name == "partial" and call.args and _tail_name(call.args[0]) == "jit":
        return True
    if isinstance(fn, ast.Call) and _is_jit_construction(fn):
        return True  # functools.partial(jax.jit, ...)(impl)
    return False


def _module_jitted_names(tree: ast.Module) -> set:
    """Module-level names that are jitted callables."""
    out = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _tail_name(dec) == "jit" or (
                    isinstance(dec, ast.Call) and _is_jit_construction(dec)
                ):
                    out.add(node.name)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call) and _is_jit_construction(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _module_memo_dicts(tree: ast.Module) -> set:
    out = set()
    for node in tree.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            if isinstance(node.value, (ast.Dict,)) or (
                isinstance(node.value, ast.Call) and _tail_name(node.value.func) == "dict"
            ):
                out.add(node.target.id)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and (
                isinstance(node.value, ast.Dict)
                or (isinstance(node.value, ast.Call) and _tail_name(node.value.func) == "dict")
            ):
                out.add(tgt.id)
    return out


def _cached_functions(tree: ast.Module) -> set:
    """Functions whose jit constructions are amortized: lru_cache-
    decorated, or memoized into a module dict by some caller."""
    cached = set()
    memo_dicts = _module_memo_dicts(tree)
    fns = [
        n for n in tree.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in fns:
        for dec in fn.decorator_list:
            base = dec.func if isinstance(dec, ast.Call) else dec
            if _tail_name(base) in ("lru_cache", "cache"):
                cached.add(fn.name)
    for fn in fns:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and _tail_name(node.value.func) is not None
            ):
                continue
            into_memo = any(
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in memo_dicts
                for t in node.targets
            )
            if into_memo:
                cached.add(_tail_name(node.value.func))
    return cached


def _loop_nodes(fn: ast.AST) -> set:
    """ids of every node lexically inside a for/while body of fn."""
    inside = set()

    def mark(node):
        for child in ast.walk(node):
            inside.add(id(child))

    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            for stmt in node.body + node.orelse:
                mark(stmt)
    return inside


def _tainted_names(fn: ast.AST, jitted: set) -> set:
    """Local names bound from a call to a jitted callable (device
    values), including tuple-unpack targets; locally-constructed jitted
    callables taint what they return too."""
    local_jits = set(jitted)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_construction(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_jits.add(t.id)
    tainted = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        callee = _tail_name(node.value.func)
        if callee not in local_jits:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                tainted.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    if isinstance(elt, ast.Name):
                        tainted.add(elt.id)
    return tainted


def _assigned_to_self(fn: ast.AST) -> set:
    """ids of Call nodes whose result lands on a self attribute."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if any(
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                for t in node.targets
            ):
                out.add(id(node.value))
    return out


@register
class JaxHotPathPass(AnalysisPass):
    pass_id = "jaxhot"
    description = (
        "JAX hot-path hygiene: jit construction in loops / uncached jit "
        "(recompile hazards), host syncs inside scan/fold loops "
        "(ORX301/302/303)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            if mod.tree is None or "jax" not in mod.text:
                continue
            findings.extend(self._check_module(mod))
        return findings

    def _check_module(self, mod: Module) -> list[Finding]:
        tree = mod.tree
        jitted = _module_jitted_names(tree)
        cached_fns = _cached_functions(tree)
        findings: list[Finding] = []

        def check_fn(fn, qualname, in_cached):
            loops = _loop_nodes(fn)
            tainted = _tainted_names(fn, jitted)
            self_cached = _assigned_to_self(fn)
            # the function's own decorators (@functools.partial(jax.jit,
            # ...)) define a module-level jitted callable — jit's own
            # trace cache covers it, that's the idiom not the hazard
            own_decorators = {
                id(sub)
                for dec in fn.decorator_list
                for sub in ast.walk(dec)
            }
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in own_decorators:
                    continue
                in_loop = id(node) in loops
                if _is_jit_construction(node):
                    if in_loop:
                        findings.append(
                            Finding(
                                "jaxhot",
                                "ORX301",
                                mod.path,
                                node.lineno,
                                qualname,
                                f"jitted callable constructed inside a loop "
                                f"in {qualname}(): recompiles every iteration",
                            )
                        )
                    elif not in_cached and id(node) not in self_cached:
                        findings.append(
                            Finding(
                                "jaxhot",
                                "ORX303",
                                mod.path,
                                node.lineno,
                                qualname,
                                f"jax.jit result in {qualname}() is not "
                                f"cached (no lru_cache, module memo, or "
                                f"self attribute): recompiles per call",
                            )
                        )
                    continue
                if not in_loop:
                    continue
                callee = _tail_name(node.func)
                if callee == "block_until_ready" or callee == "device_get":
                    findings.append(
                        Finding(
                            "jaxhot",
                            "ORX302",
                            mod.path,
                            node.lineno,
                            f"{qualname}:{callee}",
                            f"host sync {callee}() inside a loop in "
                            f"{qualname}(): serializes the device pipeline",
                        )
                    )
                elif (
                    callee in _SYNC_WRAPPERS
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in tainted
                ):
                    findings.append(
                        Finding(
                            "jaxhot",
                            "ORX302",
                            mod.path,
                            node.lineno,
                            f"{qualname}:{node.args[0].id}",
                            f"{callee}({node.args[0].id}) inside a loop in "
                            f"{qualname}() forces a device->host sync per "
                            f"iteration ({node.args[0].id} comes from a "
                            f"jitted call)",
                        )
                    )

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_fn(node, node.name, node.name in cached_fns)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        check_fn(
                            sub, f"{node.name}.{sub.name}", sub.name in cached_fns
                        )
        return findings
