"""Static lock-order analyzer: build the lock-acquisition nesting graph
and flag cycles (the static half of the TSan-lite watchdog in
oryx_tpu/common/locks.py).

Edges come from two shapes, resolved per module:

- directly nested ``with`` statements over known locks
  (``with self._a: ... with self._b:`` adds a -> b);
- a call made while holding a lock, to a method/function *of the same
  class or module* that itself acquires a lock at any depth
  (``with self._a: self._flush()`` where ``_flush`` takes ``self._b``
  adds a -> b). One level of call indirection covers the repo's
  "caller holds the lock, helper takes the finer one" idiom without
  exploding into a whole-program alias analysis — the runtime watchdog
  owns the cross-module residue.

Lock identity is the canonical attribute (Condition aliases collapse,
matching the lockset pass), qualified as ``Class.attr`` / module
globals as ``<module>.name``. A cycle in the resulting digraph is
reported once per strongly-connected pair as ORX201.
"""

from __future__ import annotations

import ast
from pathlib import Path

from oryx_tpu.analysis.core import AnalysisPass, Finding, Module, register
from oryx_tpu.analysis.lockset import (
    _collect_lock_attrs,
    _module_locks,
    _self_attr,
)


def _canonical(expr: ast.AST, lock_attrs: dict, module_locks: set, cls: str) -> str | None:
    attr = _self_attr(expr)
    if attr is not None and attr in lock_attrs:
        return f"{cls}.{lock_attrs[attr]}"
    if isinstance(expr, ast.Name) and expr.id in module_locks:
        return f"<module>.{expr.id}"
    return None


class _Scope:
    """One class (or the module's function space): methods + lock names."""

    def __init__(self, name, methods, lock_attrs, module_locks):
        self.name = name
        self.methods = methods  # name -> FunctionDef
        self.lock_attrs = lock_attrs
        self.module_locks = module_locks
        # method -> set of lock names acquired anywhere in its body
        self.acquires: dict[str, set] = {}

    def locks_in(self, fn: ast.AST) -> set:
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    c = _canonical(
                        item.context_expr, self.lock_attrs, self.module_locks, self.name
                    )
                    if c:
                        out.add(c)
        return out


def _edges_for_scope(scope: _Scope) -> dict[tuple, int]:
    """(src, dst) -> witness line."""
    for m, fn in scope.methods.items():
        scope.acquires[m] = scope.locks_in(fn)
    edges: dict[tuple, int] = {}

    def walk(node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = []
            for item in node.items:
                c = _canonical(
                    item.context_expr, scope.lock_attrs, scope.module_locks, scope.name
                )
                if c:
                    for h in held:
                        if h != c:
                            edges.setdefault((h, c), node.lineno)
                    newly.append(c)
            held = held + [c for c in newly if c not in held]
            for stmt in node.body:
                walk(stmt, held)
            return
        if isinstance(node, ast.Call) and held:
            callee = _self_attr(node.func)
            if callee is None and isinstance(node.func, ast.Name):
                callee = node.func.id
            inner = scope.acquires.get(callee, ()) if callee else ()
            for c in inner:
                for h in held:
                    if h != c:
                        edges.setdefault((h, c), node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for fn in scope.methods.values():
        for stmt in fn.body:
            walk(stmt, [])
    return edges


def module_lock_graph(mod: Module) -> dict[tuple, int]:
    """(src, dst) -> line for every observed nesting in this module."""
    if mod.tree is None:
        return {}
    module_locks = _module_locks(mod.tree)
    edges: dict[tuple, int] = {}
    top_fns = {
        n.name: n
        for n in mod.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    scopes = [_Scope("<module>", top_fns, {}, module_locks)]
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            methods = {
                n.name: n
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            scopes.append(
                _Scope(node.name, methods, _collect_lock_attrs(node), module_locks)
            )
    for scope in scopes:
        edges.update(_edges_for_scope(scope))
    return edges


def _find_cycles(edges: dict[tuple, int]) -> list[tuple]:
    """Minimal cycle witnesses: (a, b) pairs where both a->b and a path
    b ->* a exist. Deduped on the unordered pair."""
    adj: dict[str, set] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src, dst):
        seen, work = {src}, [src]
        while work:
            cur = work.pop()
            if cur == dst:
                return True
            for nxt in adj.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
        return False

    seen_pairs = set()
    cycles = []
    for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
        pair = frozenset((a, b))
        if pair in seen_pairs:
            continue
        if reaches(b, a):
            seen_pairs.add(pair)
            cycles.append((a, b, line))
    return cycles


@register
class LockOrderPass(AnalysisPass):
    pass_id = "lockorder"
    description = (
        "static lock-acquisition nesting graph; cycles (potential "
        "deadlocks) are ORX201"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        findings = []
        for mod in modules:
            edges = module_lock_graph(mod)
            for a, b, line in _find_cycles(edges):
                findings.append(
                    Finding(
                        "lockorder",
                        "ORX201",
                        mod.path,
                        line,
                        f"{a}<->{b}",
                        f"lock-order cycle: {a} and {b} are acquired in "
                        f"both nesting orders (AB/BA deadlock hazard)",
                    )
                )
        return findings
