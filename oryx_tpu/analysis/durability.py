"""Crash-durability hygiene (the static half of docs/durability.md).

Every durable-state mutation in the repo is supposed to be one commit
sequence: same-directory sibling temp, file fsync, atomic rename,
parent-directory fsync. The kill-point sweep proves the instrumented
sequences recover; this pass hunts the sequences that *skipped* the
protocol — the writes a sweep can't find because no crashpoint was ever
threaded through them.

Rules:

- ORX601 rename-without-fsync: a publish-by-rename (``os.replace``,
  ``os.rename``, ``shutil.move``, ``Path.replace``/``rename``) in a
  function that never fsyncs a directory. The rename itself is atomic
  but not durable — until the parent directory entry is synced, a crash
  can un-happen the publish *after* the caller acknowledged it. Call
  ``storage.fsync_dir(target.parent)`` after the rename, or use the
  commit helpers.
- ORX602 cross-filesystem temp: the rename source is tempfile-derived
  (``tempfile.mkstemp``/``mkdtemp``/``NamedTemporaryFile``/...). The
  global temp dir is routinely a different filesystem (tmpfs) than the
  target, where ``os.rename`` fails with EXDEV and ``shutil.move``
  silently degrades to copy+delete — a crash mid-copy leaves a
  half-written target. Stage into a same-directory hidden sibling
  (``storage._tmp_sibling``'s pattern) instead.
- ORX603 state write outside the commit helpers: a direct
  ``Path.write_text``/``write_bytes`` call. Pathlib writes truncate in
  place, fsync nothing, and tear under kill — durable state goes
  through ``storage.commit_bytes``/``commit_text``/``open_write``
  (calls through the ``storage`` module are recognized and exempt).

Deliberate violations — the corruption injectors, whose whole job is
manufacturing torn state — are baselined with justification comments,
not special-cased here.
"""

from __future__ import annotations

import ast
from pathlib import Path

from oryx_tpu.analysis.core import AnalysisPass, Finding, Module, register

# module aliases whose .replace/.rename/.move are renames of paths
_RENAME_MODULE_CALLS = {
    ("os", "replace"),
    ("os", "rename"),
    ("shutil", "move"),
}
# module aliases whose attribute calls are never filesystem renames
_NON_FS_MODULES = {"dataclasses", "re", "string"}

_TEMPFILE_FACTORIES = {
    "mkstemp", "mkdtemp", "mktemp", "NamedTemporaryFile", "TemporaryFile",
    "SpooledTemporaryFile", "TemporaryDirectory", "gettempdir",
}


def _rename_source(call: ast.Call) -> ast.AST | None:
    """The expression being renamed, or None if this call is not a
    publish-by-rename site."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    if isinstance(fn.value, ast.Name):
        if (fn.value.id, fn.attr) in _RENAME_MODULE_CALLS:
            return call.args[0] if call.args else None
        if fn.value.id in _NON_FS_MODULES:
            return None
    # Path.replace(target) / Path.rename(target): exactly one positional
    # argument (str.replace and friends take two, DataFrame.rename takes
    # keywords) — the base object is the rename source
    if fn.attr in ("replace", "rename") and len(call.args) == 1 and not call.keywords:
        return fn.value
    return None


def _is_tempfile_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "tempfile"
        and node.func.attr in _TEMPFILE_FACTORIES
    )


def _calls_fsync_dir(fn_node: ast.AST) -> bool:
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Call):
            callee = sub.func
            name = callee.attr if isinstance(callee, ast.Attribute) else (
                callee.id if isinstance(callee, ast.Name) else None
            )
            if name == "fsync_dir":
                return True
    return False


def _tainted_names(fn_node: ast.AST) -> set[str]:
    """Names bound (one level) from a tempfile factory result —
    including tuple unpacks like ``fd, name = tempfile.mkstemp()``."""
    out: set[str] = set()
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Assign):
            continue
        if not any(_is_tempfile_call(n) for n in ast.walk(sub.value)):
            continue
        for tgt in sub.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _mentions_taint(node: ast.AST, tainted: set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
        if _is_tempfile_call(n):
            return True
    return False


def _iter_scopes(tree: ast.AST):
    """(qualname, node) for every function, methods included; classes
    contribute their name to the qualname."""

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[str] = []
            self.out: list[tuple[str, ast.AST]] = []

        def visit_ClassDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_FunctionDef(self, node):
            qual = ".".join(self.stack + [node.name]) if self.stack else node.name
            self.out.append((qual, node))
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    v.visit(tree)
    return v.out


def _direct_statements(fn_node: ast.AST):
    """Walk the function subtree minus nested function bodies, so each
    rename is attributed to its innermost scope exactly once."""
    nested: set[int] = set()
    for sub in ast.walk(fn_node):
        if sub is fn_node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for inner in ast.walk(sub):
                if inner is not sub:
                    nested.add(id(inner))
    for sub in ast.walk(fn_node):
        if id(sub) not in nested:
            yield sub


@register
class DurabilityPass(AnalysisPass):
    pass_id = "durability"
    description = (
        "crash-durability hygiene: publish-by-rename must fsync the "
        "directory, rename sources must not be tempfile-derived, durable "
        "state goes through the storage commit helpers (ORX601-ORX603)"
    )

    def run(self, modules: list[Module], targets: list[Path]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            if mod.tree is None:
                continue
            for qual, fn in _iter_scopes(mod.tree):
                findings.extend(self._check_scope(mod, qual, fn))
            findings.extend(self._check_writes(mod))
        return findings

    def _check_scope(self, mod: Module, qual: str, fn: ast.AST) -> list[Finding]:
        out: list[Finding] = []
        renames = [
            (sub, src)
            for sub in _direct_statements(fn)
            if isinstance(sub, ast.Call) and (src := _rename_source(sub)) is not None
        ]
        if not renames:
            return out
        tainted = _tainted_names(fn)
        synced = _calls_fsync_dir(fn)
        flagged_601 = False
        for call, src in renames:
            if not synced and not flagged_601:
                flagged_601 = True  # one per scope is enough signal
                out.append(
                    Finding(
                        "durability",
                        "ORX601",
                        mod.path,
                        call.lineno,
                        qual,
                        f"{qual}() publishes by rename (line {call.lineno}) "
                        f"but never fsyncs a directory — the rename is not "
                        f"durable until the parent directory entry is "
                        f"synced; call storage.fsync_dir(target.parent) "
                        f"after it or use the storage commit helpers",
                    )
                )
            if _mentions_taint(src, tainted):
                out.append(
                    Finding(
                        "durability",
                        "ORX602",
                        mod.path,
                        call.lineno,
                        qual,
                        f"{qual}() renames a tempfile-derived path (line "
                        f"{call.lineno}) — the global temp dir can sit on a "
                        f"different filesystem, where the rename fails "
                        f"(EXDEV) or shutil.move degrades to a non-atomic "
                        f"copy; stage into a same-directory sibling instead",
                    )
                )
        return out

    def _check_writes(self, mod: Module) -> list[Finding]:
        out: list[Finding] = []
        scopes = _iter_scopes(mod.tree)
        seen: set[str] = set()

        def enclosing(node: ast.AST) -> str:
            best = "<module>"
            for qual, fn in scopes:
                for sub in ast.walk(fn):
                    if sub is node:
                        best = qual
            return best

        for sub in ast.walk(mod.tree):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("write_text", "write_bytes")
            ):
                continue
            base = sub.func.value
            # calls through the storage module ARE the commit helpers
            if isinstance(base, ast.Name) and base.id == "storage":
                continue
            qual = enclosing(sub)
            if qual in seen:
                continue
            seen.add(qual)
            out.append(
                Finding(
                    "durability",
                    "ORX603",
                    mod.path,
                    sub.lineno,
                    qual,
                    f"{qual}() writes state with Path.{sub.func.attr} (line "
                    f"{sub.lineno}) — truncate-in-place, no fsync, tears "
                    f"under kill; route durable state through "
                    f"storage.commit_bytes/commit_text/open_write",
                )
            )
        return out
