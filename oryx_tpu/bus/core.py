"""Bus abstractions and the broker registry/factory.

API surface mirrors the reference's messaging SPI (framework/oryx-api:
KeyMessage.java, TopicProducer.java) and admin utils (framework/kafka-util/
src/main/java/com/cloudera/oryx/kafka/util/KafkaUtils.java:42-190).
"""

from __future__ import annotations

import abc
import hashlib
from dataclasses import dataclass
from typing import Iterable, Iterator


@dataclass(frozen=True)
class KeyMessage:
    """A key/message pair (KeyMessage/KeyMessageImpl analogue)."""

    key: str | None
    message: str


class TopicProducer(abc.ABC):
    """Wraps access to one topic of a broker (TopicProducer.java)."""

    @property
    @abc.abstractmethod
    def update_broker(self) -> str: ...

    @property
    @abc.abstractmethod
    def topic(self) -> str: ...

    @abc.abstractmethod
    def send(self, key: str | None, message: str) -> None: ...

    def send_many(self, records: "Iterable[tuple[str | None, str]]") -> int:
        """Publish a batch of (key, message) pairs; returns the count sent.

        The batched analogue of the reference producer's async buffering
        (TopicProducerImpl.java:194-202 — linger 1s / batch 100 / gzip):
        brokers override this to amortize per-message costs (one lock +
        one buffered write per batch on the file bus) instead of paying
        them per record. The default just loops `send`.
        """
        n = 0
        for key, message in records:
            self.send(key, message)
            n += 1
        return n

    def send_message(self, message: str) -> None:
        self.send(None, message)

    @abc.abstractmethod
    def close(self) -> None: ...

    def __enter__(self) -> "TopicProducer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class TopicConsumer(abc.ABC):
    """Iterates KeyMessage records from a topic.

    `poll(max_records, timeout)` returns possibly-empty batches;
    iteration blocks until `close()` (like a Kafka consumer stream).
    """

    @abc.abstractmethod
    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]: ...

    def poll_block(self, max_records: int = 1000, timeout: float = 0.1):
        """Columnar poll: one RecordBlock of byte-string arrays (None when
        nothing arrived). High-rate consumers (the speed layer at 100K+
        events/s) use this to skip per-record object construction; brokers
        override it to skip per-record decoding entirely.

        Trace control records (the reserved "@trc" key a traced producer
        prepends to its batch) are stripped here and surfaced as
        ``block.trace``; they still occupy a topic offset on both sides,
        so seek/commit arithmetic is untouched."""
        from oryx_tpu.common.records import RecordBlock
        from oryx_tpu.common.tracing import TRACE_KEY

        records = self.poll(max_records, timeout)
        if not records:
            return None
        trace = None
        if any(r.key == TRACE_KEY for r in records):
            kept = []
            for r in records:
                if r.key == TRACE_KEY:
                    trace = r.message
                else:
                    kept.append(r)
            records = kept
            if not records:
                return None
        block = RecordBlock.from_key_messages(records)
        block.trace = trace
        return block

    @abc.abstractmethod
    def positions(self) -> dict[int, int]:
        """Current partition -> next-offset map."""

    def seek(self, positions: dict[int, int]) -> None:
        """Move the read position of the given partitions (absolute
        offsets). The redelivery primitive: the fault bus rewinds a
        consumer to simulate a dropped delivery, and the net-bus client
        restores a reopened consumer after a reconnect. Optional —
        brokers that cannot seek raise."""
        raise NotImplementedError(f"{type(self).__name__} does not support seek")

    @abc.abstractmethod
    def commit(self) -> None:
        """Persist current positions to the group offset ledger."""

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def closed(self) -> bool: ...

    def __iter__(self) -> Iterator[KeyMessage]:
        while not self.closed():
            for rec in self.poll(timeout=0.2):
                yield rec

    def __enter__(self) -> "TopicConsumer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Broker(abc.ABC):
    """Topic admin + producer/consumer factory for one bus locator."""

    @abc.abstractmethod
    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None: ...

    @abc.abstractmethod
    def topic_exists(self, topic: str) -> bool: ...

    @abc.abstractmethod
    def delete_topic(self, topic: str) -> None: ...

    @abc.abstractmethod
    def producer(self, topic: str) -> TopicProducer: ...

    @abc.abstractmethod
    def consumer(
        self,
        topic: str,
        group: str | None = None,
        from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        """A consumer. With `group` set and offsets stored, resumes from the
        stored offsets; `from_beginning=True` starts at offset 0 (the
        update-topic replay path, SpeedLayer.java:107-121); otherwise starts
        at the topic end (latest). `partitions` restricts the consumer to
        that subset of the topic's partitions (manual assignment, the
        sharded-pipeline primitive): positions()/commit() then cover ONLY
        the owned partitions, so concurrent owners of disjoint subsets
        never clobber each other's ledger entries (set_offsets merges
        per-partition). None = all partitions (group-free Kafka-style
        assignment of everything). Brokers that cannot restrict raise."""

    @abc.abstractmethod
    def get_offsets(self, group: str, topic: str) -> dict[int, int]: ...

    @abc.abstractmethod
    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None: ...

    @abc.abstractmethod
    def latest_offsets(self, topic: str) -> dict[int, int]: ...


def resolve_partitions(nparts: int, partitions: list[int] | None) -> list[int]:
    """Normalize a consumer's partition-subset request against the topic's
    partition count: None = everything; otherwise a sorted, deduped subset
    that must be non-empty and in range (a silent clamp would quietly
    un-own data)."""
    if partitions is None:
        return list(range(nparts))
    parts = sorted({int(p) for p in partitions})
    if not parts:
        raise ValueError("partitions must be non-empty (or None for all)")
    if parts[0] < 0 or parts[-1] >= nparts:
        raise ValueError(
            f"partitions {parts} out of range for a {nparts}-partition topic"
        )
    return parts


def partition_for(key: str | None, num_partitions: int) -> int:
    if num_partitions <= 1:
        return 0
    if key is None:
        return 0
    h = hashlib.md5(key.encode("utf-8")).digest()
    return int.from_bytes(h[:4], "big") % num_partitions


# ---------------------------------------------------------------------------
# Broker factory
# ---------------------------------------------------------------------------


def get_broker(locator: str) -> Broker:
    """Resolve a bus locator to a Broker.

    inproc://<name> — process-local named broker (tests, single-process runs)
    file:/<dir> or file://<dir> or a bare path — file-backed broker
    shm:/<dir>[?ring_mb=N&full_block_ms=MS&frame_records=K] — shared-memory
        ring-buffer broker with a zero-copy columnar block format
        (oryx_tpu.bus.shmbus; the high-rate speed-layer transport)
    tcp://host:port[?connect_timeout=S&retry_max_attempts=N&...] —
        networked bus server (oryx_tpu.bus.netbus; start one with
        `python -m oryx_tpu bus-serve`)
    kafka://host:port[,host:port...] — Apache Kafka via kafka-python
        (optional dependency; oryx_tpu.bus.kafkabus)
    fault+<inner>://...?drop=0.1&delay_ms=20&dup=0.01&fail_connect=N&seed=S
        — chaos wrapper injecting seeded faults around any inner broker
        (oryx_tpu.bus.faultbus; docs/resilience.md has the grammar)
    """
    if locator.startswith("fault+"):
        from oryx_tpu.bus.faultbus import FaultBroker

        return FaultBroker.from_locator(locator)
    if locator.startswith("inproc://"):
        from oryx_tpu.bus.inproc import InProcessBroker

        return InProcessBroker.named(locator[len("inproc://") :])
    if locator.startswith("tcp://"):
        from oryx_tpu.bus.netbus import NetBroker

        rest, _, query = locator[len("tcp://") :].partition("?")
        host, _, port = rest.partition(":")
        return NetBroker(host, int(port), **NetBroker.options_from_query(query))
    if locator.startswith("kafka://"):
        from oryx_tpu.bus.kafkabus import KafkaBroker

        return KafkaBroker(locator[len("kafka://") :])
    if locator.startswith("shm:"):
        path = locator[len("shm:") :]
        while path.startswith("//"):
            path = path[1:]
        path, _, query = path.partition("?")
        from oryx_tpu.bus.shmbus import ShmBroker

        return ShmBroker(path, **ShmBroker.options_from_query(query))
    if locator.startswith("file:"):
        path = locator[len("file:") :]
        while path.startswith("//"):
            path = path[1:]
        from oryx_tpu.bus.filebus import FileBroker

        return FileBroker(path)
    # bare filesystem path
    from oryx_tpu.bus.filebus import FileBroker

    return FileBroker(locator)


# -- KafkaUtils-style module-level admin helpers ----------------------------


def maybe_create_topic(locator: str, topic: str, partitions: int = 1, config: dict | None = None) -> None:
    get_broker(locator).create_topic(topic, partitions, config)


def topic_config_from(cfg, which: str) -> dict | None:
    """Per-topic broker settings from an oryx config block
    (`oryx.<which>-topic.*`): retention + segment sizing for brokers that
    support them (the file bus), max-size recorded for operators."""
    out = {}
    for key, conf_key in (
        ("max-size", f"oryx.{which}-topic.message.max-size"),
        ("retention-hours", f"oryx.{which}-topic.retention-hours"),
        ("segment-bytes", f"oryx.{which}-topic.segment-bytes"),
    ):
        v = cfg.get(conf_key, None)
        if v is not None:
            out[key] = v
    return out or None


def topic_exists(locator: str, topic: str) -> bool:
    return get_broker(locator).topic_exists(topic)


def delete_topic(locator: str, topic: str) -> None:
    broker = get_broker(locator)
    if broker.topic_exists(topic):
        broker.delete_topic(topic)


def get_offsets(locator: str, group: str, topic: str) -> dict[int, int]:
    return get_broker(locator).get_offsets(group, topic)


def set_offsets(locator: str, group: str, topic: str, offsets: dict[int, int]) -> None:
    get_broker(locator).set_offsets(group, topic, offsets)
