"""File-backed broker: durable cross-process bus on a shared filesystem.

The single-host production analogue of Kafka + ZooKeeper in the reference:
each topic is a directory of append-only partition logs (one JSON record
per line), and consumer-group offsets live in a ledger file per group —
the rebuild of the reference's ZK offset storage (KafkaUtils.java:123-162)
that makes layers resume where they left off. Appends are serialized with
fcntl advisory locks so batch/speed/serving processes can share one bus
directory. Multi-host deployments plug a real broker behind the same
Broker interface.

Layout:
    <root>/<topic>/partition-<i>.log     one JSON line per record
    <root>/<topic>/.meta.json            {"partitions": N, "config": {...}}
    <root>/__offsets__/<group>.json      {"<topic>": {"0": 17, ...}}
"""

from __future__ import annotations

import fcntl
import json
import os
import time
from pathlib import Path

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, TopicProducer, partition_for

_OFFSETS_DIR = "__offsets__"


class _Flock:
    def __init__(self, path: Path) -> None:
        self._path = path

    def __enter__(self):
        self._f = open(self._path, "a+")
        fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
        return self._f

    def __exit__(self, *exc):
        fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
        self._f.close()
        return False


class FileBroker(Broker):
    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def locator(self) -> str:
        return f"file:{self.root}"

    # -- admin --------------------------------------------------------------

    def _topic_dir(self, topic: str) -> Path:
        return self.root / topic

    def _meta_path(self, topic: str) -> Path:
        return self._topic_dir(topic) / ".meta.json"

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        d = self._topic_dir(topic)
        d.mkdir(parents=True, exist_ok=True)
        meta = self._meta_path(topic)
        if not meta.exists():
            meta.write_text(json.dumps({"partitions": max(1, partitions), "config": config or {}}))
            for i in range(max(1, partitions)):
                (d / f"partition-{i}.log").touch()

    def topic_exists(self, topic: str) -> bool:
        return self._meta_path(topic).exists()

    def delete_topic(self, topic: str) -> None:
        import shutil

        shutil.rmtree(self._topic_dir(topic), ignore_errors=True)
        off_dir = self.root / _OFFSETS_DIR
        if off_dir.is_dir():
            for ledger in off_dir.glob("*.json"):
                with _Flock(ledger.with_suffix(".lock")):
                    try:
                        data = json.loads(ledger.read_text() or "{}")
                    except json.JSONDecodeError:
                        data = {}
                    if topic in data:
                        del data[topic]
                        tmp = ledger.with_suffix(".tmp")
                        tmp.write_text(json.dumps(data))
                        os.replace(tmp, ledger)

    def _num_partitions(self, topic: str) -> int:
        try:
            return int(json.loads(self._meta_path(topic).read_text())["partitions"])
        except (OSError, json.JSONDecodeError, KeyError):
            return 1

    # -- offsets ------------------------------------------------------------

    def _ledger_path(self, group: str) -> Path:
        d = self.root / _OFFSETS_DIR
        d.mkdir(parents=True, exist_ok=True)
        return d / f"{group}.json"

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        ledger = self._ledger_path(group)
        if not ledger.exists():
            return {}
        with _Flock(ledger.with_suffix(".lock")):
            try:
                data = json.loads(ledger.read_text() or "{}")
            except json.JSONDecodeError:
                return {}
        return {int(k): int(v) for k, v in data.get(topic, {}).items()}

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        ledger = self._ledger_path(group)
        with _Flock(ledger.with_suffix(".lock")):
            try:
                data = json.loads(ledger.read_text() or "{}") if ledger.exists() else {}
            except json.JSONDecodeError:
                data = {}
            data.setdefault(topic, {}).update({str(k): int(v) for k, v in offsets.items()})
            tmp = ledger.with_suffix(".tmp")
            tmp.write_text(json.dumps(data))
            os.replace(tmp, ledger)

    def latest_offsets(self, topic: str) -> dict[int, int]:
        out: dict[int, int] = {}
        d = self._topic_dir(topic)
        for i in range(self._num_partitions(topic)):
            p = d / f"partition-{i}.log"
            out[i] = _count_lines(p) if p.exists() else 0
        return out

    # -- produce/consume ----------------------------------------------------

    def producer(self, topic: str) -> TopicProducer:
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        return _FileProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False
    ) -> TopicConsumer:
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        return _FileConsumer(self, topic, group, from_beginning)


def _count_lines(path: Path) -> int:
    n = 0
    with open(path, "rb") as f:
        for _ in f:
            n += 1
    return n


class _FileProducer(TopicProducer):
    def __init__(self, broker: FileBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic
        self._nparts = broker._num_partitions(topic)

    @property
    def update_broker(self) -> str:
        return self._broker.locator()

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        p = partition_for(key, self._nparts)
        path = self._broker._topic_dir(self._topic) / f"partition-{p}.log"
        record = json.dumps({"k": key, "m": message}, separators=(",", ":"))
        with _Flock(path.with_suffix(".lock")):
            with open(path, "a", encoding="utf-8") as f:
                f.write(record + "\n")

    def close(self) -> None:
        pass


class _FileConsumer(TopicConsumer):
    def __init__(
        self, broker: FileBroker, topic: str, group: str | None, from_beginning: bool
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._group = group
        self._closed = False
        nparts = broker._num_partitions(topic)
        stored = broker.get_offsets(group, topic) if group else {}
        if stored:
            self._pos = {i: stored.get(i, 0) for i in range(nparts)}
        elif from_beginning:
            self._pos = {i: 0 for i in range(nparts)}
        else:
            latest = broker.latest_offsets(topic)
            self._pos = {i: latest.get(i, 0) for i in range(nparts)}
        # byte position of record self._pos[i] in each log; established
        # lazily (one O(n) scan per partition), then advanced incrementally
        # so each poll seeks instead of re-reading the whole log.
        self._byte: dict[int, int] = {}

    def _seek_start(self, f, partition: int) -> None:
        """Position f at record index self._pos[partition]."""
        byte = self._byte.get(partition)
        if byte is not None:
            f.seek(byte)
            return
        for _ in range(self._pos[partition]):
            if not f.readline():
                break
        self._byte[partition] = f.tell()

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        deadline = time.monotonic() + timeout
        while True:
            out: list[KeyMessage] = []
            d = self._broker._topic_dir(self._topic)
            for i in sorted(self._pos):
                path = d / f"partition-{i}.log"
                if not path.exists():
                    continue
                scanned = 0  # complete records consumed this poll
                with open(path, "rb") as f:
                    self._seek_start(f, i)
                    while True:
                        raw = f.readline()
                        if not raw:
                            break
                        if not raw.endswith(b"\n"):
                            break  # partial tail of an in-flight append; retry
                        scanned += 1
                        self._byte[i] = f.tell()
                        line = raw.decode("utf-8", errors="replace").strip()
                        if line:
                            try:
                                rec = json.loads(line)
                            except json.JSONDecodeError:
                                continue  # corrupt complete line: skip it for good
                            out.append(KeyMessage(rec.get("k"), rec.get("m", "")))
                        if len(out) >= max_records:
                            break
                self._pos[i] += scanned
                if len(out) >= max_records:
                    return out
            if out or self._closed or time.monotonic() >= deadline:
                return out
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))

    def positions(self) -> dict[int, int]:
        return dict(self._pos)

    def commit(self) -> None:
        if self._group:
            self._broker.set_offsets(self._group, self._topic, self._pos)

    def close(self) -> None:
        self._closed = True

    def closed(self) -> bool:
        return self._closed
