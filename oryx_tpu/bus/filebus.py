"""File-backed broker: durable cross-process bus on a shared filesystem.

The single-host production analogue of Kafka + ZooKeeper in the reference:
each topic is a directory of append-only partition logs (one JSON record
per line), and consumer-group offsets live in a ledger file per group —
the rebuild of the reference's ZK offset storage (KafkaUtils.java:123-162)
that makes layers resume where they left off. Appends are serialized with
fcntl advisory locks so batch/speed/serving processes can share one bus
directory. Multi-host deployments plug a real broker behind the same
Broker interface.

Segmented logs + retention: each partition is a sequence of segments —
archived `partition-<i>.seg<base>.log` files (base = absolute offset of
their first record) plus the active `partition-<i>.log` whose base lives
in a `partition-<i>.base` sidecar. The producer rolls the active segment
past `segment-bytes` and deletes archived segments older than
`retention-hours`. This bounds the replay-from-zero recovery story the
same way Kafka topic retention does for the reference (admin.md:78-81
tells operators to bound update-topic retention): speed/serving restart
by replaying from the earliest *retained* offset, and a stored offset
that has aged out clamps forward to it (Kafka earliest-reset semantics).
Offsets are absolute and survive segment rolls.

Layout:
    <root>/<topic>/partition-<i>.log           active segment
    <root>/<topic>/partition-<i>.base          {"base": N} for the active
    <root>/<topic>/partition-<i>.seg<J>.log    archived segment, base J
    <root>/<topic>/.meta.json                  {"partitions": N, "config": {...}}
    <root>/__offsets__/<group>.json            {"<topic>": {"0": 17, ...}}
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import time
from pathlib import Path

from oryx_tpu.bus import blockcodec
from oryx_tpu.bus.core import (
    Broker,
    KeyMessage,
    TopicConsumer,
    TopicProducer,
    partition_for,
    resolve_partitions,
)
from oryx_tpu.common import metrics, storage
from oryx_tpu.common.crashpoints import crashpoint

log = logging.getLogger(__name__)

_OFFSETS_DIR = "__offsets__"

_TAIL_SCAN_BYTES = 1 << 20


def _repair_torn_tail(path: Path) -> int:
    """Truncate a partition segment to its last newline-terminated record.

    Every committed record ends in ``\\n`` (the producer writes whole
    payloads under the partition flock), so bytes past the final newline
    can only be the torn tail of a writer that died mid-append — never
    acknowledged, safe to drop, and *necessary* to drop before fresh
    appends land after them and weld two half-records into one corrupt
    line. Caller holds the partition flock. Returns bytes dropped
    (0 = intact); counted on ``bus.repair.truncated``."""
    try:
        size = path.stat().st_size
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb+") as f:
        f.seek(size - 1)
        if f.read(1) == b"\n":
            return 0
        good = 0  # byte just past the last newline; 0 = no complete record
        pos = size
        while pos > 0:
            step = min(_TAIL_SCAN_BYTES, pos)
            f.seek(pos - step)
            nl = f.read(step).rfind(b"\n")
            if nl != -1:
                good = pos - step + nl + 1
                break
            pos -= step
        dropped = size - good
        f.truncate(good)
        f.flush()
        os.fsync(f.fileno())
    metrics.registry.counter("bus.repair.truncated").inc()
    log.warning(
        "bus repair: truncated %d torn byte(s) off %s (never acknowledged)",
        dropped, path,
    )
    return dropped


class _Flock:
    def __init__(self, path: Path) -> None:
        self._path = path

    def __enter__(self):
        self._f = open(self._path, "a+")
        fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
        return self._f

    def __exit__(self, *exc):
        fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
        self._f.close()
        return False


class FileBroker(Broker):
    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def locator(self) -> str:
        return f"file:{self.root}"

    # -- admin --------------------------------------------------------------

    def _topic_dir(self, topic: str) -> Path:
        return self.root / topic

    def _meta_path(self, topic: str) -> Path:
        return self._topic_dir(topic) / ".meta.json"

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        d = self._topic_dir(topic)
        d.mkdir(parents=True, exist_ok=True)
        meta = self._meta_path(topic)
        if not meta.exists():
            storage.commit_text(
                meta, json.dumps({"partitions": max(1, partitions), "config": config or {}})
            )
            for i in range(max(1, partitions)):
                (d / f"partition-{i}.log").touch()

    def topic_exists(self, topic: str) -> bool:
        return self._meta_path(topic).exists()

    def delete_topic(self, topic: str) -> None:
        import shutil

        shutil.rmtree(self._topic_dir(topic), ignore_errors=True)
        off_dir = self.root / _OFFSETS_DIR
        if off_dir.is_dir():
            for ledger in off_dir.glob("*.json"):
                with _Flock(ledger.with_suffix(".lock")):
                    try:
                        data = json.loads(ledger.read_text() or "{}")
                    except json.JSONDecodeError:
                        data = {}
                    if topic in data:
                        del data[topic]
                        storage.commit_text(ledger, json.dumps(data))

    def _num_partitions(self, topic: str) -> int:
        try:
            return int(json.loads(self._meta_path(topic).read_text())["partitions"])
        except (OSError, json.JSONDecodeError, KeyError):
            return 1

    def _topic_config(self, topic: str) -> dict:
        try:
            return json.loads(self._meta_path(topic).read_text()).get("config") or {}
        except (OSError, json.JSONDecodeError):
            return {}

    # -- segments ------------------------------------------------------------

    def _active_path(self, topic: str, i: int) -> Path:
        return self._topic_dir(topic) / f"partition-{i}.log"

    def _active_base(self, topic: str, i: int) -> int:
        side = self._topic_dir(topic) / f"partition-{i}.base"
        try:
            return int(json.loads(side.read_text())["base"])
        except (OSError, json.JSONDecodeError, KeyError, ValueError):
            return 0  # pre-segmentation logs: active segment starts at 0

    def _set_active_base(self, topic: str, i: int, base: int) -> None:
        side = self._topic_dir(topic) / f"partition-{i}.base"
        storage.commit_text(side, json.dumps({"base": base}))

    def _segments(self, topic: str, i: int) -> list[tuple[int, Path]]:
        """(base, path) of every live segment, archived first, active last."""
        d = self._topic_dir(topic)
        segs: list[tuple[int, Path]] = []
        prefix = f"partition-{i}.seg"
        for p in d.glob(f"{prefix}*.log"):
            try:
                segs.append((int(p.name[len(prefix):-len(".log")]), p))
            except ValueError:
                continue
        segs.sort()
        segs.append((self._active_base(topic, i), self._active_path(topic, i)))
        return segs

    def earliest_offsets(self, topic: str) -> dict[int, int]:
        """First retained offset per partition (post-retention floor)."""
        return {
            i: self._segments(topic, i)[0][0]
            for i in range(self._num_partitions(topic))
        }

    def apply_retention(self, topic: str, now: float | None = None) -> list[Path]:
        """Delete archived segments older than the topic's retention-hours
        (config key; None/absent = keep forever). The active segment is
        never deleted. Returns the deleted paths."""
        hours = self._topic_config(topic).get("retention-hours")
        if hours is None:
            return []
        cutoff = (time.time() if now is None else now) - float(hours) * 3600.0
        deleted = []
        for i in range(self._num_partitions(topic)):
            # delete only a prefix of the segment chain — a hole in the
            # middle would make offsets between surviving segments
            # unreadable
            for base, path in self._segments(topic, i)[:-1]:  # skip active
                try:
                    if path.stat().st_mtime >= cutoff:
                        break
                    path.unlink(missing_ok=True)
                    deleted.append(path)
                except OSError:
                    break
        return deleted

    # -- offsets ------------------------------------------------------------

    def _ledger_path(self, group: str) -> Path:
        d = self.root / _OFFSETS_DIR
        d.mkdir(parents=True, exist_ok=True)
        return d / f"{group}.json"

    def _quarantine_ledger(self, ledger: Path) -> None:
        """A ledger that no longer parses is moved aside (forensics, not
        deletion) — consumers then resume from the earliest retained
        offset, which is the at-least-once answer: replayed work, never
        lost acknowledged input. Caller holds the ledger flock."""
        aside = ledger.with_name(f"{ledger.name}.corrupt-{os.getpid()}")
        try:
            os.replace(ledger, aside)
        except OSError:
            return
        # the quarantine must survive the next crash too, or the group
        # replays its earliest-offset reset against a resurrected ledger
        storage.fsync_dir(ledger.parent)
        metrics.registry.counter("bus.repair.ledger-quarantined").inc()
        log.warning(
            "bus repair: quarantined unreadable offset ledger %s -> %s "
            "(group resumes from earliest retained offsets)", ledger, aside,
        )

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        ledger = self._ledger_path(group)
        if not ledger.exists():
            return {}
        with _Flock(ledger.with_suffix(".lock")):
            try:
                data = json.loads(ledger.read_text() or "{}")
            except json.JSONDecodeError:
                self._quarantine_ledger(ledger)
                # the group HAD commits we can no longer read. Answering
                # {} would drop it into fresh-group-starts-at-latest and
                # silently skip everything since those commits; pinning
                # it to the earliest retained offsets is the at-least-
                # once answer (replayed work, never lost input).
                return self.earliest_offsets(topic)
        return {int(k): int(v) for k, v in data.get(topic, {}).items()}

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        ledger = self._ledger_path(group)
        with _Flock(ledger.with_suffix(".lock")):
            try:
                data = json.loads(ledger.read_text() or "{}") if ledger.exists() else {}
            except json.JSONDecodeError:
                self._quarantine_ledger(ledger)
                data = {}
            data.setdefault(topic, {}).update({str(k): int(v) for k, v in offsets.items()})
            crashpoint("bus.file.offsets.pre")
            storage.commit_text(ledger, json.dumps(data))
            crashpoint("bus.file.offsets.post")

    def latest_offsets(self, topic: str) -> dict[int, int]:
        out: dict[int, int] = {}
        for i in range(self._num_partitions(topic)):
            p = self._active_path(topic, i)
            # Under the partition lock: a concurrent roll replaces the
            # active file before bumping the base sidecar, so an unlocked
            # read could pair a fresh (empty) active with the stale base
            # and report an offset lower than reality.
            with _Flock(p.with_suffix(".lock")):
                base = self._active_base(topic, i)
                out[i] = base + (_count_lines(p) if p.exists() else 0)
        return out

    # -- fsck / repair -------------------------------------------------------

    def _repair_partition(self, topic: str, i: int, report: dict) -> None:
        """One partition's fsck, under its flock: torn active tail is
        truncated to the last complete record, and a base sidecar that is
        unreadable — or *behind* the archived segment chain — is rebuilt
        from the chain. A stale base is what a producer killed mid-roll
        leaves (the active segment archived, the new base never
        committed); left alone it would shadow every record in the
        freshly archived segment, silently losing acknowledged input.
        Found by the kill-point sweep at ``bus.file.roll.mid``."""
        path = self._active_path(topic, i)
        with _Flock(path.with_suffix(".lock")):
            if _repair_torn_tail(path):
                report["truncated"] += 1
            side = self._topic_dir(topic) / f"partition-{i}.base"
            stored = 0
            parseable = True
            if side.exists():
                try:
                    stored = int(json.loads(side.read_text())["base"])
                except (OSError, json.JSONDecodeError, KeyError, ValueError):
                    parseable = False
            # the archived chain's end; the active base can legitimately
            # EXCEED it (retention deleted every archived segment) but can
            # never trail it
            chain_end = 0
            for seg_base, seg_path in self._segments(topic, i)[:-1]:
                try:
                    chain_end = max(chain_end, seg_base + _count_lines(seg_path))
                except OSError:
                    continue
            if not parseable or stored < chain_end:
                self._set_active_base(topic, i, chain_end)
                report["bases-rebuilt"] += 1
                metrics.registry.counter("bus.repair.base-rebuilt").inc()
                log.warning(
                    "bus repair: rebuilt %s base sidecar for "
                    "%s/partition-%d (%d -> %d)",
                    "unreadable" if not parseable else "stale",
                    topic, i, stored, chain_end,
                )

    def repair(self, topic: str | None = None) -> dict:
        """fsck-style sweep over the bus directory: torn segment tails,
        unreadable base sidecars, stale commit temp litter, unreadable
        offset ledgers. Safe against live writers (every mutation runs
        under the same flocks the producers take). Run automatically on
        consumer open and via ``oryx-tpu repair``. Returns a count
        report; every action also lands on a bus.repair.* counter."""
        report = {
            "truncated": 0, "bases-rebuilt": 0,
            "tmp-swept": 0, "ledgers-quarantined": 0,
        }
        topics = (
            [topic]
            if topic is not None
            else [
                d.name
                for d in sorted(self.root.iterdir())
                if d.is_dir() and d.name != _OFFSETS_DIR and (d / ".meta.json").exists()
            ]
        )
        for t in topics:
            if not self.topic_exists(t):
                continue
            report["tmp-swept"] += storage.sweep_tmp(self._topic_dir(t))
            for i in range(self._num_partitions(t)):
                self._repair_partition(t, i, report)
        off_dir = self.root / _OFFSETS_DIR
        if topic is None and off_dir.is_dir():
            report["tmp-swept"] += storage.sweep_tmp(off_dir)
            for ledger in sorted(off_dir.glob("*.json")):
                with _Flock(ledger.with_suffix(".lock")):
                    try:
                        json.loads(ledger.read_text() or "{}")
                    except json.JSONDecodeError:
                        self._quarantine_ledger(ledger)
                        report["ledgers-quarantined"] += 1
        if report["tmp-swept"]:
            metrics.registry.counter("bus.repair.tmp-swept").inc(report["tmp-swept"])
        return report

    # -- produce/consume ----------------------------------------------------

    def producer(self, topic: str) -> TopicProducer:
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        return _FileProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        # repair-on-open: a consumer whose offsets were computed against a
        # torn tail (e.g. latest_offsets counting a half-record) would sit
        # one record in the future forever; fsck the topic first
        self.repair(topic)
        return _FileConsumer(self, topic, group, from_beginning, partitions)


def _count_lines(path: Path) -> int:
    # only newline-terminated lines are records: a torn final line (writer
    # died mid-append) was never acknowledged and must not shift offsets
    n = 0
    with open(path, "rb") as f:
        for line in f:
            n += line.endswith(b"\n")
    return n


_DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024
_READ_CHUNK_BYTES = 1 << 20

# -- record wire format -------------------------------------------------------
#
# One record per line: `<key>\t<message>` with backslash escapes; see
# bus/blockcodec.py, the single home of both the text and the binary
# frame codecs (shared with netbus and shmbus so the formats cannot
# drift). The old private names stay as aliases for callers that grew
# up importing them from here.

_ESC_MAP = blockcodec._ESC_MAP
_NEEDS_ESC = blockcodec._NEEDS_ESC
_NEEDS_ESC_BODY = blockcodec._NEEDS_ESC_BODY
_SENTINEL = blockcodec._SENTINEL
_enc_field = blockcodec.enc_field
_encode_record = blockcodec.encode_record
_unescape = blockcodec.unescape


class _FileProducer(TopicProducer):
    def __init__(self, broker: FileBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic
        self._nparts = broker._num_partitions(topic)
        cfg = broker._topic_config(topic)
        self._segment_bytes = int(cfg.get("segment-bytes") or _DEFAULT_SEGMENT_BYTES)
        self._has_retention = cfg.get("retention-hours") is not None

    @property
    def update_broker(self) -> str:
        return self._broker.locator()

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        p = partition_for(key, self._nparts)
        self._append_lines(p, _encode_record(key, message) + "\n")

    # One buffered write's worth of payload; also bounds how far a batch
    # can overshoot segment-bytes (the roll check runs once per slice).
    _WRITE_SLICE_BYTES = 4 * 1024 * 1024

    def send_many(self, records) -> int:
        """One flock + one buffered write per ~4MB slice per partition —
        the file-bus analogue of the reference producer's batching
        (TopicProducerImpl.java:194-202). A million-row model publish is
        a handful of lock/open/write cycles instead of a million, while
        segment rolls still happen at slice granularity so retention and
        replay stay bounded for arbitrarily large batches.

        Per-record work is kept off the hot path: the partition and the
        encoded key are cached per key object (speed-layer batches carry
        one constant key), and the needs-escape scan runs as ONE regex
        pass over each joined slice — a clean slice (the overwhelmingly
        common case: messages are JSON, keys are short tokens) is joined
        and written without ever touching records individually."""
        pending: dict[int, list[tuple[str, str]]] = {}
        pending_bytes = [0] * self._nparts
        pending_nuls = [0] * self._nparts
        n = 0

        def flush(p: int) -> None:
            recs = pending.pop(p, None)
            if not recs:
                return
            nuls, pending_nuls[p] = pending_nuls[p], 0
            pending_bytes[p] = 0
            # one pass over the joined slice instead of a regex scan per
            # record: \ and \r never occur in a clean framed slice, and a
            # raw \t / \n / \0 inside a message shows up as a count
            # mismatch against the expected separator/None-marker counts
            # (keys are already escaped). Any hit re-encodes the slice per
            # record (_enc_field no-ops on clean fields).
            blob = "\n".join(ek + "\t" + m for ek, m in recs)
            if (
                _NEEDS_ESC_BODY.search(blob) is not None
                or blob.count("\n") != len(recs) - 1
                or blob.count("\t") != len(recs)
                or blob.count("\x00") != nuls
            ):
                blob = "\n".join(ek + "\t" + _enc_field(m) for ek, m in recs)
            self._append_lines(p, blob + "\n")

        last_key: str | None | object = _SENTINEL
        p = 0
        ek = ""
        for key, message in records:
            if key is not last_key:
                p = partition_for(key, self._nparts)
                ek = "\x00" if key is None else _enc_field(key)
                last_key = key
            pending.setdefault(p, []).append((ek, message))
            pending_bytes[p] += len(ek) + len(message) + 2
            pending_nuls[p] += ek == "\x00"
            n += 1
            if pending_bytes[p] >= self._WRITE_SLICE_BYTES:
                flush(p)
        for p in list(pending):
            flush(p)
        return n

    def _append_lines(self, p: int, payload: str) -> None:
        path = self._broker._topic_dir(self._topic) / f"partition-{p}.log"
        with _Flock(path.with_suffix(".lock")):
            # a writer that died mid-append left a torn (un-acknowledged)
            # tail; it MUST go before fresh bytes land after it, or the
            # two half-records weld into one corrupt line
            _repair_torn_tail(path)
            try:
                if path.stat().st_size >= self._segment_bytes:
                    self._roll(p, path)
            except OSError:
                pass
            crashpoint("bus.file.append.pre")
            with open(path, "a", encoding="utf-8") as f:
                f.write(payload)
                f.flush()
            crashpoint("bus.file.append.post")

    def _roll(self, partition: int, path: Path) -> None:
        """Archive the full active segment and start a fresh one (under
        the partition flock). Retention runs opportunistically here so a
        long-lived bus stays bounded without an external GC process."""
        broker = self._broker
        base = broker._active_base(self._topic, partition)
        n = _count_lines(path)
        if n == 0:
            return
        archived = path.with_name(f"partition-{partition}.seg{base:020d}.log")
        if archived.exists():
            # the sidecar is stale — a writer died mid-roll (segment
            # archived, new base never committed) and we are about to
            # archive a fresh active over its segment, destroying
            # acknowledged records. Re-anchor the base past the archived
            # chain first; the active's records shift to the repaired
            # offsets, the archive keeps its own.
            for seg_base, seg_path in broker._segments(self._topic, partition)[:-1]:
                try:
                    base = max(base, seg_base + _count_lines(seg_path))
                except OSError:
                    continue
            broker._set_active_base(self._topic, partition, base)
            metrics.registry.counter("bus.repair.base-rebuilt").inc()
            log.warning(
                "bus repair: roll found stale base for %s/partition-%d; "
                "re-anchored to %d past the archived chain",
                self._topic, partition, base,
            )
            archived = path.with_name(f"partition-{partition}.seg{base:020d}.log")
        os.replace(path, archived)
        storage.fsync_dir(path.parent)
        crashpoint("bus.file.roll.mid")
        broker._set_active_base(self._topic, partition, base + n)
        path.touch()
        if self._has_retention:
            broker.apply_retention(self._topic)

    def close(self) -> None:
        pass


class _FileConsumer(TopicConsumer):
    def __init__(
        self, broker: FileBroker, topic: str, group: str | None,
        from_beginning: bool, partitions: list[int] | None = None,
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._group = group
        self._closed = False
        nparts = broker._num_partitions(topic)
        parts = resolve_partitions(nparts, partitions)
        stored = broker.get_offsets(group, topic) if group else {}
        if stored:
            # a stored offset older than retention clamps forward to the
            # earliest retained record (Kafka earliest-reset semantics)
            earliest = broker.earliest_offsets(topic)
            self._pos = {
                i: max(stored.get(i, 0), earliest.get(i, 0)) for i in parts
            }
        elif from_beginning:
            earliest = broker.earliest_offsets(topic)
            self._pos = {i: earliest.get(i, 0) for i in parts}
        else:
            latest = broker.latest_offsets(topic)
            self._pos = {i: latest.get(i, 0) for i in parts}
        # (segment base, byte position of record self._pos[i]) per
        # partition; established lazily (one O(n) line skip), then advanced
        # incrementally so each poll seeks instead of re-reading. Survives
        # segment rolls: a rolled active keeps its base in the archived
        # name, so the cached byte stays valid for the same content.
        self._cursor: dict[int, tuple[int, int]] = {}
        from oryx_tpu.common import ledger

        ledger.register("consumer", self, live=lambda c: not c.closed())

    def _read_partition_raw(self, i: int, budget: int, out: list[bytes]) -> None:
        """Append up to `budget` complete raw record lines (bytes, newline
        stripped) from partition i, walking the segment chain from
        self._pos[i]. Decoding is the caller's job — the hot consume path
        (poll_block) decodes whole batches columnar instead."""
        broker = self._broker
        while budget > 0:
            segs = broker._segments(self._topic, i)
            pos = self._pos[i]
            if pos < segs[0][0]:
                pos = self._pos[i] = segs[0][0]  # aged past: clamp forward
                self._cursor.pop(i, None)
            idx = len(segs) - 1
            while idx > 0 and segs[idx][0] > pos:
                idx -= 1
            seg_base, seg_path = segs[idx]
            is_active = idx == len(segs) - 1
            if not seg_path.exists():
                return
            got = 0
            with open(seg_path, "rb") as f:
                cur = self._cursor.get(i)
                if cur is not None and cur[0] == seg_base:
                    f.seek(cur[1])
                else:
                    for _ in range(pos - seg_base):
                        if not f.readline():
                            break
                # chunked reads + one split, with the byte cursor tracked
                # arithmetically — per-record readline()+tell() was ~20% of
                # the drain path. Over-read past `budget` is fine: the
                # cursor only advances over taken lines and every call
                # seeks to it first.
                byte0 = f.tell()
                consumed = 0
                while budget > 0:
                    chunk = f.read(_READ_CHUNK_BYTES)
                    if not chunk:
                        break
                    nl = chunk.rfind(b"\n")
                    # a record larger than the chunk has no newline yet:
                    # keep growing until one appears or the data truly
                    # ends (then it's a partial in-flight append)
                    while nl == -1 and len(chunk) % _READ_CHUNK_BYTES == 0:
                        more = f.read(_READ_CHUNK_BYTES)
                        if not more:
                            break
                        chunk += more
                        nl = chunk.rfind(b"\n")
                    if nl == -1:
                        break  # partial tail of an in-flight append; retry
                    lines = chunk[: nl + 1].split(b"\n")
                    lines.pop()  # trailing empty piece after the last \n
                    if len(lines) > budget:
                        lines = lines[:budget]
                        taken = sum(map(len, lines)) + len(lines)
                    else:
                        taken = nl + 1
                    got += len(lines)
                    consumed += taken
                    if b"" in lines:
                        lines = [ln for ln in lines if ln]
                    out.extend(lines)
                    budget -= len(lines)
                    if taken < len(chunk):
                        f.seek(byte0 + consumed)  # rewind the over-read
                if got:
                    self._cursor[i] = (seg_base, byte0 + consumed)
            self._pos[i] += got
            if is_active or got == 0:
                # active exhausted, or an archived segment yielded nothing
                # (roll race: re-resolve next poll instead of spinning)
                return
            # archived segment exhausted: fall through to the next one

    @staticmethod
    def _decode_line(line: bytes) -> KeyMessage | None:
        return blockcodec.decode_line(line)

    def _read_partition(self, i: int, budget: int, out: list[KeyMessage]) -> None:
        """Append up to `budget` records from partition i."""
        while budget > 0:
            raw: list[bytes] = []
            self._read_partition_raw(i, budget, raw)
            if not raw:
                return
            exhausted = len(raw) < budget  # raw gave all it currently has
            for line in raw:
                rec = self._decode_line(line)
                if rec is not None:
                    out.append(rec)
                    budget -= 1
            if exhausted:
                return

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        deadline = time.monotonic() + timeout
        while True:
            out: list[KeyMessage] = []
            for i in sorted(self._pos):
                self._read_partition(i, max_records - len(out), out)
                if len(out) >= max_records:
                    return out
            if out or self._closed or time.monotonic() >= deadline:
                return out
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))

    def poll_block(self, max_records: int = 1000, timeout: float = 0.1):
        """Columnar poll: raw record lines are sliced with bytes ops — no
        per-record decoding or KeyMessage construction. The tab wire
        format means even JSON payloads ("UP" deltas, MODEL PMML) carry
        no escapes, so effectively every record takes the fast path. This
        is what lets one consumer thread keep up with 100K+ events/s."""
        from oryx_tpu.common.records import RecordBlock

        deadline = time.monotonic() + timeout
        while True:
            raw: list[bytes] = []
            for i in sorted(self._pos):
                self._read_partition_raw(i, max_records - len(raw), raw)
                if len(raw) >= max_records:
                    break
            if raw:
                return self._lines_to_block(raw, RecordBlock)
            if self._closed or time.monotonic() >= deadline:
                return None
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))

    def _lines_to_block(self, raw: list[bytes], RecordBlock):
        return _lines_to_block_standalone(raw, RecordBlock)

    def positions(self) -> dict[int, int]:
        return dict(self._pos)

    def seek(self, positions: dict[int, int]) -> None:
        for i, off in positions.items():
            i = int(i)
            self._pos[i] = int(off)
            # drop the cached byte cursor; the next read re-establishes it
            self._cursor.pop(i, None)

    def commit(self) -> None:
        if self._group:
            self._broker.set_offsets(self._group, self._topic, self._pos)

    def close(self) -> None:
        self._closed = True

    def closed(self) -> bool:
        return self._closed


# transported-batch codec aliases (implementation: bus/blockcodec.py,
# shared with netbus and shmbus so the wire formats cannot drift)
_lines_to_block_standalone = blockcodec.lines_to_block
_NEEDS_ESC_B = blockcodec._NEEDS_ESC_B
_enc_field_b = blockcodec.enc_field_b
_encode_wire_lines = blockcodec.encode_wire_lines
_decode_wire_lines = blockcodec.decode_wire_lines
_encode_block_lines = blockcodec.encode_block_lines
