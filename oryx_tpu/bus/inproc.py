"""In-process broker: the embedded test/single-process bus.

Analogue of the reference's embedded LocalKafkaBroker + LocalZKServer test
assets (framework/kafka-util/src/test, SURVEY.md §2.2) promoted to a
first-class implementation: topics with partitioned append-only in-memory
logs, blocking poll via condition variables, and per-group offset storage.
"""

from __future__ import annotations

import threading

from oryx_tpu.bus.core import (
    Broker,
    KeyMessage,
    TopicConsumer,
    TopicProducer,
    partition_for,
    resolve_partitions,
)


class _Topic:
    def __init__(self, name: str, partitions: int) -> None:
        self.name = name
        self.partitions: list[list[KeyMessage]] = [[] for _ in range(partitions)]


class InProcessBroker(Broker):
    _registry: dict[str, "InProcessBroker"] = {}
    _registry_lock = threading.Lock()

    @classmethod
    def named(cls, name: str) -> "InProcessBroker":
        with cls._registry_lock:
            if name not in cls._registry:
                cls._registry[name] = InProcessBroker(name)
            return cls._registry[name]

    @classmethod
    def reset_all(cls) -> None:
        """Drop all named brokers (test isolation)."""
        with cls._registry_lock:
            cls._registry.clear()

    def __init__(self, name: str) -> None:
        self.name = name
        self._cond = threading.Condition()
        self._topics: dict[str, _Topic] = {}
        self._offsets: dict[tuple[str, str], dict[int, int]] = {}

    # -- admin --------------------------------------------------------------

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        with self._cond:
            if topic not in self._topics:
                self._topics[topic] = _Topic(topic, max(1, partitions))

    def topic_exists(self, topic: str) -> bool:
        with self._cond:
            return topic in self._topics

    def delete_topic(self, topic: str) -> None:
        with self._cond:
            self._topics.pop(topic, None)
            for key in [k for k in self._offsets if k[1] == topic]:
                del self._offsets[key]
            self._cond.notify_all()

    # -- offsets ------------------------------------------------------------

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        with self._cond:
            return dict(self._offsets.get((group, topic), {}))

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        with self._cond:
            self._offsets.setdefault((group, topic), {}).update(offsets)

    def latest_offsets(self, topic: str) -> dict[int, int]:
        with self._cond:
            t = self._topics.get(topic)
            if t is None:
                return {}
            return {i: len(log) for i, log in enumerate(t.partitions)}

    # -- produce/consume ----------------------------------------------------

    def _get_or_create(self, topic: str) -> _Topic:
        """Caller must hold self._cond."""
        t = self._topics.get(topic)
        if t is None:
            t = _Topic(topic, 1)
            self._topics[topic] = t
        return t

    def _append(self, topic: str, key: str | None, message: str) -> None:
        with self._cond:
            t = self._get_or_create(topic)
            p = partition_for(key, len(t.partitions))
            t.partitions[p].append(KeyMessage(key, message))
            self._cond.notify_all()

    def _append_many(self, topic: str, records) -> int:
        """Batch append under one lock acquisition + one wakeup."""
        with self._cond:
            t = self._get_or_create(topic)
            nparts = len(t.partitions)
            n = 0
            for key, message in records:
                t.partitions[partition_for(key, nparts)].append(KeyMessage(key, message))
                n += 1
            self._cond.notify_all()
            return n

    def producer(self, topic: str) -> TopicProducer:
        return _InProcProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        return _InProcConsumer(self, topic, group, from_beginning, partitions)


class _InProcProducer(TopicProducer):
    def __init__(self, broker: InProcessBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic

    @property
    def update_broker(self) -> str:
        return f"inproc://{self._broker.name}"

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        self._broker._append(self._topic, key, message)

    def send_many(self, records) -> int:
        return self._broker._append_many(self._topic, records)

    def close(self) -> None:
        pass


class _InProcConsumer(TopicConsumer):
    def __init__(
        self, broker: InProcessBroker, topic: str, group: str | None,
        from_beginning: bool, partitions: list[int] | None = None,
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._group = group
        self._closed = False
        # None = dynamic assignment: follow the topic as it grows partitions
        self._assigned = partitions is not None
        with broker._cond:
            t = broker._topics.get(topic)
            nparts = len(t.partitions) if t else 1
            parts = resolve_partitions(nparts, partitions)
            stored = broker._offsets.get((group, topic)) if group else None
            if stored:
                self._pos = {i: stored.get(i, 0) for i in parts}
            elif from_beginning:
                self._pos = {i: 0 for i in parts}
            else:
                self._pos = {i: (len(t.partitions[i]) if t else 0) for i in parts}
        from oryx_tpu.common import ledger

        ledger.register("consumer", self, live=lambda c: not c.closed())

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        out: list[KeyMessage] = []
        with self._broker._cond:
            deadline = None
            while True:
                if self._closed:
                    return out
                t = self._broker._topics.get(self._topic)
                if t is not None:
                    if not self._assigned:
                        # topic may have grown partitions since construction
                        for i in range(len(t.partitions)):
                            self._pos.setdefault(i, 0)
                    for i, log in enumerate(t.partitions):
                        if i not in self._pos:
                            continue
                        start = self._pos[i]
                        take = log[start : start + (max_records - len(out))]
                        if take:
                            out.extend(take)
                            self._pos[i] = start + len(take)
                        if len(out) >= max_records:
                            return out
                if out:
                    return out
                import time

                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return out
                self._broker._cond.wait(remaining)

    def positions(self) -> dict[int, int]:
        return dict(self._pos)

    def seek(self, positions: dict[int, int]) -> None:
        with self._broker._cond:
            for i, off in positions.items():
                self._pos[int(i)] = int(off)

    def commit(self) -> None:
        if self._group:
            self._broker.set_offsets(self._group, self._topic, self._pos)

    def close(self) -> None:
        with self._broker._cond:
            self._closed = True
            self._broker._cond.notify_all()

    def closed(self) -> bool:
        return self._closed
